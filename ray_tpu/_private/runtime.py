"""Driver-side runtime: the core-worker + head-node composition.

This process plays three reference roles at once (single-node topology):
- the driver's core worker (reference src/ray/core_worker/core_worker.cc:
  SubmitTask:2166, CreateActor:2243, Put:1246, Get:1551),
- the GCS head (tables live in ``Controller``),
- the raylet (dispatch lives in ``Scheduler``).

Multi-process reality is preserved where it matters — user tasks and actors
always run in separate worker processes wired over the socket protocol, and
bulk data rides shared memory — so the concurrency/failure semantics match
the reference even though control-plane hops are function calls.
"""
from __future__ import annotations

import glob
import logging
import os
import socket
import threading
import time
from typing import Any, Optional

log = logging.getLogger(__name__)

from ray_tpu._private import context as _context
from ray_tpu._private import metrics_plane as _mp
from ray_tpu._private import protocol
from ray_tpu._private import tracing_plane as _tp
from ray_tpu._private.controller import (ALIVE, DEAD, PENDING, RESTARTING,
                                         Controller)
from ray_tpu._private.object_store import LocalStore, StoredObject, deserialize
from ray_tpu._private.refs import ObjectRef
from ray_tpu._private.scheduler import Scheduler
from ray_tpu._private.specs import (ActorSpec, ActorTaskSpec, TaskSpec,
                                    bump_attempt)
from ray_tpu.exceptions import (ActorDiedError, ActorError, GetTimeoutError,
                                TaskCancelledError, TaskError,
                                WorkerDiedError)


def detect_num_tpu_chips() -> int:
    """TPU chip detection, reference python/ray/_private/accelerators/tpu.py:98-117
    (probes /dev/accel* then /dev/vfio), with an env override."""
    env = os.environ.get("RAY_TPU_CHIPS")
    if env is not None:
        return int(env)
    accel = glob.glob("/dev/accel*")
    if accel:
        return len(accel)
    vfio = glob.glob("/dev/vfio/[0-9]*")
    if vfio:
        return len(vfio)
    return 0


def _summarize_by_state(rows: list) -> dict:
    out: dict[str, int] = {}
    for r in rows:
        out[r.get("state", "?")] = out.get(r.get("state", "?"), 0) + 1
    return out


class _ActorState:
    """Driver-side actor-task routing state (actor_task_submitter.cc parity:
    per-actor ordered queue while the actor is pending/restarting, inflight
    tracking for failure handling)."""

    def __init__(self):
        self.queued: list[ActorTaskSpec] = []
        self.inflight: dict[str, ActorTaskSpec] = {}
        # r18 direct call plane: specs REMOTE callers mirrored via
        # ACTOR_INFLIGHT_DELTA while their direct calls are in flight
        # (the driver's own direct calls sit in `inflight` like any
        # other — its mirror is in-process). Death/restart recovery
        # claims both tables.
        self.direct_inflight: dict[str, ActorTaskSpec] = {}
        # claim epoch (r18 satellite): bumped by every recovery /
        # unplaceable sweep that claims the inflight table. A send
        # that fails AFTER such a sweep must NOT pop/requeue — the
        # sweep already owns the spec (it may have been requeued and
        # re-sent), and popping here silently dropped the call.
        self.epoch = 0
        # sticky head-routed fallback (r18): set on any direct-path
        # failure; cleared once every book is empty (all prior calls
        # terminal), so a fresh direct call can never overtake an
        # older fallback call still queued at the head.
        self.fallback = False
        # per-actor submission-order stamp: every requeue path inserts
        # by it, so a recovery sweep claiming in-flight calls can
        # never prepend them AHEAD of earlier calls a direct-path NACK
        # already requeued (mixed-source queues broke the old
        # "inflight always precedes queued" prepend invariant).
        self.next_order = 0
        self.lock = threading.Lock()


class Runtime(_context.BaseContext):
    is_driver = True

    def __init__(self, num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 resources: Optional[dict] = None,
                 max_workers: Optional[int] = None,
                 namespace: str = "default",
                 bind_host: Optional[str] = None,
                 port: Optional[int] = None,
                 labels: Optional[dict] = None):
        self.namespace = namespace
        self._started_at = time.time()
        self._head_labels = {k: str(v) for k, v in (labels or {}).items()}
        self.controller = Controller()
        # capacity via RAY_TPU_OBJECT_STORE_MEMORY (bytes); spill policy
        # must never touch objects pinned by in-flight tasks.
        self.store = LocalStore(pinned_fn=self.controller.pinned_ids)
        from concurrent.futures import ThreadPoolExecutor
        from ray_tpu._private.object_transfer import PullServer
        from ray_tpu._private.waiters import WaiterRegistry
        # Blocked worker gets/waits park here (no thread each); the
        # store's seal hook resolves them. "Present" means a local copy
        # OR a known remote location (multi-host). Spill restores and
        # remote pulls run on a small pool so disk reads / network
        # fetches never block connection reader threads.
        self.waiters = WaiterRegistry(
            lambda oid: (self.store.contains(oid)
                         or self.controller.has_location(oid)))
        self.store.on_seal = self.waiters.notify
        self._restore_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="rtpu-restore")
        self._pull_server = PullServer(self.store,
                                       executor=self._restore_pool)
        self._shutdown = False
        self._actor_states: dict[str, _ActorState] = {}
        self._actor_lock = threading.Lock()
        # Head HA (r15): persistence coordinator (WAL + snapshots) and
        # the per-node reconcile state deferred until each rejoining
        # agent's outage backlog has drained. Set early: the cluster
        # consults _ha when it builds RemoteNodeHandles.
        self._ha = None
        self._pending_reconcile: dict[str, tuple] = {}
        # r16 decref-delta accounting (head side): applied frames/
        # entries + replayed frames dropped by the seq watermark
        self._decref_delta_stats = {"frames": 0, "entries": 0,
                                    "deduped_frames": 0}
        # r18 direct actor call plane: head-side counters (driver-as-
        # caller and head-as-host in one dict), the pending table for
        # head-hosted actors' direct calls, the driver's dialed
        # endpoint connections, and the count of head-routed actor
        # frames (the load-independent "head frames per actor call"
        # signal bench_core reads).
        from ray_tpu._private import direct_actor as _da
        self._direct_stats = _da.new_stats()
        self._direct_stats.update(head_routed_sends=0,
                                  head_actor_dones=0, delta_frames=0,
                                  delta_adds=0, delta_dones=0,
                                  send_race_kept=0)
        self._direct_pending = _da.PendingDirectCalls()
        self._direct_conns: dict[tuple, protocol.Connection] = {}
        # per-actor endpoint the driver is currently streaming to:
        # upgrades (agent-hosted -> worker socket once its port rides
        # a heartbeat) only happen at quiet moments — two inbound
        # channels to one worker could reorder a handle's calls
        self._direct_actor_addr: dict[str, tuple] = {}
        # head-as-host completions the worker's TASK_DONE answered
        # BEFORE the caller's coalesced mirror add arrived (the 25 ms
        # delta window vs ~1 ms execution): late adds for these ids
        # must not pin args or park phantom in-flight entries, and
        # their dones must not re-seal/re-record a terminal call
        import collections as _collections
        self._direct_done_ring: "_collections.OrderedDict" = \
            _collections.OrderedDict()
        self._direct_lock = threading.Lock()
        # r17 membership fencing: frames dropped because their
        # connection's incarnation trails the node table (zombie after
        # a partition/stall) + terminal entries dropped because their
        # attempt counter trails the live spec (first-terminal-wins).
        self._fence_stats = {"fenced_frames": 0, "fence_notices": 0,
                             "stale_attempt_drops": 0}
        # reader threads are per-connection with RAY_TPU_EPOLL=0, so
        # these read-modify-writes need the same discipline as the
        # cluster's liveness counters
        self._fence_lock = threading.Lock()
        # serializes snapshot publication: the periodic loop, manual
        # snapshot_now calls, and WAL compaction share one tmp/.prev
        # rotation chain — concurrent writers would rename each
        # other's files out from underneath
        self._snapshot_lock = threading.Lock()

        if num_cpus is None:
            num_cpus = float(max(os.cpu_count() or 1, 4))
        if num_tpus is None:
            num_tpus = float(detect_num_tpu_chips())
        node_res = {"CPU": float(num_cpus)}
        if num_tpus:
            node_res["TPU"] = float(num_tpus)
        from ray_tpu._private.config import CONFIG as _CFG
        node_res["memory"] = float(
            os.environ.get("RAY_TPU_NODE_MEMORY")    # legacy name
            or _CFG.node_memory_bytes)
        if resources:
            node_res.update({k: float(v) for k, v in resources.items()})

        from ray_tpu._private.config import CONFIG as _CFG2
        bind = bind_host or _CFG2.bind_host
        # r10: one epoll/select event loop reads every accepted
        # connection (workers, agents, clients) instead of a reader
        # thread each; None (RAY_TPU_EPOLL=0) restores threads.
        self._poller = protocol.make_poller()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((bind, int(port or _CFG2.port)))
        self._listener.listen(128)
        self.address = self._listener.getsockname()

        from ray_tpu._private.cluster import ClusterTaskManager
        self.cluster = ClusterTaskManager(self)
        # The accept loop starts only AFTER head persistence has
        # rehydrated (end of __init__): an agent re-registering against
        # half-restored tables would miss its parked mirror and its
        # live-actor re-attachment, and a registration processed before
        # the WAL activates would never be logged — the reference GCS
        # likewise serves no RPCs until gcs_init_data has loaded.
        # connect() still succeeds meanwhile (the listener is bound,
        # backlog holds the handshake).
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="ray-tpu-accept", daemon=True)
        head = self.cluster.add_node(node_res, max_workers=max_workers,
                                     is_head=True,
                                     labels=self._head_labels)
        self.head_node_id = head.node_id
        _tp.set_role("driver", self.head_node_id)
        # Object plane v2: the head's own pull manager (deduped,
        # bounded, multi-source fetches from agent holders) and the
        # tree-broadcast coordinator, driven by directory add events.
        from ray_tpu._private.broadcast import BroadcastCoordinator
        from ray_tpu._private.pull_manager import PullManager
        self._pull_mgr = PullManager(
            self.store, sources_fn=self._head_pull_sources,
            on_source_failed=lambda oid, nid:
                self.controller.remove_location(oid, nid),
            # r17: suspect holders go to the end of the rotation
            deprioritize_fn=self.cluster.is_suspect,
            # cut-through (r12): the head mid-pull serves landed chunk
            # ranges too — register/retract it as a partial holder so
            # a broadcast rooted elsewhere can relay through it
            on_partial=lambda oid, nbytes:
                self.controller.add_location(oid, self.head_node_id,
                                             nbytes, partial=True),
            on_partial_failed=lambda oid:
                self.controller.remove_location(oid, self.head_node_id))
        self.bcast = BroadcastCoordinator(self)
        self.controller.directory.add_listener(self.bcast.on_location)
        # Cluster metrics plane (r11): head-side scrape fan-out/merge
        # + retention ring; the head's own sampled gauges (per-node
        # lease ledgers, pull-manager occupancy) refresh at scrape
        # time through the sampler hook.
        self.metrics = _mp.ClusterCollector(self)
        _mp.set_sampler("head", self._sample_metrics)
        self._init_head_persistence()
        self._accept_thread.start()

    # ================= head fault tolerance =================
    def _init_head_persistence(self) -> None:
        """Reference GCS persistence (gcs_server_main.cc:26-33 storage
        backend + gcs_init_data.cc rehydration): when
        RAY_TPU_HEAD_SNAPSHOT_PATH is set, restore controller tables
        from disk, then keep them durable. With the r15 WAL
        (RAY_TPU_HEAD_WAL, default on) every state-mutating event is
        group-commit logged and snapshots are taken by compaction, so
        a restarted head rehydrates to the exact pre-crash frontier;
        RAY_TPU_HEAD_WAL=0 reverts to the 1 Hz snapshot-only mode."""
        from ray_tpu._private.config import CONFIG as _CFG
        self._snapshot_path = _CFG.head_snapshot_path or None
        if self._snapshot_path is None:
            return
        if _CFG.head_wal:
            from ray_tpu._private.head_ha import HeadPersistence
            self._ha = HeadPersistence(
                self._snapshot_path,
                _CFG.head_wal_path or (self._snapshot_path + ".wal"),
                fsync_ms=_CFG.head_wal_fsync_ms,
                compact_bytes=_CFG.head_wal_compact_bytes,
                compact_interval_s=_CFG.head_wal_compact_interval_s)
            try:
                self._rehydrate(self._snapshot_path)
            except Exception:
                log.exception("head state restore failed; "
                              "starting with empty tables")
            # live logging starts only after replay: the controller
            # methods replay drives must not re-log their own input
            self._ha.activate()
            self.controller.ha = self._ha
            try:
                # immediate post-recovery snapshot: everything restored
                # (and anything registered before activation) is durable
                # from the first second, and the WAL restarts from a
                # fresh frontier instead of re-replaying the old tail
                # on the next crash
                self.snapshot_now()
            except Exception:
                log.exception("post-recovery snapshot failed")
        elif os.path.exists(self._snapshot_path):
            try:
                self._rehydrate(self._snapshot_path)
            except Exception:
                log.exception("head snapshot restore failed; "
                              "starting with empty tables")
        self._snapshot_thread = threading.Thread(
            target=self._snapshot_loop, name="rtpu-head-snapshot",
            daemon=True)
        self._snapshot_thread.start()

    def _snapshot_loop(self) -> None:
        from ray_tpu._private.config import CONFIG as _CFG
        if self._ha is not None:
            # WAL mode: snapshots happen at compaction (size/age
            # triggered), not on a timer — the WAL carries everything
            # in between
            while not self._shutdown:
                time.sleep(1.0)
                try:
                    self._ha.maybe_compact(self.snapshot_now)
                except Exception:
                    log.exception("head WAL compaction failed")
            return
        period = max(0.1, _CFG.head_snapshot_period_s)
        while not self._shutdown:
            time.sleep(period)
            try:
                self.snapshot_now()
            except Exception:
                log.exception("head snapshot failed")

    def _mirror_tables(self) -> dict:
        """Snapshot extra: every remote node's spec mirror + lease
        ledger (live proxies), merged with mirrors still parked for
        nodes that have not rejoined yet — a compaction during the
        rejoin grace window must not drop their work."""
        mirrors: dict = {}
        for n in self.cluster.alive_nodes():
            h = n.scheduler
            if not hasattr(h, "_work") or not hasattr(h, "_leased"):
                continue                     # in-process local node
            with h._lock:
                mirrors[n.node_id] = {"work": dict(h._work),
                                      "leased": list(h._leased)}
        if self._ha is not None:
            for nid, m in self._ha.pending_mirrors().items():
                mirrors.setdefault(nid, m)
        return mirrors

    def snapshot_now(self) -> None:
        """Atomic, torn-write-proof controller snapshot: the blob is
        version+checksum framed, flushed+fsynced BEFORE the rename
        (a crash after a bare rename could publish a partially-written
        file), and the previous snapshot is kept as ``.prev`` so a
        corrupt blob falls back instead of zeroing the tables."""
        if self._snapshot_path is None or self._shutdown:
            return
        from ray_tpu._private import head_ha as _hha
        # mirrors are captured AFTER the frontier (extra_fn contract):
        # a task routed in the gap is either in the capture or in a
        # replayed madd record, never in neither
        blob = self.controller.snapshot_state(
            extra_fn=lambda: {"_node_mirrors": self._mirror_tables()})
        with self._snapshot_lock:
            if self._ha is not None:
                self._ha.write_snapshot(blob)
            else:
                _hha.write_snapshot_file(self._snapshot_path, blob)

    def _load_snapshot_blob(self, path: str):
        """Newest intact snapshot blob (current file, else ``.prev``),
        or None when neither verifies."""
        from ray_tpu._private import head_ha as _hha
        if self._ha is not None:
            return self._ha.load_snapshot()
        return _hha.load_snapshot_file(path)[0]

    def _rehydrate(self, path: str) -> None:
        """Restore controller tables (snapshot + WAL tail when the WAL
        is on), park each agent's rehydrated spec mirror until it
        rejoins, then reconcile: agents recorded alive get a rejoin
        grace window; actors whose node died with the old head
        (head-local workers, unknown nodes) restart through the normal
        recovery machinery; live tasks mirrored to NO node (they were
        queued or running on the old head's own workers, which died
        with it) re-place immediately."""
        from ray_tpu._private.config import CONFIG as _CFG
        from ray_tpu._private.specs import TaskSpec as _TaskSpec
        blob = self._load_snapshot_blob(path)
        state: dict = {}
        frontier = 0
        if blob is not None:
            state = self.controller.restore_state(blob)
            frontier = int(state.get("_wal_seq", 0))
        snap_mirrors = state.get("_node_mirrors") or {}
        mirrors: dict = {nid: dict(m.get("work", {}))
                         for nid, m in snap_mirrors.items()}
        leases: dict = {nid: set(m.get("leased", ()))
                        for nid, m in snap_mirrors.items()}
        if self._ha is not None:
            tail = self._ha.wal_tail()
            # seed the sequence counter past EVERYTHING recovered: new
            # records appended to the same segment must sort after the
            # old process's records and above the snapshot frontier, or
            # a second crash replays them wrong (skipped or clobbered
            # by stale state)
            self._ha.wal.advance_seq(
                max([frontier] + [r[0] for r in tail]))
            if blob is None and not tail:
                return                       # genuinely fresh start
            self._ha.replay(self.controller, tail, frontier,
                            mirrors, leases)
        elif blob is None:
            return
        # Resolve mirror entries: WAL-replayed adds carry only the key
        # (the spec rides the task-submit record); entries whose task
        # is no longer live completed before the crash — drop them so
        # a replayed completion dedups and a reconcile cannot
        # double-place finished work.
        live_ids = set(self.controller.live_task_ids())
        mirrored_live: set[str] = set()
        for nid in list(mirrors):
            resolved: dict = {}
            for key, entry in mirrors[nid].items():
                if isinstance(entry, tuple):
                    spec, dispatched = entry
                else:                        # WAL "madd": key only
                    spec, dispatched = (
                        self.controller.live_task(key), False)
                if spec is None or not isinstance(spec, _TaskSpec):
                    continue                 # done, or an actor entry
                if spec.task_id not in live_ids:
                    continue
                resolved[key] = (spec, bool(dispatched))
                mirrored_live.add(spec.task_id)
            if resolved and self._ha is not None:
                self._ha.park_node(nid, resolved,
                                   set(leases.get(nid, ()))
                                   & set(resolved))
        if self._ha is not None:
            self._ha.restored_task_ids = set(mirrored_live)
            self._ha.recovered["live_tasks"] = len(live_ids)
        rejoining: set[str] = set()
        for n in self.controller.list_nodes():
            if n["is_head"] or not n["alive"]:
                continue
            rejoining.add(n["node_id"])
            self.cluster.expect_rejoin(n["node_id"],
                                       _CFG.node_rejoin_grace_s)
        self.cluster.restore_pgs(self.controller.list_pgs())
        for info in self.controller.list_actors():
            rec = self.controller.get_actor(info["actor_id"])
            if rec is None or rec.state == DEAD:
                continue
            if rec.node_id in rejoining:
                continue            # its worker may still be alive there
            # worker died with the old head: normal restart bookkeeping
            rec.worker_id = None
            self._recover_actor(rec.spec.actor_id)
        # Live tasks owned by the dead head's own node: nothing will
        # ever complete them — re-place now (no retry budget consumed:
        # the head's death is not the task's failure, r10 agent-death
        # resubmit semantics).
        resubmitted = 0
        for tid in live_ids:
            if tid in mirrored_live:
                continue                # an agent still owes this task
            spec = self.controller.live_task(tid)
            if spec is None:
                continue
            self.controller.record_task_event(
                tid, getattr(spec, "name", ""), "RESUBMITTED",
                error="head restart")
            try:
                bump_attempt(spec)
                self.cluster.submit(spec)
                resubmitted += 1
            except Exception:
                log.exception("head-restart resubmit of %s failed", tid)
        if self._ha is not None:
            self._ha.recovered["resubmitted"] = resubmitted
        log.info("head rehydrated from %s: %d actors, %d live tasks "
                 "(%d mirrored, %d resubmitted), %d nodes pending "
                 "rejoin", path, len(self.controller.list_actors()),
                 len(live_ids), len(mirrored_live), resubmitted,
                 len(rejoining))

    def _process_rejoin(self, rec, msg: dict) -> None:
        """An agent re-registered after a head restart (or reconnect):
        re-attach its live actors, re-learn its object copies, and
        hand its rehydrated spec mirror to the fresh proxy. The
        mirror RECONCILE (re-placing mirrored tasks absent from the
        agent's reported in-flight set) is deferred until the agent's
        ``rejoin_drained`` marker — its buffered completions must pop
        their mirror entries first, or a just-finished task would be
        re-placed and run twice."""
        proxy = rec.scheduler
        node_id = rec.node_id
        pend = (self._ha.take_pending_node(node_id)
                if self._ha is not None else None)
        if pend is not None:
            from ray_tpu._private.specs import TaskSpec as _TaskSpec
            task_work = {k: v for k, v in pend.work.items()
                         if isinstance(v[0], _TaskSpec)}
            proxy.adopt_mirror(task_work, pend.leased & set(task_work))
            known = msg.get("inflight_tasks")
            self._pending_reconcile[node_id] = (
                set(task_work), None if known is None else set(known))
        for oid, nbytes in msg.get("objects", ()):
            self.controller.add_location(oid, node_id, nbytes)
            self.waiters.notify(oid)
        reported = dict(msg.get("live_actors", {}))
        for actor_id, worker_id in reported.items():
            arec = self.controller.get_actor(actor_id)
            if arec is None or arec.state == DEAD:
                continue
            if arec.node_id != node_id:
                # already recovered elsewhere while this agent was away
                # (transient disconnect): the agent's copy is stale —
                # kill it, or two instances of one actor run forever
                proxy.kill_worker(worker_id)
                continue
            proxy.on_dispatched("actor:" + actor_id, worker_id,
                                actor_id=actor_id)
            proxy.track_live_actor(actor_id, arec.spec)
            self.controller.set_actor_state(actor_id, ALIVE,
                                            worker_id=worker_id,
                                            node_id=node_id)
            self._flush_actor_queue(actor_id)
        # actors the tables place on this node but the agent did NOT
        # report: their workers died while no head was watching —
        # recover them or their callers hang forever
        for actor_id in self.controller.actors_on_node(node_id):
            if actor_id not in reported:
                self._recover_actor(actor_id)

    def _reconcile_node_mirror(self, node_id: str) -> None:
        """Post-rejoin lease-ledger resync (r15): of the RESTORED
        mirror entries (and only those — work enqueued after the
        rejoin is untouched), entries the agent did not report as
        in-flight never reached it (lost lease batch / parked lease
        buffer) — re-place them exactly once; entries whose task is no
        longer live completed while the backlog drained — drop them.
        Runs after the agent's ``rejoin_drained`` marker so buffered
        completions have already popped their mirror entries."""
        st = self._pending_reconcile.pop(node_id, None)
        if st is None:
            return
        restored_keys, known = st
        if known is None:
            return          # agent predates the report: keep mirrored
        rec = self.cluster.get_node(node_id)
        if rec is None or not rec.alive:
            return          # node death recovery already ran
        proxy = rec.scheduler
        resubmit = []
        with proxy._lock:
            for key in restored_keys:
                entry = proxy._work.get(key)
                if entry is None or key in known:
                    continue
                if self.controller.live_task(key) is None:
                    # completed during the drain: off the books
                    proxy._work.pop(key, None)
                    proxy._leased.discard(key)
                    continue
                proxy._work.pop(key, None)
                proxy._leased.discard(key)
                resubmit.append(entry[0])
        for spec in resubmit:
            self.controller.record_task_event(
                spec.task_id, spec.name, "RESUBMITTED",
                error=f"lease lost in head restart ({node_id})")
            try:
                bump_attempt(spec)
                self.cluster.submit(spec)
            except Exception:
                log.exception("lease-resync resubmit failed")
        if resubmit and self._ha is not None:
            self._ha.recovered["resubmitted"] += len(resubmit)
        if resubmit:
            log.info("head HA: re-placed %d task(s) whose lease never "
                     "reached %s", len(resubmit), node_id)

    @property
    def scheduler(self):
        """The head node's scheduler (single-node compatibility view)."""
        rec = self.cluster.get_node(self.head_node_id)
        return rec.scheduler if rec else None

    def _scheduler_for_worker(self, worker_id: str):
        return self.cluster.scheduler_for_worker(worker_id)

    def _sched_for_conn(self, conn: protocol.Connection):
        """Scheduler owning this worker connection, cached on the
        connection at REGISTER. A worker never migrates between nodes
        and the cache dies with the connection on worker death, so the
        entry can't go stale — and the per-message probe it replaces
        took EVERY node's hot scheduler lock on every received
        TASK_DONE/GET/WAIT (r7 profile: a top head-CPU cost under
        drains, serializing reader threads against dispatch)."""
        sched = conn.meta.get("sched")
        if sched is None:
            wid = conn.meta.get("worker_id")
            if not wid:
                return None
            sched = self.cluster.scheduler_for_worker(wid)
            if sched is not None:
                conn.meta["sched"] = sched
        return sched

    # ================= connection plumbing =================
    def _accept_loop(self) -> None:
        while not self._shutdown:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            conn = protocol.Connection(sock, self._handle_msg,
                                       self._on_conn_closed, name="driver",
                                       server=True, poller=self._poller)
            conn.start()

    def _on_conn_closed(self, conn: protocol.Connection) -> None:
        # reap pull sessions this peer (agent or worker) had open
        self._pull_server.on_conn_closed(conn)
        if self._shutdown:
            return
        nid = conn.meta.get("node_id")
        if nid is not None:
            # an agent's control connection dropped: node death — unless
            # the agent already re-registered on a NEW connection (the
            # old conn's close callback can arrive after the rejoin)
            rec = self.cluster.get_node(nid)
            if rec is not None and getattr(rec.scheduler, "conn",
                                           None) is not conn:
                return
            self.cluster._on_node_death(nid, cause="agent disconnected")
            return
        wid = conn.meta.get("worker_id")
        if wid is None:
            return
        sched = self._scheduler_for_worker(wid)
        if sched is None:
            return
        tasks, actor_id = sched.on_worker_lost(wid)
        self._drop_direct_calls_of_caller(wid)
        for task in tasks:
            self._recover_task(task)
        if actor_id is not None:
            self._recover_actor(actor_id)

    # ================= failure recovery =================
    def _recover_task(self, spec: TaskSpec) -> None:
        """Reference parity: task retries on worker failure
        (task_manager.cc retry bookkeeping; max_retries option)."""
        if getattr(spec, "cancelled", False):
            self._store_error(spec.return_ids, TaskError(
                TaskCancelledError(spec.task_id), task_name=spec.name))
            self._unpin(spec.pinned_refs)
            self.controller.record_task_event(
                spec.task_id, spec.name, "CANCELLED")
            return
        if spec.retries_used < spec.max_retries:
            spec.retries_used += 1
            bump_attempt(spec)
            self.controller.record_task_event(
                spec.task_id, spec.name, "RETRYING")
            self.cluster.submit(spec)
        else:
            err = TaskError(WorkerDiedError(
                f"worker died running task {spec.name or spec.task_id}"),
                task_name=spec.name)
            self._store_error(spec.return_ids, err)
            self._unpin(spec.pinned_refs)
            self.controller.record_task_event(
                spec.task_id, spec.name, "FAILED", error="worker died")

    def _recover_actor(self, actor_id: str) -> None:
        """GcsActorManager restart-on-failure parity
        (gcs_actor_manager.h:89-91 max_restarts bookkeeping)."""
        rec = self.controller.get_actor(actor_id)
        if rec is None or rec.state == DEAD:
            return
        st = self._actor_state(actor_id)
        with st.lock:
            # claim epoch (r18): any in-flight send that fails after
            # this sweep must not repop/requeue — we own every spec
            st.epoch += 1
            inflight = (list(st.inflight.values())
                        + list(st.direct_inflight.values()))
            st.inflight.clear()
            st.direct_inflight.clear()
        can_restart = (rec.spec.max_restarts < 0
                       or rec.num_restarts < rec.spec.max_restarts)
        if can_restart:
            rec.num_restarts += 1
            self.controller.set_actor_state(actor_id, RESTARTING)
            retried = []
            for t in inflight:           # preserve submission order
                if t.retries_used < t.max_retries:
                    t.retries_used += 1
                    retried.append(t)
                else:
                    self._store_error(t.return_ids, TaskError(
                        ActorError(actor_id, "actor restarting; task lost"),
                        task_name=t.name))
            with st.lock:
                # merge by submission stamp (r18): the queue may
                # already hold EARLIER calls a direct-path NACK
                # requeued — a blind prepend of the claimed in-flight
                # set would put later calls ahead of them
                st.queued = sorted(
                    retried + st.queued,
                    key=lambda s: getattr(s, "_order", 0))
            self.cluster.submit(rec.spec)
        else:
            self.controller.set_actor_state(actor_id, DEAD,
                                            death_cause="worker died")
            with st.lock:
                dead_tasks = inflight + st.queued
                st.queued = []
            for t in dead_tasks:
                self._store_error(t.return_ids, TaskError(
                    ActorDiedError(actor_id, f"Actor {actor_id} is dead"),
                    task_name=t.name))

    def _store_error(self, return_ids: list[str], err: BaseException) -> None:
        from ray_tpu._private.object_store import reap_object_segments
        for oid in return_ids:
            # a killed worker may have sealed result buffers for these
            # ids without delivering TASK_DONE; reap them or they leak
            # until host reboot (shm persists past process death)
            reap_object_segments(oid)
            self.store.put(err, object_id=oid)

    def on_unplaceable(self, spec, reason: str) -> None:
        """Cluster callback: a spec can never be placed (e.g. hard node
        affinity to a dead node). Fail fast rather than hang."""
        from ray_tpu._private.specs import ActorSpec as _ActorSpec
        if isinstance(spec, _ActorSpec):
            self.controller.set_actor_state(spec.actor_id, DEAD,
                                            death_cause=reason)
            st = self._actor_state(spec.actor_id)
            with st.lock:
                st.epoch += 1
                dead = (st.queued + list(st.inflight.values())
                        + list(st.direct_inflight.values()))
                st.queued = []
                st.inflight.clear()
                st.direct_inflight.clear()
            for t in dead:
                self._store_error(t.return_ids, TaskError(
                    ActorDiedError(spec.actor_id, reason),
                    task_name=t.name))
            return
        self._store_error(spec.return_ids, TaskError(
            WorkerDiedError(f"task unplaceable: {reason}"),
            task_name=spec.name))
        self._unpin(spec.pinned_refs)
        self.controller.record_task_event(spec.task_id, spec.name,
                                          "FAILED", error=reason)

    def _unpin(self, object_ids: list[str]) -> None:
        for oid in object_ids:
            if self.controller.unpin(oid):
                self._delete_everywhere(oid)

    def _seal_contained(self, object_id: str, ids: list[str]) -> None:
        """Register nested-ref containment for a sealed object; inner
        refs released by a refresh (lineage reseal with fresh ids) go
        through the full deletion path."""
        for cid in self.controller.register_contained(object_id, ids):
            self.decref(cid)

    # ================= scheduler callbacks =================
    def on_task_dispatched(self, spec: TaskSpec, worker_id: str) -> None:
        self.controller.record_task_event(
            spec.task_id, spec.name, "RUNNING", worker_id=worker_id)

    def on_actor_dispatched(self, spec: ActorSpec, worker_id: str) -> None:
        sched = self._scheduler_for_worker(worker_id)
        self.controller.set_actor_state(
            spec.actor_id, PENDING, worker_id=worker_id,
            node_id=getattr(sched, "node_id", None))

    # ================= message handlers =================
    def _reply_off_reader(self, conn, msg, name, fn) -> None:
        """Run `fn` on its own thread and reply with its result: a
        state op that fans out and WAITS for replies (one of which may
        arrive on the requesting connection's reader — with the r10
        shared poller, on the one loop thread serving every
        connection) must never run on a connection reader thread."""
        def _run():
            try:
                conn.reply(msg, value=fn())
            except protocol.ConnectionClosed:
                pass
            except Exception as e:
                # the caller is BLOCKED on this reply: a swallowed
                # exception here means it hangs for its full request
                # timeout instead of seeing the failure
                try:
                    conn.reply(msg, value=None,
                               error=f"{type(e).__name__}: {e}")
                except protocol.ConnectionClosed:
                    pass
        threading.Thread(target=_run, name=name, daemon=True).start()

    # ---- incarnation fencing (r17) ----
    # State-bearing frame types an agent emits: every one of these is
    # admission-checked against the node incarnation table before it
    # can touch head state. Request/reply relays (SUBMIT/WAIT/KV) are
    # deliberately NOT fenced — their effects are idempotent or
    # re-issued by the re-placed winner, and swallowing their replies
    # would hang workers the fence reset is about to kill anyway.
    _FENCED_TYPES = frozenset((
        protocol.NODE_HEARTBEAT, protocol.NODE_EVENT,
        protocol.NODE_TASK_DONE, protocol.NODE_TASK_DONE_BATCH,
        protocol.NODE_DECREF_DELTA, protocol.OBJECT_ADDED,
        protocol.OBJECT_REMOVED, protocol.DECREF,
        protocol.DECREF_BATCH, protocol.ADDREF,
        # r18: a zombie agent's relayed direct-call mirror deltas
        # must not pin refs or park phantom in-flight entries
        protocol.ACTOR_INFLIGHT_DELTA))

    def _admit_node_frame(self, conn: protocol.Connection,
                          msg: dict) -> bool:
        """False = the frame came from a STALE incarnation of its node
        (declared dead while still alive): drop it — none of its
        completions, refcount releases, or location claims may land —
        and answer NODE_FENCED once per connection, telling the zombie
        to kill its workers, clear its ledgers, and re-register."""
        inc = conn.meta.get("incarnation")
        if inc is None:
            return True          # local worker conn / pre-r17 agent
        nid = conn.meta.get("node_id") or msg.get("node_id")
        cur = self.controller.node_incarnation(nid)
        if cur is None or inc == cur:
            return True
        with self._fence_lock:
            self._fence_stats["fenced_frames"] += 1
        if not conn.meta.get("fence_notified"):
            conn.meta["fence_notified"] = True
            with self._fence_lock:
                self._fence_stats["fence_notices"] += 1
            self.cluster.bump_liveness("fenced")
            self.controller.publish_node_event(
                nid, "FENCED",
                cause=f"stale incarnation {inc} < {cur}")
            log.warning("fencing node %s: frame from stale "
                        "incarnation %s (current %s)", nid, inc, cur)
            try:
                conn.send({"type": protocol.NODE_FENCED,
                           "node_id": nid, "incarnation": cur})
            except protocol.ConnectionClosed:
                pass
        return False

    def _handle_msg(self, conn: protocol.Connection, msg: dict) -> None:
        mtype = msg["type"]
        if (mtype in self._FENCED_TYPES
                and not self._admit_node_frame(conn, msg)):
            return
        if mtype == protocol.REGISTER:
            sched = self._scheduler_for_worker(msg["worker_id"])
            if sched is not None:
                sched.on_worker_registered(msg["worker_id"], conn)
                conn.meta["sched"] = sched     # hot-path cache
                # surfaced via workers_snapshot / list_workers
                conn.meta["wire_native"] = bool(
                    msg.get("wire_native", False))
                # r18 worker-direct serving port (None: no listener)
                conn.meta["direct_port"] = msg.get("direct_port")
            else:
                conn.close()              # worker from a dead/old node
        elif mtype == protocol.TASK_DONE:
            self._on_task_done(conn, msg)
        elif mtype == protocol.GET_OBJECT:
            self._on_get_object(conn, msg)
        elif mtype == protocol.WAIT:
            self._on_wait(conn, msg)
        elif mtype == protocol.PUT_OBJECT:
            stored: StoredObject = msg["stored"]
            self._seal_contained(stored.object_id, stored.contained_ids)
            self.store.put_stored(stored)
            self.controller.addref(stored.object_id)
            # producer-side backpressure hint: the WORKER throttles its
            # own puts (blocking this reader thread would stall the
            # completions that release pins)
            conn.reply(msg, ok=True,
                       pressure=self.store.over_capacity())
        elif mtype == protocol.SUBMIT:
            spec: TaskSpec = msg["spec"]
            if msg.get("func_bytes") is not None:
                self.controller.put_function(spec.func_id, msg["func_bytes"])
            self.submit_spec(spec)
            conn.reply(msg, ok=True)
        elif mtype == protocol.SUBMIT_ACTOR:
            aspec: ActorSpec = msg["spec"]
            if msg.get("class_bytes") is not None:
                self.controller.put_function(aspec.class_id,
                                             msg["class_bytes"])
            self.create_actor_from_spec(aspec)
            conn.reply(msg, ok=True)
        elif mtype == protocol.SUBMIT_ACTOR_TASK:
            self.submit_actor_task_spec(msg["actor_id"], msg["spec"],
                                        register_borrows=False)
            conn.reply(msg, ok=True)
        elif mtype == protocol.ACTOR_RESOLVE:
            conn.reply(msg,
                       **self._resolve_actor_endpoint(msg["actor_id"]))
        elif mtype == protocol.ACTOR_TASK_DIRECT:
            self._on_actor_task_direct(conn, msg)
        elif mtype == protocol.ACTOR_INFLIGHT_DELTA:
            self._on_actor_inflight_delta(conn, msg)
        elif mtype == protocol.KV_OP:
            conn.reply(msg, value=self._kv_dispatch(msg))
        elif mtype == protocol.DECREF:
            self.decref(msg["object_id"])
        elif mtype == protocol.DECREF_BATCH:
            self.decref_batch(msg["object_ids"])
        elif mtype == protocol.NODE_DECREF_DELTA:
            self._on_decref_delta(msg)
        elif mtype == protocol.ADDREF:
            self.controller.addref(msg["object_id"])
        elif mtype == protocol.STATE_OP:
            from ray_tpu._private.pubsub import StaleCursorError
            kwargs = msg.get("kwargs", {})
            try:
                if (msg["op"] == "pubsub_poll"
                        and kwargs.get("timeout")):
                    # long-poll parks in the publisher's waiter list and
                    # replies on publish/expiry — NEVER blocks this
                    # reader thread (it carries the subscriber's other
                    # traffic)
                    def _reply(msgs, cursor, conn=conn, msg=msg):
                        try:
                            conn.reply(msg, value=(msgs, cursor))
                        except protocol.ConnectionClosed:
                            pass
                    self.controller.pubsub.add_waiter(
                        kwargs["channel"], kwargs.get("cursor", 0),
                        float(kwargs["timeout"]), _reply)
                elif msg["op"] == "trace_dump":
                    # fans TRACE_DUMP out and WAITS for replies — one
                    # of which may arrive on THIS reader thread (the
                    # requesting worker's own dump): never collect on
                    # a connection reader (same rule as broadcast)
                    self._reply_off_reader(
                        conn, msg, "rtpu-trace-dump",
                        lambda kwargs=kwargs: self._trace_dump(
                            timeout=kwargs.get("timeout", 5.0)))
                elif msg["op"] in ("metrics_dump", "metrics_summary"):
                    # fans METRICS_DUMP out and WAITS for replies —
                    # one may arrive on THIS reader thread (same rule
                    # as trace_dump: never collect on a conn reader)
                    self._reply_off_reader(
                        conn, msg, "rtpu-metrics-dump",
                        lambda op=msg["op"], kwargs=kwargs:
                            self.state_op(op, **kwargs))
                elif msg["op"] == "cancel_task":
                    # issues blocking NODE_CANCEL_PENDING /
                    # NODE_FIND_TASK RPCs to agents whose replies
                    # arrive on THIS reader (with the r10 shared
                    # poller: on the one loop thread serving every
                    # connection) — same rule as trace_dump/broadcast:
                    # never collect on a connection reader
                    self._reply_off_reader(
                        conn, msg, "rtpu-cancel",
                        lambda kwargs=kwargs: self.state_op(
                            "cancel_task", **kwargs))
                elif msg["op"] == "broadcast_object":
                    # blocks until the whole tree completes — never on
                    # a connection reader thread
                    def _bc(conn=conn, msg=msg, kwargs=kwargs):
                        try:
                            conn.reply(msg, value=self.state_op(
                                "broadcast_object", **kwargs))
                        except protocol.ConnectionClosed:
                            pass
                        except Exception as e:
                            # api.broadcast re-raises from this shape,
                            # so remote callers see the same exception
                            # contract as the in-process driver path
                            try:
                                conn.reply(msg, value={
                                    "error": str(e),
                                    "error_type": type(e).__name__})
                            except protocol.ConnectionClosed:
                                pass
                    threading.Thread(target=_bc, name="rtpu-bcast",
                                     daemon=True).start()
                else:
                    conn.reply(msg, value=self.state_op(
                        msg["op"], **kwargs))
            except StaleCursorError as e:
                # one contract across transports: the client-side
                # state_op re-raises this as StaleCursorError(resync=N)
                conn.reply(msg, value=None, stale=True,
                           resync=getattr(e, "resync", 0),
                           detail=str(e))
        elif mtype == protocol.NODE_REGISTER:
            rec = self.cluster.add_remote_node(
                conn, msg["resources"], labels=msg.get("labels"),
                advertise_addr=tuple(msg["advertise_addr"]),
                node_id=msg.get("node_id"))
            conn.meta["node_id"] = rec.node_id
            # r17: this connection speaks for the incarnation minted
            # at THIS registration — frames from any older connection
            # of the same node are fenced from here on
            conn.meta["incarnation"] = rec.scheduler.incarnation
            conn.meta.pop("fence_notified", None)
            if msg.get("rejoin"):
                self._process_rejoin(rec, msg)
            else:
                # a FRESH agent process under this node id restarts
                # its decref-delta seq counter: drop the watermark or
                # its first frames would be deduped as replays
                self.controller.reset_decref_seq(rec.node_id)
            conn.reply(msg, node_id=rec.node_id,
                       incarnation=rec.scheduler.incarnation)
        elif mtype == protocol.NODE_HEARTBEAT:
            nid = msg["node_id"]
            self.cluster.heartbeat(nid)
            rec = self.cluster.get_node(nid)
            if rec is not None:
                rec.scheduler.on_heartbeat(msg)
            if "host_stats" in msg:
                self.controller.update_host_stats(nid, msg["host_stats"])
        elif mtype == protocol.NODE_EVENT:
            self._on_node_event(conn, msg)
        elif mtype == protocol.NODE_TASK_DONE:
            self._on_node_task_done(conn, msg)
        elif mtype == protocol.NODE_TASK_DONE_BATCH:
            self._on_node_task_done_batch(conn, msg)
        elif mtype == protocol.OBJECT_LOOKUP:
            self._on_object_lookup(conn, msg)
        elif mtype == protocol.LOCATE_OBJECT:
            self._on_locate_object(conn, msg)
        elif mtype == protocol.OBJECT_ADDED:
            self._on_object_added(msg)
        elif mtype == protocol.OBJECT_REMOVED:
            self.controller.remove_location(msg["object_id"],
                                            msg.get("node_id"))
        elif mtype == protocol.PULL_OBJECT:
            self._pull_server.handle_pull(conn, msg)
        elif mtype == protocol.PULL_CHUNK:
            self._pull_server.handle_chunk(conn, msg)
        elif mtype == protocol.PING:
            conn.reply(msg, ok=True)

    def _on_task_done(self, conn: protocol.Connection, msg: dict) -> None:
        t_tr = _tp.recv_t0(msg)
        try:
            self._on_task_done_inner(conn, msg)
        finally:
            self._record_done(msg, t_tr)

    def _on_task_done_inner(self, conn: protocol.Connection,
                            msg: dict) -> None:
        results: list[StoredObject] = msg.get("results", [])
        for stored in results:
            self._seal_contained(stored.object_id, stored.contained_ids)
            self.store.put_stored(stored)
            # Fire-and-forget results whose refs were already dropped must
            # be evicted here, or they accumulate until shutdown.
            if self.controller.unreferenced(stored.object_id):
                self._delete_everywhere(stored.object_id)
        if msg.get("direct_located"):
            # r18 worker-direct large results from a HEAD-LOCAL
            # worker: sealed into the head store by the loop above
            # (the owner-side copy every getter resolves against) —
            # the worker already answered its caller inline, so no
            # done routing happens here
            return
        worker_id = conn.meta.get("worker_id", "")
        wsched = self._sched_for_conn(conn)
        if msg.get("is_actor_create"):
            actor_id = msg["actor_id"]
            if wsched is not None:
                wsched.actor_ready(worker_id)
            if msg.get("error"):
                rec = self.controller.get_actor(actor_id)
                if rec is not None:
                    rec.spec.max_restarts = 0  # init failure is terminal
                self.controller.set_actor_state(
                    actor_id, DEAD, death_cause="creation failed")
                st = self._actor_state(actor_id)
                with st.lock:
                    dead = st.queued
                    st.queued = []
                cause = msg.get("error_repr", "actor __init__ raised")
                for t in dead:
                    self._store_error(t.return_ids, TaskError(
                        ActorDiedError(actor_id, cause), task_name=t.name))
            else:
                self.controller.set_actor_state(
                    actor_id, ALIVE, worker_id=worker_id,
                    node_id=getattr(wsched, "node_id", None))
                self._flush_actor_queue(actor_id)
            return
        task_id = msg["task_id"]
        if msg.get("is_actor_task"):
            # r18 head-as-host: this completion belongs to a remote
            # caller's direct call — answer it inline on the dialed
            # connection (results are already sealed above, the head
            # store IS the owner-side copy) and clear any mirror entry
            # the caller's delta already parked.
            ent = self._direct_pending.pop(task_id)
            if ent is not None:
                with self._direct_lock:
                    ring = self._direct_done_ring
                    ring[task_id] = None
                    while len(ring) > 4096:
                        ring.popitem(last=False)
                self._reply_direct_done(ent, msg, results)
                st = self._actor_states.get(msg.get("actor_id", ""))
                if st is not None:
                    with st.lock:
                        spec = st.direct_inflight.pop(task_id, None)
                    if spec is not None:
                        self._unpin(spec.pinned_refs)
                state = "FAILED" if msg.get("error") else "FINISHED"
                self.controller.record_task_event(
                    task_id, msg.get("name", ""), state,
                    worker_id=worker_id)
                return
            self._direct_stats["head_actor_dones"] += 1
            st = self._actor_states.get(msg.get("actor_id", ""))
            if st is not None:
                with st.lock:
                    spec = st.inflight.pop(task_id, None)
                if spec is not None:
                    self._unpin(spec.pinned_refs)
                    _mp.observe_task_done(
                        spec, getattr(wsched, "node_id",
                                      self.head_node_id))
            state = "FAILED" if msg.get("error") else "FINISHED"
            self.controller.record_task_event(task_id, msg.get("name", ""),
                                              state, worker_id=worker_id)
            return
        spec = (wsched.task_finished(worker_id, task_id)
                if wsched is not None else None)
        if spec is not None:
            self._unpin(spec.pinned_refs)
            _mp.observe_task_done(
                spec, getattr(wsched, "node_id", self.head_node_id))
            state = "FAILED" if msg.get("error") else "FINISHED"
            self.controller.record_task_event(spec.task_id, spec.name, state,
                                              worker_id=worker_id)

    # ================= node-agent message handlers =================
    def _proxy_for(self, node_id: str):
        rec = self.cluster.get_node(node_id)
        return rec.scheduler if rec is not None else None

    def _on_node_event(self, conn: protocol.Connection, msg: dict) -> None:
        kind = msg["kind"]
        proxy = self._proxy_for(msg["node_id"])
        if kind == "task_dispatched":
            if proxy is not None:
                proxy.on_dispatched(msg["key"], msg["worker_id"])
            self.controller.record_task_event(
                msg["key"], msg.get("name", ""), "RUNNING",
                worker_id=msg["worker_id"])
        elif kind == "actor_dispatched":
            if proxy is not None:
                proxy.on_dispatched(msg["key"], msg["worker_id"],
                                    actor_id=msg["actor_id"])
            self.controller.set_actor_state(msg["actor_id"], PENDING,
                                            worker_id=msg["worker_id"],
                                            node_id=msg["node_id"])
        elif kind == "worker_lost":
            if proxy is not None:
                proxy.on_worker_lost(msg["worker_id"])
            self._drop_direct_calls_of_caller(msg["worker_id"])
            for task in msg.get("tasks", ()):
                if proxy is not None:
                    proxy.on_finished(task.task_id)
                self._recover_task(task)
            actor_id = msg.get("actor_id")
            if actor_id is not None:
                if proxy is not None:
                    proxy.on_finished("actor:" + actor_id)
                self._recover_actor(actor_id)
        elif kind == "lease_reclaimed":
            # r10 lease revoke hand-back: the agent pulled these
            # queued-not-started tasks out of its queue — re-place the
            # MIRROR specs (authoritative retry/trace state). The pop
            # is the dedup guard: a replayed event or a racing death
            # drain finds the mirror empty and does nothing, so a task
            # is re-placed at most once.
            for spec in msg.get("specs", ()):
                mirror = (proxy.on_finished(spec.task_id)
                          if proxy is not None else None)
                if mirror is None:
                    continue
                try:
                    # same churn cap as spillback: a task bounced
                    # between saturated nodes stops moving after 3 hops
                    mirror._spill_count = \
                        getattr(mirror, "_spill_count", 0) + 1
                except AttributeError:
                    pass
                bump_attempt(mirror)
                self.cluster.submit(mirror)
        elif kind == "unplaceable":
            if proxy is not None:
                proxy.on_finished(proxy._key(msg["spec"]))
            self.on_unplaceable(msg["spec"], msg["reason"])
        elif kind == "object_at":
            self._on_object_added(msg)
        elif kind == "location_gone":
            holder = msg.get("holder")
            if holder:
                self.controller.remove_location(msg["object_id"], holder)
        elif kind == "rejoin_drained":
            # the rejoining agent's outage backlog has fully flushed
            # (connection FIFO): safe to reconcile its restored mirror.
            # Off the reader thread — resubmits may fan out RPCs.
            threading.Thread(
                target=self._reconcile_node_mirror,
                args=(msg["node_id"],),
                name="rtpu-ha-reconcile", daemon=True).start()
        elif kind == "actor_task_undeliverable":
            # the agent couldn't hand the pushed task to its worker
            # (worker died in the gap): requeue unless recovery already
            # claimed it (mirrors the local send-failure path)
            spec = msg["spec"]
            st = self._actor_state(msg["actor_id"])
            with st.lock:
                if st.inflight.pop(spec.task_id, None) is not None:
                    self._requeue_in_order(st, spec)

    def _on_node_task_done(self, conn: protocol.Connection,
                           msg: dict) -> None:
        """NODE_TASK_DONE: the control half of a remote TASK_DONE. Bulk
        results either arrived inline (small / errors) or stayed in the
        agent's store with a location registered here."""
        t_tr = _tp.recv_t0(msg)
        try:
            self._on_node_task_done_inner(conn, msg)
        finally:
            self._record_done(msg, t_tr)

    def _on_node_task_done_inner(self, conn: protocol.Connection,
                                 msg: dict) -> None:
        node_id = msg["node_id"]
        proxy = self._proxy_for(node_id)
        self._apply_node_done(node_id, proxy, msg)

    def _on_node_task_done_batch(self, conn: protocol.Connection,
                                 msg: dict) -> None:
        """NODE_TASK_DONE_BATCH (r10 delegated dispatch): N plain-task
        completions in ONE frame — each entry is the control half of a
        classic NODE_TASK_DONE (worker_id, inline/located results,
        error, per-entry trace context). One decode + one handler
        invocation amortizes the head's per-completion cost; the
        per-entry bookkeeping (seal, directory, mirror, task events)
        is unchanged."""
        node_id = msg["node_id"]
        proxy = self._proxy_for(node_id)
        # r15: a rejoining agent re-ships the sent-but-maybe-never-
        # processed tail of its completion ring; entries the old head
        # DID process dedup against the rehydrated mirror below
        replayed = bool(msg.get("replayed"))
        for entry in msg.get("done", ()):
            t_tr = _tp.recv_t0(entry)
            try:
                self._apply_node_done(node_id, proxy, entry,
                                      replayed=replayed)
            finally:
                self._record_done(entry, t_tr)

    def _apply_node_done(self, node_id: str, proxy, msg: dict,
                         replayed: bool = False) -> None:
        # r17 first-terminal-wins: a completion whose attempt counter
        # trails the live spec executed a SUPERSEDED placement (the
        # task was re-placed after a death declaration / reclaim) —
        # drop the whole entry before any seal/directory/unpin runs,
        # or the loser's results and refcount releases would land on
        # top of the winner's. A task that is no longer LIVE already
        # saw its first terminal (winner applied, or cancelled/failed):
        # any later attempt-carrying entry is a loser or a duplicate —
        # drop it too, or its re-seal would refresh nested-ref
        # containment with the loser's fresh inner ids and decref the
        # winner's (premature free).
        att = msg.get("attempt")
        if (att is not None and not msg.get("is_actor_create")
                and not msg.get("is_actor_task")):
            task_id_ = msg.get("task_id")
            live = self.controller.live_task(task_id_)
            if live is None:
                if replayed and self._ha is not None:
                    # r15 accounting: a replayed entry whose task is
                    # already terminal is a dedup, same as the
                    # empty-mirror-pop path it used to take
                    self._ha.note_replayed_completion(task_id_,
                                                      deduped=True)
                else:
                    with self._fence_lock:
                        self._fence_stats["stale_attempt_drops"] += 1
                if proxy is not None:
                    proxy.on_finished(task_id_)   # mirror hygiene
                return
            if getattr(live, "attempt", 0) > att:
                with self._fence_lock:
                    self._fence_stats["stale_attempt_drops"] += 1
                return
        for stored in msg.get("inline", []):
            self._seal_contained(stored.object_id, stored.contained_ids)
            self.store.put_stored(stored)
            if self.controller.unreferenced(stored.object_id):
                self._delete_everywhere(stored.object_id)
        for oid, nbytes, contained in msg.get("located", []):
            self._seal_contained(oid, contained)
            self.controller.add_location(oid, node_id, nbytes)
            self.waiters.notify(oid)
        worker_id = msg.get("worker_id", "")
        if msg.get("is_actor_create"):
            actor_id = msg["actor_id"]
            if proxy is not None:
                proxy.on_finished("actor:" + actor_id)
                # keep the actor's mirror entry: restarts need the spec
                rec0 = self.controller.get_actor(actor_id)
                if rec0 is not None and not msg.get("error"):
                    proxy.track_live_actor(actor_id, rec0.spec)
            if msg.get("error"):
                rec = self.controller.get_actor(actor_id)
                if rec is not None:
                    rec.spec.max_restarts = 0
                self.controller.set_actor_state(
                    actor_id, DEAD, death_cause="creation failed")
                st = self._actor_state(actor_id)
                with st.lock:
                    dead = st.queued
                    st.queued = []
                cause = msg.get("error_repr", "actor __init__ raised")
                for t in dead:
                    self._store_error(t.return_ids, TaskError(
                        ActorDiedError(actor_id, cause), task_name=t.name))
            else:
                self.controller.set_actor_state(actor_id, ALIVE,
                                                worker_id=worker_id,
                                                node_id=node_id)
                self._flush_actor_queue(actor_id)
            return
        task_id = msg["task_id"]
        if msg.get("is_actor_task"):
            self._direct_stats["head_actor_dones"] += 1
            st = self._actor_states.get(msg.get("actor_id", ""))
            if st is not None:
                with st.lock:
                    spec = st.inflight.pop(task_id, None)
                if spec is not None:
                    self._unpin(spec.pinned_refs)
                    _mp.observe_task_done(spec, node_id)
            state = "FAILED" if msg.get("error") else "FINISHED"
            self.controller.record_task_event(task_id, msg.get("name", ""),
                                              state, worker_id=worker_id)
            return
        spec = proxy.on_finished(task_id) if proxy is not None else None
        if replayed and self._ha is not None:
            # exactly-once accounting across the restart: a replayed
            # entry whose mirror pop hit counts as a recovered
            # completion; an empty pop means the pre-crash head (or an
            # earlier copy of this entry) already processed it
            self._ha.note_replayed_completion(task_id,
                                              deduped=spec is None)
        if spec is not None:
            self._unpin(spec.pinned_refs)
            _mp.observe_task_done(spec, node_id)
            state = "FAILED" if msg.get("error") else "FINISHED"
            self.controller.record_task_event(spec.task_id, spec.name,
                                              state, worker_id=worker_id)

    def _on_object_added(self, msg: dict) -> None:
        """A node sealed/pulled a copy (OBJECT_ADDED, or the legacy
        object_at node event): register the location — the directory
        listener cascades any active broadcast — and wake getters.
        ``partial`` entries (r12 cut-through: the sender landed its
        first chunk and can relay landed ranges) register advisory
        partial holders only: no refcount, no waiter wakeups — the
        object is not actually available there yet."""
        oid = msg["object_id"]
        if msg.get("partial"):
            self.controller.add_location(oid, msg["node_id"],
                                         msg.get("nbytes", 0),
                                         partial=True)
            return
        self._seal_contained(oid, msg.get("contained") or [])
        if msg.get("addref"):
            self.controller.addref(oid)
        self.controller.add_location(oid, msg["node_id"],
                                     msg.get("nbytes", 0))
        self.waiters.notify(oid)

    def _on_locate_object(self, conn: protocol.Connection,
                          msg: dict) -> None:
        """Non-blocking directory read (LOCATE_OBJECT): every alive
        holder's dial address, for multi-source pulls. Unlike
        OBJECT_LOOKUP this never parks — pull managers use it to
        rotate sources mid-transfer."""
        oid = msg["object_id"]
        locs = []
        alive = {n.node_id: n for n in self.cluster.alive_nodes()}
        for nid in self.controller.locations(oid):
            rec = alive.get(nid)
            addr = (getattr(rec.scheduler, "advertise_addr", None)
                    if rec else None)
            if addr is not None:
                locs.append({"host": addr[0], "port": int(addr[1]),
                             "node_id": nid,
                             # r17: pullers deprioritize suspect
                             # holders (gray failure in progress) —
                             # the flag is the contract; the agent
                             # shuffles and re-orders locally
                             "suspect": rec.suspect})
        conn.reply(msg, locations=locs,
                   head_has=self.store.contains(oid),
                   nbytes=self.controller.directory.nbytes(oid))

    def _on_object_lookup(self, conn: protocol.Connection,
                          msg: dict) -> None:
        """An agent asks where an object lives; parks here until it
        exists anywhere (the head owns waiter parking cluster-wide)."""
        oid = msg["object_id"]

        def answer(w=None, timed_out: bool = False) -> None:
            try:
                if timed_out:
                    conn.reply(msg, stored=None, location=None)
                    return
                stored = self.store.get_stored(oid, timeout=0,
                                               restore=False)
                if stored is None and self.store.contains(oid):
                    # spilled head-side: restore off-thread, then serve
                    self._restore_pool.submit(self._lookup_restore_reply,
                                              conn, msg, oid)
                    return
                if stored is not None:
                    from ray_tpu._private.config import CONFIG as _C
                    from ray_tpu._private.object_transfer import materialize
                    if stored.nbytes <= _C.remote_inline_max_bytes:
                        conn.reply(msg, stored=materialize(stored))
                    else:
                        conn.reply(msg, stored=None, head_pull=True)
                    return
                locs = self.controller.locations(oid)
                alive = {n.node_id: n for n in self.cluster.alive_nodes()}
                for nid in locs:
                    rec = alive.get(nid)
                    addr = getattr(rec.scheduler, "advertise_addr",
                                   None) if rec else None
                    if addr is not None:
                        conn.reply(msg, stored=None,
                                   location={"host": addr[0],
                                             "port": addr[1],
                                             "node_id": nid})
                        return
                conn.reply(msg, stored=None, location=None)
            except protocol.ConnectionClosed:
                pass

        if (self.store.contains(oid)
                or self.controller.has_location(oid)):
            answer()
            return
        self.waiters.add_get(oid, lambda w, to: answer(w, to),
                             msg.get("timeout"))

    def _lookup_restore_reply(self, conn, msg, oid: str) -> None:
        from ray_tpu._private.config import CONFIG as _C
        from ray_tpu._private.object_transfer import materialize
        try:
            stored = self.store.get_stored(oid, timeout=30)
            if stored is None:
                conn.reply(msg, stored=None, location=None)
            elif stored.nbytes <= _C.remote_inline_max_bytes:
                conn.reply(msg, stored=materialize(stored))
            else:
                conn.reply(msg, stored=None, head_pull=True)
        except protocol.ConnectionClosed:
            pass

    def _on_get_object(self, conn: protocol.Connection, msg: dict) -> None:
        """Event-driven get: a fast residency probe on the reader
        thread; on miss the request parks in the waiter registry (no
        thread) and the put_stored seal hook resolves it. Spilled
        objects restore on a small worker pool so the disk read never
        runs on a connection reader thread."""
        oid = msg["object_id"]
        stored = self.store.get_stored(oid, timeout=0, restore=False)
        if stored is not None:
            conn.reply(msg, stored=stored)
            return
        timeout = msg.get("timeout")
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        wid = conn.meta.get("worker_id")
        wsched = self._sched_for_conn(conn)
        if self.store.contains(oid) or self.controller.has_location(oid):
            self._restore_pool.submit(
                self._blocking_get_reply, conn, msg, oid, deadline,
                wsched, wid)
            return
        self._park_get(conn, msg, oid, deadline, wsched, wid)

    def _park_get(self, conn, msg, oid, deadline: Optional[float],
                  wsched, wid) -> None:
        """Park a get in the waiter registry until the object seals
        locally or a location registers; resolution routes any actual
        disk/network work back to the restore pool."""
        if wsched is not None:
            wsched.worker_blocked(wid)
        remaining = (None if deadline is None
                     else max(0.0, deadline - time.monotonic()))

        def reply(w, timed_out: bool) -> None:
            try:
                if timed_out:
                    conn.reply(msg, stored=None, timeout=True)
                    return
                got = self.store.get_stored(oid, timeout=0, restore=False)
                if got is not None:
                    conn.reply(msg, stored=got)
                elif (self.store.contains(oid)
                      or self.controller.has_location(oid)):
                    # spilled or remote: remaining budget only
                    self._restore_pool.submit(
                        self._blocking_get_reply, conn, msg, oid,
                        deadline, wsched, wid)
                else:
                    # sealed then evicted in the gap: genuine miss
                    conn.reply(msg, stored=None, timeout=True)
            except protocol.ConnectionClosed:
                pass

        self.waiters.add_get(
            oid, reply, remaining,
            on_done=((lambda: wsched.worker_unblocked(wid))
                     if wsched is not None else None))

    def _blocking_get_reply(self, conn, msg, oid,
                            deadline: Optional[float],
                            wsched=None, wid=None) -> None:
        """Restore/pull-pool path: does only work that is actionable NOW
        (spill restore, remote pull). If the object becomes truly absent
        — stale location dropped, nothing local — the request goes BACK
        to the waiter registry instead of parking a pool thread: the
        2-thread pool must never be consumed by indefinite waits. The
        worker stays marked blocked while we do actual work here
        (oversubscription parity with the old thread-per-get path)."""
        if wsched is not None:
            wsched.worker_blocked(wid)
        try:
            while True:
                got = self.store.get_stored(oid, timeout=0)
                if got is not None:
                    conn.reply(msg, stored=got)
                    return
                if self.controller.has_location(oid):
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    got = self._pull_remote(oid, timeout=remaining)
                    if got is not None:
                        conn.reply(msg, stored=got)
                        return
                    if (deadline is not None
                            and time.monotonic() >= deadline):
                        conn.reply(msg, stored=None, timeout=True)
                        return
                    continue            # stale location dropped; re-check
                if (deadline is not None
                        and time.monotonic() >= deadline):
                    conn.reply(msg, stored=None, timeout=True)
                    return
                # nothing actionable: hand back to the registry
                self._park_get(conn, msg, oid, deadline, wsched, wid)
                return
        except protocol.ConnectionClosed:
            pass
        finally:
            if wsched is not None:
                wsched.worker_unblocked(wid)

    # ================= cross-host object fetch =================
    def _get_stored_anywhere(self, oid: str,
                             timeout: Optional[float]) -> Optional[
                                 StoredObject]:
        """Blocking fetch that spans the cluster: local store (incl.
        spill restore), else chunked pull from whichever alive agent
        holds a copy (reference pull_manager.cc role). Stale locations
        (holder died/evicted) are dropped and the wait resumes, which
        gives lineage resubmission time to regenerate the object."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            stored = self.store.get_stored(oid, timeout=0)
            if stored is not None:
                return stored
            if self.controller.has_location(oid):
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                stored = self._pull_remote(oid, timeout=remaining)
                if stored is not None:
                    return stored
                # a failed pull no longer guarantees a location was
                # dropped (semaphore/budget/dedup-join timeouts keep
                # them by design): honour the caller's deadline here
                # or contention turns this loop into a busy spin
                if deadline is not None and time.monotonic() >= deadline:
                    return None
                continue                 # stale location dropped; retry
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                return None
            ev = threading.Event()
            self.waiters.add_get(oid, lambda w, to: ev.set(), remaining)
            ev.wait(None if remaining is None else remaining + 1)
            if deadline is not None and time.monotonic() > deadline:
                # one last probe: the seal may have raced the deadline
                stored = self.store.get_stored(oid, timeout=0)
                if stored is not None:
                    return stored
                if not self.controller.has_location(oid):
                    return None

    def _head_pull_sources(self, oid: str, prefer=None):
        """Pull-manager source iterator: every alive agent holding a
        copy, over its existing control connection (shuffled for load
        spread). Dead / in-process locations are dropped from the
        directory as they are encountered — same stale-location
        hygiene the pre-pull-manager loop had."""
        import random
        nids = self.controller.locations(oid)
        random.shuffle(nids)
        for nid in nids:
            rec = self.cluster.get_node(nid)
            if rec is None or not rec.alive:
                self.controller.remove_location(oid, nid)
                continue
            conn = getattr(rec.scheduler, "conn", None)
            if conn is None:   # local in-process node: nothing to pull
                self.controller.remove_location(oid, nid)
                continue
            yield (nid, conn)

    def _pull_remote(self, oid: str,
                     timeout: Optional[float] = None
                     ) -> Optional[StoredObject]:
        """Pull one object from any alive agent holding it, through the
        head's pull manager (dedup: N parked getters of one object cost
        one transfer; bounded in-flight bytes); caches the bytes in the
        head store (LRU/spill governs them from there). Returns None
        once every registered location proved stale. `timeout` bounds
        this attempt to the caller's remaining budget (default 30s for
        deadline-less gets, so a single attempt can't park forever)."""
        if timeout is None:
            timeout = 30.0
        return self._pull_mgr.pull(oid, timeout=max(0.1, timeout))

    def broadcast_object(self, object_id: str,
                         fanout: Optional[int] = None,
                         timeout: Optional[float] = None) -> dict:
        """Distribute one object to every alive node in a fanout tree
        (the source serves <= fanout transfers; each completed puller
        serves its subtree). Returns the tree/completion stats."""
        return self.bcast.broadcast(object_id, fanout=fanout,
                                    timeout=timeout)

    def _object_plane_stats(self) -> dict:
        """Object-plane observability: head counters + per-node
        heartbeat-carried counters + directory/broadcast state."""
        from ray_tpu._private.object_transfer import OBJECT_PLANE_STATS
        nodes = {}
        for n in self.cluster.alive_nodes():
            op = getattr(n.scheduler, "object_plane", None)
            if op:
                nodes[n.node_id] = dict(op)
        return {
            "head": {
                **OBJECT_PLANE_STATS,
                "sessions": self._pull_server.session_count(),
                "serves_per_object":
                    self._pull_server.serves_per_object(),
                **{"pull_" + k: v
                   for k, v in self._pull_mgr.stats().items()},
            },
            "nodes": nodes,
            "directory": self.controller.directory.stats(),
            "broadcast": self.bcast.stats(),
        }

    # ================= tracing plane: collection =================
    def _trace_dump(self, timeout: float = 5.0) -> dict:
        """Drain every process's flight recorder: the head's own, each
        local worker's, and each agent's (the agent fans out to ITS
        workers and replies with the whole node). Pull, not push —
        heartbeats only carry watermarks. Peer timestamps are aligned
        onto the head's monotonic clock via the request/reply RTT
        midpoint (tracing_plane.rtt_offset); an agent's workers are
        aligned transitively (their offsets are relative to the
        agent)."""
        procs = [dict(_tp.dump(), offset_ns=0,
                      node_id=self.head_node_id)]
        targets: list[tuple] = []    # ((kind, node_id), connection)
        sched = self.scheduler
        if sched is not None:
            for wid, conn in sched.worker_conns():
                targets.append((("worker", self.head_node_id), conn))
        for node in self.cluster.alive_nodes():
            conn = getattr(node.scheduler, "conn", None)
            # an agent that negotiated MINOR < 2 silently drops the
            # unknown TRACE_DUMP type and would burn the shared
            # deadline waiting for a reply that can never come
            if conn is not None and conn._peer_speaks_trace():
                targets.append((("agent", node.node_id), conn))
        for (kind, nid), t0, t1, rep in _tp.fanout_dumps(
                targets, timeout, extra={"timeout": timeout}):
            if kind == "worker":
                d = rep.get("dump")
                if d:
                    procs.append(dict(
                        d, node_id=nid,
                        offset_ns=_tp.rtt_offset(t0, t1, d["now_ns"])))
            else:
                # the agent re-samples its clock just before replying
                # (now_ns field), AFTER its worker drain — an RTT-
                # midpoint estimate over the whole exchange would be
                # skewed by however long that drain took
                if "now_ns" in rep:
                    agent_off = int(rep["now_ns"]) - t1
                else:
                    agent_off = None
                for d in rep.get("processes") or ():
                    if agent_off is None:   # agent's own dump is first
                        agent_off = _tp.rtt_offset(
                            t0, t1, d.get("now_ns", 0))
                    procs.append(dict(
                        d, node_id=nid,
                        offset_ns=(int(d.get("offset_ns", 0))
                                   + agent_off)))
        return {"processes": procs}

    # ================= metrics plane: collection =================
    def _sample_metrics(self) -> None:
        """Head sampler: mirror per-agent delegated-lease ledgers and
        the head's pull-manager/pull-server occupancy into gauges.
        set_many REPLACES the series set, so a removed node's labeled
        gauges drop from the head's own registry immediately."""
        m = _mp._metrics()
        out, batches, leased, revoked = [], [], [], []
        for n in self.cluster.alive_nodes():
            h = n.scheduler
            if not hasattr(h, "_leased"):
                continue                     # in-process local node
            with h._lock:
                out.append(({"node": n.node_id}, float(len(h._leased))))
            batches.append(({"node": n.node_id}, float(h._leases_sent)))
            leased.append(({"node": n.node_id}, float(h._tasks_leased)))
            revoked.append(({"node": n.node_id}, float(
                (h.delegate_stats or {}).get("revoked", 0))))
        m.lease_outstanding.set_many(out)
        m.lease_batches.set_many(batches)
        m.lease_tasks.set_many(leased)
        m.lease_revoked.set_many(revoked)
        pm = self._pull_mgr.stats()
        m.pull_inflight.set(pm["inflight"])
        m.pull_inflight_bytes.set(pm["inflight_bytes"])
        if self._ha is not None:
            # r15 head-HA gauges: WAL volume, fsync tail latency,
            # snapshot staleness, replayed-completion accounting
            st = self._ha.stats()
            wal = st["wal"]
            rows = [({"counter": "wal_bytes"}, float(wal["bytes"])),
                    ({"counter": "wal_records"}, float(wal["records"])),
                    ({"counter": "wal_fsyncs"}, float(wal["fsyncs"])),
                    ({"counter": "compactions"},
                     float(wal["compactions"])),
                    ({"counter": "replayed_completions"}, float(
                        st["recovered"]["replayed_completions"])),
                    ({"counter": "deduped_completions"}, float(
                        st["recovered"]["deduped_completions"]))]
            if wal["fsync_p99_ms"] is not None:
                rows.append(({"counter": "fsync_p99_ms"},
                             float(wal["fsync_p99_ms"])))
            if st["last_snapshot_age_s"] is not None:
                rows.append(({"counter": "last_snapshot_age_s"},
                             float(st["last_snapshot_age_s"])))
            m.head_wal.set_many(rows)
        # r16: striped-table occupancy/contention + decref-delta
        # application counters — the sharding win observable, not
        # just benchable
        rows = []
        for table, st in self.controller.shard_stats().items():
            for k in ("entries", "max_stripe", "contended", "evicted"):
                if k in st:
                    rows.append(({"table": table, "counter": k},
                                 float(st[k])))
        m.head_shard.set_many(rows)
        m.decref_delta.set_many(
            [({"counter": "head_" + k}, float(v))
             for k, v in self._decref_delta_stats.items()])
        # r18 direct actor plane: head-process caller/host counters
        # plus each agent's heartbeat-carried host counters
        rows = [({"party": "head", "counter": k}, float(v))
                for k, v in self._direct_stats.items()]
        for n in self.cluster.alive_nodes():
            for k, v in (getattr(n.scheduler, "direct_stats", None)
                         or {}).items():
                rows.append(({"party": "node:" + n.node_id,
                              "counter": k}, float(v)))
        m.direct_actor.set_many(rows)
        # r17 membership plane: per-node liveness (one-hot by state) +
        # last-heartbeat age, plus fence/suspicion transition counters
        lv = self.cluster.liveness_stats()
        m.node_liveness.set_many(
            [({"node": row["node_id"], "state": row["state"]}, 1.0)
             for row in lv["nodes"]])
        m.node_heartbeat_age.set_many(
            [({"node": row["node_id"]},
              float(row["last_heartbeat_age_s"]))
             for row in lv["nodes"]])
        m.membership.set_many(
            [({"counter": k}, float(v))
             for k, v in {**lv["counters"],
                          **self._fence_stats}.items()])

    def _trace_stats(self) -> dict:
        rec = _tp.recorder()
        nodes = {}
        for n in self.cluster.alive_nodes():
            wm = getattr(n.scheduler, "trace_watermark", None)
            if wm is not None:
                nodes[n.node_id] = wm
        return {"enabled": _tp.enabled(),
                "head": {"watermark": rec.watermark(),
                         "capacity": rec.capacity,
                         "dropped": rec.dropped()},
                "nodes": nodes}

    def _delete_everywhere(self, oid: str) -> None:
        """Deletion fan-out: local store + every agent holding a copy.
        Releases the counts this object held on refs pickled inside it
        (nested-ref ownership), cascading deletes as counts hit zero."""
        self.store.delete(oid)
        for cid in self.controller.pop_contained(oid):
            self.decref(cid)
        locs = self.controller.locations(oid)
        for nid in locs:
            rec = self.cluster.get_node(nid)
            conn = getattr(rec.scheduler, "conn", None) if rec else None
            if conn is not None:
                try:
                    conn.send({"type": protocol.NODE_DELETE_OBJECT,
                               "object_id": oid})
                except protocol.ConnectionClosed:
                    pass
        if locs:
            self.controller.remove_location(oid)
        self.controller.drop_lineage(oid)

    def on_node_objects_lost(self, node_id: str) -> None:
        """Lineage reconstruction (reference task_manager.h:269
        ResubmitTask + object_recovery_manager.h:41): objects whose ONLY
        copy died with `node_id` and are still referenced get their
        producing task resubmitted. Single-level: if the resubmitted
        task's own args were also lost, their gets re-enter this path
        when their holders' deaths are processed."""
        from ray_tpu._private.config import CONFIG as _C
        orphaned = self.controller.purge_node_locations(node_id)
        resubmitted: set[str] = set()
        for oid in orphaned:
            if self.controller.unreferenced(oid):
                self.controller.drop_lineage(oid)
                continue
            spec = self.controller.lineage_for(oid)
            if spec is None or spec.task_id in resubmitted:
                continue
            n = getattr(spec, "lineage_resubmits", 0)
            if n >= _C.lineage_max_resubmits:
                continue
            spec.lineage_resubmits = n + 1
            resubmitted.add(spec.task_id)
            bump_attempt(spec)
            # back on the live books: the regenerating execution must
            # survive a head restart too
            self.controller.task_submitted(spec)
            self.controller.record_task_event(
                spec.task_id, spec.name, "RESUBMITTED",
                error=f"lost output {oid} on {node_id}")
            for pid in spec.pinned_refs:
                self.controller.pin(pid)
            self.cluster.submit(spec)

    def _on_wait(self, conn: protocol.Connection, msg: dict) -> None:
        ids, num_returns = msg["object_ids"], msg["num_returns"]
        ready_now = [o for o in ids if self.store.contains(o)]
        if len(ready_now) >= num_returns:
            conn.reply(msg, ready=ready_now[:num_returns])
            return
        wid = conn.meta.get("worker_id")
        wsched = self._sched_for_conn(conn)
        if wsched is not None:
            wsched.worker_blocked(wid)

        def reply(w, ready: list[str]) -> None:
            try:
                conn.reply(msg, ready=ready[:num_returns])
            except protocol.ConnectionClosed:
                pass

        self.waiters.add_wait(
            ids, num_returns, reply, msg.get("timeout"),
            on_done=((lambda: wsched.worker_unblocked(wid))
                     if wsched is not None else None))

    def _kv_dispatch(self, msg: dict) -> Any:
        op = msg["op"]
        ns = msg.get("namespace", "default")
        key = msg.get("key", "")
        if op == "get":
            return self.controller.kv_get(key, ns)
        if op == "put":
            return self.controller.kv_put(key, msg.get("value"), ns,
                                          msg.get("overwrite", True))
        if op == "del":
            return self.controller.kv_del(key, ns)
        if op == "exists":
            return self.controller.kv_exists(key, ns)
        if op == "keys":
            return self.controller.kv_keys(key, ns)
        if op == "func_get":
            return self.controller.get_function(key)
        raise ValueError(f"unknown kv op {op}")

    # ================= BaseContext API (driver) =================
    def put(self, value: Any) -> ObjectRef:
        from ray_tpu._private.object_store import serialize
        stored = serialize(value)
        self._seal_contained(stored.object_id, stored.contained_ids)
        # driver thread: safe to apply create-queueing backpressure
        self.store.put_stored(stored, block=True)
        self.controller.addref(stored.object_id)
        return ObjectRef(stored.object_id)

    def get_objects(self, object_ids: list[str],
                    timeout: Optional[float]) -> list[Any]:
        deadline = None if timeout is None else time.time() + timeout
        out = []
        for oid in object_ids:
            remaining = None if deadline is None else max(
                0.0, deadline - time.time())
            stored = self._get_stored_anywhere(oid, remaining)
            if stored is None:
                raise GetTimeoutError(
                    f"get() timed out waiting for {oid}")
            try:
                value = deserialize(stored)
            except FileNotFoundError:
                # The spill policy unlinked this object's shm between
                # get_stored and the map (rare: touch-grace usually
                # prevents it). The data lives in the spill file —
                # re-fetch; the restore comes back with inline buffers.
                stored = self._get_stored_anywhere(oid, remaining)
                if stored is None:
                    raise GetTimeoutError(
                        f"get() timed out waiting for {oid}")
                value = deserialize(stored)
            if stored.is_error:
                raise value
            out.append(value)
        return out

    def wait(self, object_ids: list[str], num_returns: int,
             timeout: Optional[float]) -> tuple[list[str], list[str]]:
        """Registry-based wait spanning local residency AND remote
        locations. Contract: at most num_returns ready, input order."""
        result: list[list[str]] = []
        ev = threading.Event()

        def reply(w, ready: list[str]) -> None:
            result.append(ready)
            ev.set()

        self.waiters.add_wait(object_ids, num_returns, reply, timeout)
        ev.wait(None if timeout is None else timeout + 5)
        ready_list = (result[0] if result else [])[:num_returns]
        taken = set(ready_list)
        not_ready = [o for o in object_ids if o not in taken]
        return ready_list, not_ready

    def addref(self, object_id: str) -> None:
        self.controller.addref(object_id)

    def decref(self, object_id: str) -> None:
        if self._shutdown:
            return
        if self.controller.decref(object_id):
            self._delete_everywhere(object_id)

    def decref_batch(self, object_ids: list[str]) -> None:
        """Batched release (head-local workers' DECREF_BATCH and the
        driver's own flusher): counts apply per shard — one stripe
        lock per shard, not one controller lock per release (r16)."""
        if self._shutdown or not object_ids:
            return
        counts: dict[str, int] = {}
        for oid in object_ids:
            counts[oid] = counts.get(oid, 0) + 1
        for oid in self.controller.apply_decref_delta("", 0, counts) or ():
            self._delete_everywhere(oid)

    def _on_decref_delta(self, msg: dict) -> None:
        """NODE_DECREF_DELTA (r16): a delegated agent's coalesced
        release counts. The controller's per-node seq watermark drops
        replayed frames (rejoin replay after a head restart or
        reconnect) so no release is ever applied twice."""
        counts = msg.get("counts") or {}
        dead = self.controller.apply_decref_delta(
            msg.get("node_id", ""), int(msg.get("seq", 0)), counts)
        st = self._decref_delta_stats
        if dead is None:
            st["deduped_frames"] += 1
            return
        st["frames"] += 1
        st["entries"] += len(counts)
        if not self._shutdown:
            for oid in dead:
                self._delete_everywhere(oid)

    # ---- tracing plane (r9) ----
    def _stamp_trace(self, spec) -> Optional[tuple]:
        """Open the spec's submit span: join the caller's active trace
        (or the trace a relaying worker already stamped on the spec;
        else — when the sampler elects this root submission,
        RAY_TPU_TRACE_SAMPLE — start a fresh one) and point the spec's
        parent_span at this span, so downstream scheduler/worker spans
        chain under it. The decision here is the WHOLE decision (r16):
        an unsampled spec keeps trace_id 0, so every downstream
        emission site (scheduler queue/lease, agent, worker recv/exec/
        put, pull manager, done) skips its span and its frames carry
        zero trace bytes — whole-or-nothing across processes, exactly
        the RAY_TPU_TRACE=0 byte shape. Returns (trace_id, span_id,
        parent, t0_ns) for _record_submit, or None when tracing is off
        or this task is unsampled."""
        if not _tp.enabled():
            return None
        tid = getattr(spec, "trace_id", 0)   # pre-r9-pickled specs
        if tid:                              # have no trace fields
            parent = getattr(spec, "parent_span", 0)   # relayed
        else:
            cur = _tp.current()
            if cur:
                tid, parent = cur[0], cur[1]   # nested: inherit
            elif _tp.sample():
                tid, parent = _tp.new_id(), 0  # sampled root
            else:
                return None                    # unsampled: no trace
            spec.trace_id = tid
        sid = _tp.new_id()
        spec.parent_span = sid
        return (tid, sid, parent, _tp.now())

    @staticmethod
    def _record_submit(tr: Optional[tuple], spec) -> None:
        if tr is not None:
            tid, sid, parent, t0 = tr
            _tp.record("submit", spec.name or spec.task_id, t0,
                       _tp.now(), tid, sid, parent)

    @staticmethod
    def _record_done(msg: dict, t0: Optional[int]) -> None:
        """TASK_DONE-processing span, parented under the worker's exec
        span via the envelope-carried trace context."""
        if t0 is None:
            return
        tr = msg.get("_trace")
        if tr:
            _tp.record("done", msg.get("name", "") or
                       str(msg.get("task_id", "")), t0, _tp.now(),
                       tr[0], _tp.new_id(), tr[1])

    def submit_spec(self, spec: TaskSpec) -> list[str]:
        tr = self._stamp_trace(spec)
        _mp.submit_stamp(spec)
        for oid in spec.pinned_refs:
            self.controller.pin(oid)
        # lineage + live-task entry + ONE WAL submit record (r15): a
        # restarted head re-owns this task from here
        self.controller.task_submitted(spec)
        self.controller.record_task_event(spec.task_id, spec.name, "PENDING")
        self.cluster.submit(spec)
        self._record_submit(tr, spec)
        return spec.return_ids

    submit_task = submit_spec

    def register_function(self, func_id: str, data: bytes) -> None:
        self.controller.put_function(func_id, data)

    # ---- actors ----
    def _actor_state(self, actor_id: str) -> _ActorState:
        with self._actor_lock:
            st = self._actor_states.get(actor_id)
            if st is None:
                st = self._actor_states[actor_id] = _ActorState()
            return st

    def create_actor_from_spec(self, spec: ActorSpec) -> str:
        self.controller.register_actor(spec)
        self._actor_state(spec.actor_id)
        self.cluster.submit(spec)
        return spec.actor_id

    create_actor = create_actor_from_spec

    def submit_actor_task_spec(self, actor_id: str,
                               spec: ActorTaskSpec,
                               register_borrows: bool = True
                               ) -> list[str]:
        # register_borrows: the driver-as-caller registers its return-
        # id borrows here (in-process, free). Wire-relayed submissions
        # pass False — their caller already addref'd eagerly on the
        # head-routed path.
        if register_borrows:
            for oid in spec.return_ids:
                self.controller.addref(oid)
        _mp.submit_stamp(spec)
        tr = self._stamp_trace(spec)
        try:
            return self._submit_actor_task_inner(actor_id, spec)
        finally:
            self._record_submit(tr, spec)

    def _submit_actor_task_inner(self, actor_id: str,
                                 spec: ActorTaskSpec) -> list[str]:
        for oid in spec.pinned_refs:
            self.controller.pin(oid)
        rec = self.controller.get_actor(actor_id)
        if rec is None:
            self._store_error(spec.return_ids, TaskError(
                ActorError(actor_id, "unknown actor"), task_name=spec.name))
            return spec.return_ids
        st = self._actor_state(actor_id)
        with st.lock:
            if rec.state == DEAD:
                self._store_error(spec.return_ids, TaskError(
                    ActorDiedError(actor_id,
                                   f"Actor {actor_id} is dead: "
                                   f"{rec.death_cause}"),
                    task_name=spec.name))
                return spec.return_ids
            # queued-not-empty implies an ordering predecessor (an
            # undeliverable requeue or a direct-path fallback) still
            # waiting: append BEHIND it even while ALIVE, or this call
            # would overtake it (per-handle submission order)
            self._stamp_order(st, spec)
            if (rec.state != ALIVE or rec.worker_id is None
                    or st.queued):
                was_alive = rec.state == ALIVE and st.queued
                st.queued.append(spec)
                if not was_alive:
                    return spec.return_ids
            else:
                # sticky direct fallback clears once every book is
                # empty: all prior calls reached a terminal state, so
                # a fresh direct call cannot overtake anything
                if (st.fallback and not st.inflight
                        and not st.direct_inflight):
                    st.fallback = False
                spec._route = "direct"   # tentative: the routability
                                         # probe must not see this
                                         # spec as a head predecessor
                st.inflight[spec.task_id] = spec
                claim = st.epoch
                target = rec.worker_id
                use_direct = (not st.fallback
                              and self._direct_routable(rec, st))
                was_alive = False
        if was_alive:                   # appended behind the queue
            self._flush_actor_queue(actor_id)
            return spec.return_ids
        if use_direct and self._try_direct_actor_call(rec, st, spec):
            return spec.return_ids
        spec._route = "head"
        if not self._send_actor_task(target, spec):
            with st.lock:
                # Requeue only if a concurrent _recover_actor didn't
                # already claim it (epoch check): recovery may have
                # requeued AND re-sent this spec already — a blind pop
                # here silently dropped the call (r18 satellite fix).
                if st.epoch != claim:
                    self._direct_stats["send_race_kept"] += 1
                elif st.inflight.pop(spec.task_id, None) is not None:
                    self._requeue_in_order(st, spec)
        return spec.return_ids

    submit_actor_task = submit_actor_task_spec

    def _send_actor_task(self, worker_id: str, spec: ActorTaskSpec) -> bool:
        # load-independent signal for bench_core: every head-routed
        # actor-task send counts (direct-path calls never come here)
        self._direct_stats["head_routed_sends"] += 1
        sched = self._scheduler_for_worker(worker_id)
        if sched is None:
            return False
        return sched.send_actor_task(worker_id, spec)

    def _flush_actor_queue(self, actor_id: str) -> None:
        rec = self.controller.get_actor(actor_id)
        if rec is None or rec.state != ALIVE:
            return
        st = self._actor_state(actor_id)
        while True:
            with st.lock:
                if not st.queued:
                    return
                spec = st.queued.pop(0)
                st.inflight[spec.task_id] = spec
                claim = st.epoch
                target = rec.worker_id
            spec._route = "head"
            if not self._send_actor_task(target, spec):
                with st.lock:
                    # same claim discipline as the submit path: a
                    # recovery sweep between the send failure and this
                    # repop already owns the spec
                    if st.epoch != claim:
                        self._direct_stats["send_race_kept"] += 1
                    elif st.inflight.pop(spec.task_id,
                                         None) is not None:
                        self._requeue_in_order(st, spec)
                return

    # ---- per-handle ordering helpers (r18) ----
    @staticmethod
    def _stamp_order(st, spec) -> None:
        """Assign the actor's next submission-order stamp (caller
        holds st.lock). Idempotent: a re-placed spec keeps its
        original position."""
        if getattr(spec, "_order", None) is None:
            spec._order = st.next_order
            st.next_order += 1

    @staticmethod
    def _requeue_in_order(st, spec) -> None:
        """Insert a re-placed spec into st.queued by its submission
        stamp (caller holds st.lock): requeues arrive from multiple
        sources (direct NACK fallbacks, undeliverable events, recovery
        sweeps) whose processing order is not submission order."""
        import bisect
        keys = [getattr(s, "_order", 0) for s in st.queued]
        i = bisect.bisect(keys, getattr(spec, "_order", 0))
        st.queued.insert(i, spec)

    # ---- direct actor call plane (r18) ----
    def _direct_routable(self, rec, st) -> bool:
        """Whether the driver may dial this actor's host directly:
        config on, the actor lives on a REMOTE healthy node whose
        agent speaks wire MINOR >= 8, and every in-flight call for the
        handle is itself direct (a head-routed call still in transit
        must not be overtaken). Caller holds st.lock."""
        from ray_tpu._private.config import CONFIG as _C
        if not _C.direct_actor:
            return False
        if rec.node_id in (None, self.head_node_id):
            return False          # head-local: already zero-hop here
        node = self.cluster.get_node(rec.node_id)
        if node is None or not node.alive or node.suspect:
            return False
        handle = node.scheduler
        conn = getattr(handle, "conn", None)
        if (conn is None or getattr(handle, "draining", False)
                or not conn.peer_speaks_direct_actor()):
            return False
        return all(getattr(s, "_route", "") == "direct"
                   for s in st.inflight.values())

    def _direct_conn(self, addr: tuple) -> Optional[protocol.Connection]:
        from ray_tpu._private import direct_actor as _da
        return _da.dial_cached(self._direct_conns, self._direct_lock,
                               addr, poller=self._poller)

    def _try_direct_actor_call(self, rec, st, spec) -> bool:
        """Driver-as-caller: stream the call straight to the hosting
        agent's listener; the reply (inline results / located hints)
        lands on the dialed connection and seals into the head store
        in-process — zero head control-plane frames in steady state.
        The spec is already claimed in st.inflight; False falls back
        to the head-routed send."""
        node = self.cluster.get_node(rec.node_id)
        handle = node.scheduler if node else None
        addr = getattr(handle, "advertise_addr", None)
        if not addr:
            return False
        # worker-direct when the worker's listener is known (heartbeat
        # rows); agent-hosted otherwise — same preference as resolve,
        # but never switch endpoints while other calls are in flight
        wport = handle.direct_port_of(rec.worker_id)
        want = (addr[0], int(wport or addr[1]))
        with self._direct_lock:
            prev = self._direct_actor_addr.get(spec.actor_id)
        if prev is not None and prev != want:
            with st.lock:
                if len(st.inflight) > 1:      # beyond this spec
                    want = prev               # quiet moments only
        with self._direct_lock:
            self._direct_actor_addr[spec.actor_id] = want
        conn = self._direct_conn(want)
        if conn is not None:
            # chaos rules match by peer node id: a partition of the
            # node must park this plane's frames too
            conn.meta.setdefault("chaos_peer", rec.node_id)
        if conn is None:
            return False
        spec._route = "direct"
        msg = {"type": protocol.ACTOR_TASK_DIRECT, "spec": spec,
               "actor_id": spec.actor_id, "worker_id": rec.worker_id,
               "epoch": rec.num_restarts,
               "node_incarnation": handle.incarnation}
        if _tp.enabled() and getattr(spec, "trace_id", 0):
            sid = _tp.new_id()
            t0 = _tp.now()
            _tp.record("direct", "send", t0, t0, spec.trace_id, sid,
                       getattr(spec, "parent_span", 0),
                       {"node": rec.node_id})
            spec.parent_span = sid
            msg["_trace"] = (spec.trace_id, sid)
        try:
            fut = conn.request_async(msg)
        except protocol.ConnectionClosed:
            spec._route = "head"
            return False
        self._direct_stats["direct_calls"] += 1
        node_id = rec.node_id
        fut.add_done_callback(
            lambda f: self._on_direct_reply(node_id, st, spec, f))
        return True

    def _on_direct_reply(self, node_id: str, st, spec, fut) -> None:
        try:
            rep = fut.result(timeout=0)
        except BaseException:
            self._direct_fail(st, spec, started=True)
            return
        if rep.get("redirect"):
            self._direct_fail(st, spec,
                              started=bool(rep.get("started")))
            return
        with st.lock:
            if st.inflight.pop(spec.task_id, None) is None:
                # recovery (node death / restart) already claimed this
                # call and re-placed or errored it: first terminal
                # wins — the late reply is dropped whole, results and
                # all, exactly like a stale-attempt NODE_TASK_DONE
                self._direct_stats["stale_replies"] += 1
                return
        self._direct_stats["direct_replies"] += 1
        error = bool(rep.get("error"))
        for stored in rep.get("inline", ()):
            self._seal_contained(stored.object_id, stored.contained_ids)
            self.store.put_stored(stored)
            self._direct_stats["inline_bytes"] += stored.nbytes
            if self.controller.unreferenced(stored.object_id):
                self._delete_everywhere(stored.object_id)
        for oid, nbytes, host_nid, contained in rep.get("located", ()):
            self._seal_contained(oid, contained)
            self.controller.add_location(oid, host_nid or node_id,
                                         nbytes)
            self.waiters.notify(oid)
        self._unpin(spec.pinned_refs)
        _mp.observe_task_done(spec, node_id)
        if _tp.enabled() and getattr(spec, "trace_id", 0):
            t1 = _tp.now()
            _tp.record("direct", "reply:" + (spec.name or ""), t1, t1,
                       spec.trace_id, _tp.new_id(),
                       getattr(spec, "parent_span", 0))
        self.controller.record_task_event(
            spec.task_id, spec.name, "FAILED" if error else "FINISHED")

    def _direct_fail(self, st, spec, started: bool) -> None:
        """A direct call NACKed (stale endpoint, fenced/disconnected
        host) or its connection died. Flip the actor to sticky head-
        routed fallback and route THIS call through the head's own
        semantics: a provably-undelivered call requeues free (the
        actor_task_undeliverable rule); an ambiguous one charges the
        retry budget (the worker-died-inflight rule). The budget is
        GATED here but not consumed: the head-routed re-execution this
        fallback hands the call to charges any subsequent loss through
        its own machinery (undeliverable requeues free, worker-death
        recovery charges) — consuming it here too double-charged one
        worker death (NACK + recovery) and errored calls that still
        had budget."""
        self._direct_stats["redirects"] += 1
        with st.lock:
            st.fallback = True
            if st.inflight.pop(spec.task_id, None) is None:
                self._direct_stats["stale_replies"] += 1
                return               # recovery already owns this call
            retry = (not started
                     or spec.retries_used < spec.max_retries)
            if retry:
                self._requeue_in_order(st, spec)
        if not retry:
            self._store_error(spec.return_ids, TaskError(
                ActorError(spec.actor_id,
                           "direct actor call failed (worker died or "
                           "endpoint fenced); no retries left"),
                task_name=spec.name))
            self._unpin(spec.pinned_refs)
            self.controller.record_task_event(
                spec.task_id, spec.name, "FAILED",
                error="direct call failed")
            return
        self._flush_actor_queue(spec.actor_id)

    def _resolve_actor_endpoint(self, actor_id: str) -> dict:
        """ACTOR_RESOLVE: the actor's direct endpoint for a remote
        caller — hosting listener address, worker id, restart epoch,
        node incarnation — or direct=False when the call must stay
        head-routed (actor pending/queued, node suspect/draining/old-
        wire, head bound to a wildcard address)."""
        from ray_tpu._private.config import CONFIG as _C
        self._direct_stats["resolves"] += 1
        if not _C.direct_actor:
            return {"direct": False, "state": "disabled"}
        rec = self.controller.get_actor(actor_id)
        if rec is None or rec.state == DEAD:
            return {"direct": False, "state": "dead",
                    "cause": (rec.death_cause if rec else
                              "unknown actor")}
        st = self._actor_states.get(actor_id)
        if st is not None:
            with st.lock:
                if st.queued or any(
                        getattr(s, "_route", "") != "direct"
                        for s in st.inflight.values()):
                    # a queued backlog or an in-flight head-routed
                    # call owns the ordering: a direct call resolved
                    # now could overtake it on the wire. Once both
                    # books are clear of head-routed work, every
                    # earlier call has EXECUTED at the host, so the
                    # caller's stream cannot reorder against them.
                    return {"direct": False, "state": "queued"}
        if rec.state != ALIVE or rec.worker_id is None:
            return {"direct": False, "state": "pending"}
        if rec.node_id in (None, self.head_node_id):
            host = self.address[0]
            if host in ("0.0.0.0", "::", ""):
                return {"direct": False, "state": "head_wildcard"}
            # worker-direct when the local worker's listener is known;
            # the head's own listener (head-as-host) otherwise
            sched = self.scheduler
            wport = (sched.direct_port_of(rec.worker_id)
                     if sched is not None else None)
            return {"direct": True, "host": host,
                    "port": int(wport or self.address[1]),
                    "worker_id": rec.worker_id,
                    "node_id": self.head_node_id,
                    "epoch": rec.num_restarts, "incarnation": None}
        node = self.cluster.get_node(rec.node_id)
        handle = node.scheduler if node else None
        conn = getattr(handle, "conn", None)
        if (node is None or not node.alive or node.suspect
                or conn is None
                or getattr(handle, "draining", False)
                or not conn.peer_speaks_direct_actor()):
            return {"direct": False, "state": "no_route"}
        addr = handle.advertise_addr
        # prefer the WORKER's own serving socket (caller -> worker ->
        # caller, no agent hop); its port rides the agent's heartbeat
        # worker rows — until a beat carries it, the agent listener
        # hosts the calls (one extra local hop, still head-free)
        wport = handle.direct_port_of(rec.worker_id)
        return {"direct": True, "host": addr[0],
                "port": int(wport or addr[1]),
                "worker_id": rec.worker_id, "node_id": rec.node_id,
                "epoch": rec.num_restarts,
                "incarnation": handle.incarnation,
                # agent-hosted because the worker's port hasn't ridden
                # a heartbeat yet: the caller may re-resolve later (at
                # a quiet moment) to upgrade to the worker's socket
                "provisional": wport is None}

    def _on_actor_task_direct(self, conn: protocol.Connection,
                              msg: dict) -> None:
        """Head-as-host: a remote caller direct-dialed the head for an
        actor living on the head node. Validate the endpoint is still
        current, forward over the worker's connection, and remember
        the caller — the worker's TASK_DONE answers it inline."""
        from ray_tpu._private import direct_actor as _da
        from ray_tpu._private.config import CONFIG as _C
        spec: ActorTaskSpec = msg["spec"]
        actor_id = msg["actor_id"]
        wid = msg["worker_id"]
        rec = self.controller.get_actor(actor_id)
        reason = None
        if not _C.direct_actor:
            reason = "disabled"
        elif (rec is None or rec.state != ALIVE
              or rec.worker_id != wid
              or rec.node_id not in (None, self.head_node_id)
              or int(msg.get("epoch", -1)) != rec.num_restarts):
            reason = "stale_endpoint"
        if reason is None:
            self._direct_pending.add(spec.task_id, conn,
                                     msg.get("rid"), wid)
            if self._send_actor_task(wid, spec):
                self._direct_stats["served"] += 1
                return
            self._direct_pending.pop(spec.task_id)
            reason = "send_failed"
        self._direct_stats["nacks"] += 1
        _da.nack(conn, msg.get("rid"), reason, False)

    def _reply_direct_done(self, ent: tuple, msg: dict,
                           results: list) -> None:
        """Head-as-host completion: results already sealed into the
        head store (the owner-side copy every getter resolves
        against); answer the dialed caller with inline copies of the
        small ones. Large results stay head-resident — the caller's
        get() falls through to the ordinary pull path."""
        from ray_tpu._private.config import CONFIG as _C
        from ray_tpu._private.object_transfer import materialize
        conn, rid, _wid = ent
        inline = []
        for stored in results:
            if (stored.nbytes <= _C.remote_inline_max_bytes
                    or stored.is_error):
                m = materialize(stored)
                inline.append(m)
                self._direct_stats["served_bytes"] += m.nbytes
        try:
            conn.reply({"rid": rid}, inline=inline, located=[],
                       error=bool(msg.get("error")),
                       error_repr=msg.get("error_repr"))
        except protocol.ConnectionClosed:
            pass          # caller died; the store keeps the results

    def _on_actor_inflight_delta(self, conn: protocol.Connection,
                                 msg: dict) -> None:
        """Coalesced direct-call mirror from a remote caller (the r16
        decref-delta pattern). Adds park the spec (and pin its args)
        so actor death/restart still errors/requeues in-flight direct
        calls; dones release pins and register holder-side result
        locations; fail entries route NACKed calls through the head's
        retry machinery. First terminal wins: a done/fail whose entry
        was already claimed (recovery ran) is dropped whole."""
        self._direct_stats["delta_frames"] += 1
        caller = msg.get("caller")
        for actor_id, spec in msg.get("adds", ()):
            self._direct_stats["delta_adds"] += 1
            with self._direct_lock:
                if spec.task_id in self._direct_done_ring:
                    # head-as-host already answered this call inline
                    # (and recorded its terminal event) before the
                    # caller's coalesced add arrived: a late add would
                    # pin args forever and park a phantom entry the
                    # next recovery sweep re-errors
                    continue
            rec = self.controller.get_actor(actor_id)
            st = self._actor_state(actor_id)
            with st.lock:
                if rec is None or rec.state == DEAD:
                    dead_cause = (rec.death_cause if rec
                                  else "unknown actor")
                else:
                    spec._direct_caller = caller
                    self._stamp_order(st, spec)
                    st.direct_inflight[spec.task_id] = spec
                    dead_cause = None
            if dead_cause is not None:
                # the caller's direct conn may be wedged on a dead
                # host; its fallback get() resolves this error
                self._store_error(spec.return_ids, TaskError(
                    ActorDiedError(actor_id,
                                   f"Actor {actor_id} is dead: "
                                   f"{dead_cause}"),
                    task_name=spec.name))
                continue
            for oid in spec.pinned_refs:
                self.controller.pin(oid)
        for ent in msg.get("dones", ()):
            self._direct_stats["delta_dones"] += 1
            self._apply_direct_done_entry(ent)

    def _apply_direct_done_entry(self, ent: dict) -> None:
        actor_id = ent["actor_id"]
        task_id = ent["task_id"]
        st = self._actor_states.get(actor_id)
        if st is None:
            return
        with st.lock:
            spec = st.direct_inflight.pop(task_id, None)
        if spec is None:
            with self._fence_lock:
                self._fence_stats["stale_attempt_drops"] += 1
            return                    # recovery already owned it
        if ent.get("retract"):
            # the caller's direct send never left its process: just
            # undo the add's pins (the caller re-submits head-routed)
            self._unpin(spec.pinned_refs)
            return
        if ent.get("failed"):
            # budget gated, not consumed — the _direct_fail rule: the
            # head-routed re-execution charges any subsequent loss
            started = bool(ent.get("started"))
            retry = (not started
                     or spec.retries_used < spec.max_retries)
            if retry:
                with st.lock:
                    self._requeue_in_order(st, spec)
                self._flush_actor_queue(actor_id)
            else:
                self._store_error(spec.return_ids, TaskError(
                    ActorError(actor_id,
                               "direct actor call failed (worker "
                               "died or endpoint fenced); no retries "
                               "left"),
                    task_name=spec.name))
                self._unpin(spec.pinned_refs)
                self.controller.record_task_event(
                    task_id, spec.name, "FAILED",
                    error="direct call failed")
            return
        for stored in ent.get("inline", ()):
            # owner-side seal of the caller's inline-replied results:
            # third parties resolve here exactly as on the head-routed
            # path — the bytes just arrived coalesced instead of per
            # call
            self._seal_contained(stored.object_id, stored.contained_ids)
            self.store.put_stored(stored)
            if self.controller.unreferenced(stored.object_id):
                self._delete_everywhere(stored.object_id)
        for oid, nbytes, host_nid, contained in ent.get("located", ()):
            self._seal_contained(oid, contained)
            if host_nid:
                self.controller.add_location(oid, host_nid, nbytes)
            self.waiters.notify(oid)
        self._unpin(spec.pinned_refs)
        located = ent.get("located") or ()
        _mp.observe_task_done(
            spec, (located[0][2] if located and located[0][2]
                   else self.head_node_id))
        state = "FAILED" if ent.get("error") else "FINISHED"
        self.controller.record_task_event(task_id, spec.name, state)

    def _drop_direct_calls_of_caller(self, worker_id: str) -> None:
        """A remote caller worker died: its mirrored direct calls can
        never send their done entries — release their pins and drop
        them (nobody is left to consume the results; the conservative
        direction, like a SIGKILLed borrower's refs)."""
        if not worker_id:
            return
        with self._actor_lock:
            states = list(self._actor_states.values())
        for st in states:
            with st.lock:
                dead = [t for t, s in st.direct_inflight.items()
                        if getattr(s, "_direct_caller", None)
                        == worker_id]
                specs = [st.direct_inflight.pop(t) for t in dead]
            for spec in specs:
                self._unpin(spec.pinned_refs)

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        rec = self.controller.get_actor(actor_id)
        if rec is None:
            return
        if no_restart:
            rec.spec.max_restarts = 0
        wid = rec.worker_id
        if wid is not None:
            sched = self._scheduler_for_worker(wid)
            if sched is not None:
                sched.kill_worker(wid)

    def cancel_task(self, object_id: str, force: bool = False) -> None:
        """Cancel a task by its return ref (reference core_worker
        CancelTask): queued tasks are removed; RUNNING tasks get
        TaskCancelledError raised in their executor thread, or their
        worker killed outright with force=True. Either way the task is
        marked non-retriable first so worker-death recovery doesn't
        resurrect it."""
        # Return ids are "<task_id>r<i>" and task ids are hex, so 'r' splits.
        task_id = object_id.split("r", 1)[0]
        for node in self.cluster.alive_nodes():
            spec = node.scheduler.cancel_pending(task_id)
            if spec is not None:
                err = TaskCancelledError(task_id)
                self._store_error(spec.return_ids, TaskError(
                    err, task_name=spec.name))
                self._unpin(spec.pinned_refs)
                self.controller.record_task_event(task_id, spec.name,
                                                  "CANCELLED")
                return
        # parked as infeasible (autoscaler may be provisioning)?
        spec = self.cluster.cancel_parked(task_id)
        if spec is not None:
            self._store_error(spec.return_ids, TaskError(
                TaskCancelledError(task_id), task_name=spec.name))
            self._unpin(spec.pinned_refs)
            self.controller.record_task_event(task_id, spec.name,
                                              "CANCELLED")
            return
        # not queued: running somewhere?
        for node in self.cluster.alive_nodes():
            hit = node.scheduler.worker_running_task(task_id)
            if hit is None:
                continue
            worker_id, spec = hit
            spec.cancelled = True        # no retry on worker death
            self.controller.record_task_event(task_id, spec.name,
                                              "CANCELLING")
            if force:
                node.scheduler.kill_worker(worker_id)
            else:
                node.scheduler.cancel_running(worker_id, task_id)
            return

    def get_actor_handle(self, name: str, namespace: str = "default"):
        actor_id = self.controller.get_named_actor(name, namespace)
        if actor_id is None:
            raise ValueError(f"No actor named {name!r} in namespace "
                             f"{namespace!r}")
        rec = self.controller.get_actor(actor_id)
        from ray_tpu.actor import ActorHandle
        import pickle as _p
        cls = _p.loads(self.controller.get_function(rec.spec.class_id))
        return ActorHandle._from_class(actor_id, cls,
                                       rec.spec.max_task_retries)

    # ---- state / introspection ----
    def kv_op(self, op: str, key: str, value: Any = None,
              namespace: str = "default", **kw) -> Any:
        """Driver-side KV access (workers reach the same store over the
        KV_OP wire message)."""
        return self._kv_dispatch({"op": op, "key": key, "value": value,
                                  "namespace": namespace, **kw})

    def state_op(self, op: str, **kwargs) -> Any:
        if op == "list_actors":
            return self.controller.list_actors()
        if op == "list_tasks":
            return self.controller.list_task_events(
                kwargs.get("limit", 1000))
        if op == "summarize_tasks":
            return self.controller.summarize_tasks()
        if op == "list_placement_groups":
            return self.cluster.pg_table()
        if op == "list_nodes":
            # the head doesn't heartbeat to itself: sample it live
            self.controller.update_host_stats(
                self.head_node_id, self.scheduler.host_stats())
            return self.controller.list_nodes()
        if op == "list_workers":
            out = []
            for n in self.cluster.alive_nodes():
                for row in n.scheduler.workers_snapshot():
                    out.append({"node_id": n.node_id, **row})
            return out
        if op == "usage_stats":
            nodes = self.controller.list_nodes()
            return {
                "uptime_s": round(time.time() - self._started_at, 1),
                "nodes_alive": sum(1 for n in nodes if n["alive"]),
                "nodes_dead": sum(1 for n in nodes if not n["alive"]),
                "total_resources": self.cluster.total_resources(),
                "available_resources":
                    self.cluster.available_resources(),
                "workers": sum(len(n.scheduler.workers_snapshot())
                               for n in self.cluster.alive_nodes()),
                "tasks": self.controller.summarize_tasks(),
                "actors": _summarize_by_state(
                    self.controller.list_actors()),
                "object_store": self.store.stats(),
            }
        if op == "cluster_resources":
            return self.cluster.total_resources()
        if op == "available_resources":
            return self.cluster.available_resources()
        if op == "scheduler_stats":
            return self.scheduler.stats()
        if op == "wire_stats":
            # head-process frame counters + which wire engine is live
            # (native read pump / writev / codec vs pure Python) — the
            # r7 frame engine's observability hook
            from ray_tpu import native
            return {**protocol.WIRE_STATS,
                    "native_frame_engine": native.frame_engine_enabled(),
                    "native_available": native.available()}
        if op == "cluster_stats":
            return self.cluster.stats()
        if op == "object_store_stats":
            return self.store.stats()
        if op == "object_plane_stats":
            return self._object_plane_stats()
        if op == "broadcast_object":
            return self.broadcast_object(kwargs["object_id"],
                                         fanout=kwargs.get("fanout"),
                                         timeout=kwargs.get("timeout"))
        if op == "trace_dump":
            return self._trace_dump(
                timeout=kwargs.get("timeout", 5.0))
        if op == "trace_stats":
            return self._trace_stats()
        if op == "metrics_dump":
            # cluster-merged registry snapshot (node/worker-labeled
            # series; the dashboard renders exposition text from it)
            return self.metrics.collect(
                timeout=kwargs.get("timeout", 3.0))
        if op == "metrics_summary":
            return self.metrics.summary(
                timeout=kwargs.get("timeout", 3.0))
        if op == "metrics_stats":
            return {"enabled": _mp.enabled(), **self.metrics.stats()}
        if op == "head_shard_stats":
            # r16 striped-table + decref-delta observability
            return {"shards": self.controller.shard_stats(),
                    "decref_delta": dict(self._decref_delta_stats)}
        if op == "liveness_stats":
            # r17 membership observability: per-node liveness state +
            # heartbeat age, incarnation table, fence/suspicion
            # counters
            return {
                **self.cluster.liveness_stats(),
                "incarnations": self.controller.incarnations(),
                "fence": dict(self._fence_stats),
            }
        if op == "direct_actor_stats":
            # r18 direct actor plane observability: head-side caller/
            # host counters, pending head-hosted direct calls, and
            # each agent's heartbeat-carried host counters
            return {
                "head": dict(self._direct_stats),
                "pending": len(self._direct_pending),
                "nodes": {
                    n.node_id: dict(getattr(n.scheduler,
                                            "direct_stats", None)
                                    or {})
                    for n in self.cluster.alive_nodes()},
            }
        if op == "head_ha_stats":
            # r15 head-HA observability: WAL bytes/records/fsync
            # latencies, snapshot age, recovery + replay-dedup counts
            if self._ha is not None:
                return self._ha.stats()
            return {"enabled": False,
                    "snapshot_path": self._snapshot_path}
        if op == "waiter_stats":
            return self.waiters.stats()
        if op == "pubsub_poll":
            return self.controller.pubsub.poll(
                kwargs["channel"], kwargs.get("cursor", 0),
                kwargs.get("timeout"))
        if op == "pubsub_publish":
            return self.controller.pubsub.publish(
                kwargs["channel"], kwargs["message"])
        if op == "record_task_events":
            self.controller.record_task_events(kwargs["events"])
            return True
        if op == "cancel_task":
            self.cancel_task(kwargs["object_id"],
                             kwargs.get("force", False))
            return True
        if op == "kill_actor":
            self.kill_actor(kwargs["actor_id"],
                            kwargs.get("no_restart", True))
            return True
        raise ValueError(f"unknown state op {op}")

    def node_resources(self) -> dict:
        return dict(self.scheduler.total)

    # ---- lifecycle ----
    def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        _mp.set_sampler("head", None)
        # each step is independent: a wedged component must not block
        # the ones after it (especially the final shm sweep)
        for step in ((lambda: (protocol._CHAOS_NET.clear()
                               if protocol._CHAOS_NET is not None
                               else None)),
                     (lambda: (self._ha.close()
                               if self._ha is not None else None)),
                     self._close_direct_conns,
                     self.cluster.shutdown, self.waiters.shutdown,
                     self.controller.pubsub.close,
                     lambda: self._restore_pool.shutdown(wait=False),
                     self._listener.close,
                     lambda: (self._poller.close()
                              if self._poller is not None else None),
                     self.store.shutdown,
                     self._sweep_orphan_segments):
            try:
                step()
            except Exception:
                log.exception("shutdown step failed")

    def _close_direct_conns(self) -> None:
        with self._direct_lock:
            conns = list(self._direct_conns.values())
            self._direct_conns.clear()
        for c in conns:
            try:
                c.close()
            except Exception:
                pass

    def _sweep_orphan_segments(self) -> None:
        """Final backstop against shm leaks: every worker/agent this
        runtime spawned is stopped by now, so any segment tagged with
        OUR session that the store didn't reclaim is an orphan from a
        killed producer (the per-death reap covers the common paths;
        this catches the rest). Only the session-tag OWNER sweeps: a
        driver started inside a job/worker of a parent session inherits
        the tag, and sweeping there would delete the parent's live
        segments."""
        from ray_tpu._private.specs import SESSION_TAG_INHERITED
        if SESSION_TAG_INHERITED:
            return
        from ray_tpu._private.object_store import sweep_session_segments
        sweep_session_segments()


# ================= module-level init/shutdown =================
def init(num_cpus: Optional[float] = None, num_tpus: Optional[float] = None,
         resources: Optional[dict] = None, max_workers: Optional[int] = None,
         namespace: str = "default",
         ignore_reinit_error: bool = False,
         bind_host: Optional[str] = None,
         port: Optional[int] = None,
         address: Optional[str] = None,
         labels: Optional[dict] = None) -> Any:
    """Start the head runtime. With bind_host="0.0.0.0" (or env
    RAY_TPU_BIND_HOST) the listener accepts remote node agents:
    `python -m ray_tpu._private.node_agent --head <host>:<port>` joins
    this cluster over TCP; rt.address carries the (host, port) to hand
    to agents. With address="host:port" this process instead CONNECTS
    to an existing head as a remote driver (the Ray Client analogue,
    ray_tpu.util.client)."""
    existing = _context.maybe_ctx()
    if existing is not None:
        if ignore_reinit_error:
            return existing  # type: ignore[return-value]
        if existing.is_driver:
            raise RuntimeError("ray_tpu.init() called twice; pass "
                               "ignore_reinit_error=True to allow this.")
        return existing  # inside a worker: init is a no-op, like ray.init
    if address is not None:
        incompatible = {k: v for k, v in {
            "num_cpus": num_cpus, "num_tpus": num_tpus,
            "resources": resources, "max_workers": max_workers,
            "bind_host": bind_host, "port": port,
            "labels": labels}.items()
            if v is not None}
        if namespace != "default":
            incompatible["namespace"] = namespace
        if incompatible:
            raise ValueError(
                f"init(address=...) connects to an EXISTING head; "
                f"{sorted(incompatible)} only apply when starting one")
        from ray_tpu.util.client import connect
        return connect(address)
    rt = Runtime(num_cpus=num_cpus, num_tpus=num_tpus, resources=resources,
                 max_workers=max_workers, namespace=namespace,
                 bind_host=bind_host, port=port, labels=labels)
    _context.set_ctx(rt)
    return rt


def shutdown() -> None:
    ctx = _context.maybe_ctx()
    if ctx is None:
        return
    if isinstance(ctx, Runtime):
        ctx.shutdown()
        _context.set_ctx(None)
        return
    # remote-driver client: disconnect (the head keeps running)
    if hasattr(ctx, "disconnect"):
        ctx.disconnect()
