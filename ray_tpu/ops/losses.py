"""Loss ops. Cross-entropy is computed in f32 with the max-subtracted
log-sum-exp; supports a vocab-sharded (tp) variant where each shard holds
a slice of the logits and the reduction runs over the mesh axis.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          mask: Optional[jax.Array] = None,
                          z_loss: float = 0.0):
    """Token-level CE. logits (..., vocab) f32/bf16; labels int (...,).

    Returns (mean_loss, per_token_loss). `mask` (same shape as labels,
    1=count) excludes padding from the mean. `z_loss` adds the standard
    logsumexp^2 regulariser (stabilises f32->bf16 logits drift).
    """
    logits = logits.astype(jnp.float32)
    # No stop_gradient on the max: the two m-terms must cancel in the
    # VJP (a half-stopped max adds a spurious one_hot(argmax) to the
    # gradient of every token).
    m = jnp.max(logits, axis=-1, keepdims=True)
    shifted = logits - m
    lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) + m[..., 0]
    label_logit = jnp.take_along_axis(
        logits, labels[..., None], axis=-1)[..., 0]
    per_token = lse - label_logit
    if z_loss:
        per_token = per_token + z_loss * jnp.square(lse)
    if mask is None:
        return jnp.mean(per_token), per_token
    mask = mask.astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.sum(per_token * mask) / denom, per_token


def sharded_softmax_cross_entropy(local_logits: jax.Array,
                                  labels: jax.Array,
                                  axis: str,
                                  vocab_shard_size: int,
                                  mask: Optional[jax.Array] = None):
    """CE when the vocab dim is sharded over mesh `axis` (inside shard_map).

    Each device holds logits[..., lo:lo+shard]; the logsumexp and the
    label-logit gather are psum-reduced so no device materialises the
    full vocab — the tp-sharded LM head never all-gathers its output.
    """
    local_logits = local_logits.astype(jnp.float32)
    lo = lax.axis_index(axis) * vocab_shard_size
    gmax = lax.pmax(jnp.max(local_logits, axis=-1), axis)
    shifted = local_logits - gmax[..., None]
    sumexp = lax.psum(jnp.sum(jnp.exp(shifted), axis=-1), axis)
    lse = jnp.log(sumexp) + gmax
    local_label = labels - lo
    in_shard = (local_label >= 0) & (local_label < vocab_shard_size)
    safe = jnp.clip(local_label, 0, vocab_shard_size - 1)
    picked = jnp.take_along_axis(local_logits, safe[..., None],
                                 axis=-1)[..., 0]
    label_logit = lax.psum(jnp.where(in_shard, picked, 0.0), axis)
    per_token = lse - label_logit
    if mask is None:
        return jnp.mean(per_token), per_token
    mask = mask.astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.sum(per_token * mask) / denom, per_token
