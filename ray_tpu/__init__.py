"""ray_tpu: a TPU-native distributed computing + ML framework.

Core API parity with the reference (python/ray/__init__.py):
``init/shutdown/remote/get/put/wait/kill/cancel/get_actor`` plus the ML
platform subpackages (``train``, ``tune``, ``data``, ``rllib``, ``serve``),
TPU-first parallelism (``parallel``), Pallas kernels (``ops``) and model
zoo (``models``). The core deliberately avoids importing jax so worker
process startup stays cheap; accelerator-touching subpackages import it
lazily.
"""
from ray_tpu._version import version as __version__  # noqa: F401
from ray_tpu._private import context as _context
from ray_tpu._private.refs import ObjectRef  # noqa: F401
from ray_tpu._private.runtime import init, shutdown  # noqa: F401
from ray_tpu.actor import ActorClass, ActorHandle  # noqa: F401
from ray_tpu.api import (cancel, available_resources,  # noqa: F401
                         broadcast, cluster_resources, get, get_actor,
                         kill, method, put, remote, wait)
from ray_tpu import exceptions  # noqa: F401


def is_initialized() -> bool:
    return _context.is_initialized()


__all__ = [
    "init", "shutdown", "is_initialized", "remote", "method", "get", "put",
    "wait", "kill", "cancel", "broadcast", "get_actor",
    "cluster_resources", "available_resources", "ObjectRef", "ActorClass",
    "ActorHandle", "exceptions", "__version__",
]
