"""Autoscaler: demand-driven node scale-up, idle-timeout scale-down.

Parity: reference autoscaler v2 (python/ray/autoscaler/v2/ —
`autoscaler.py` + `scheduler.py` bin-packing pending demand into node
types, `instance_manager/` provisioning) — re-shaped for this stack:
the provider abstraction launches in-process nodes by default (the
fake_multi_node analogue, and the honest model for one driver managing
TPU pod hosts); a real deployment implements `NodeProvider` against its
pod/VM API.

Loop (reference autoscaler.py update cycle):
  demand = queued-but-unplaceable resources + infeasible tasks
         + pending placement-group bundles
  scale UP:   first node type whose shape covers an unmet demand unit,
              respecting max_workers
  scale DOWN: non-head nodes idle (all resources free, nothing queued)
              longer than idle_timeout_s
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from ray_tpu._private.scheduler import fits as _fits_with_eps


@dataclasses.dataclass
class NodeTypeConfig:
    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10


class NodeProvider:
    """Provisioning backend. The default launches in-process nodes on
    the driver's cluster manager (tests, single-host); subclass for
    real pods/VMs (reference NodeProvider plugins)."""

    def __init__(self, cluster):
        self._cluster = cluster

    def create_node(self, node_type: NodeTypeConfig) -> str:
        rec = self._cluster.add_node(
            dict(node_type.resources),
            labels={"ray_tpu.io/node-type": node_type.name})
        return rec.node_id

    def terminate_node(self, node_id: str) -> None:
        self._cluster.remove_node(node_id, graceful=True)


class Autoscaler:
    def __init__(self, cluster, node_types: List[NodeTypeConfig],
                 provider: Optional[NodeProvider] = None,
                 idle_timeout_s: float = 60.0,
                 update_interval_s: float = 1.0):
        self._cluster = cluster
        self._types = {t.name: t for t in node_types}
        self._provider = provider or NodeProvider(cluster)
        self.idle_timeout_s = idle_timeout_s
        self._interval = update_interval_s
        self._idle_since: Dict[str, float] = {}
        self._managed: Dict[str, str] = {}   # node_id -> type name
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self.num_scale_ups = 0
        self.num_scale_downs = 0
        # launches whose node hasn't registered yet (async providers):
        # counted as planned capacity so repeated updates don't
        # re-launch for the same demand. (node_id, resources, at)
        self._in_flight_launches: List[tuple] = []
        self.provision_grace_s = 60.0
        cluster.autoscaling_enabled = True
        # type-level feasibility: demand NO node type can ever satisfy
        # is a hard error, not pending demand
        cluster.autoscaler_node_types = [dict(t.resources)
                                         for t in node_types]

    # --------------------------------------------------------- control
    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="ray-tpu-autoscaler",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        self._cluster.autoscaling_enabled = False
        self._cluster.autoscaler_node_types = []

    def _loop(self) -> None:
        import sys
        while self._running:
            try:
                self.update()
            except Exception as e:  # noqa: BLE001
                sys.stderr.write(f"ray_tpu autoscaler: update failed: "
                                 f"{e!r}\n")
            time.sleep(self._interval)

    # ---------------------------------------------------------- demand
    def _unmet_demand(self) -> List[Dict[str, float]]:
        """Resource shapes that cannot be placed on current capacity."""
        demand: List[Dict[str, float]] = []
        # Queued specs beyond each node's own availability are only
        # demand if NO other alive node could absorb them either —
        # spillback (spill_delay_s) will move them before a new node
        # could boot, so simulate placement against the other nodes'
        # effective availability before counting a shape as unmet.
        alive_nodes = self._cluster.alive_nodes()
        sim_avail = {n.node_id: dict(n.scheduler.effective_avail())
                     for n in alive_nodes}
        for node in alive_nodes:
            for shape in node.scheduler.pending_shapes():
                placed = False
                for nid, avail in sim_avail.items():
                    if nid == node.node_id:
                        continue   # pending_shapes already proved no fit
                    if self._fits(shape, avail):
                        for k, v in shape.items():
                            avail[k] = avail.get(k, 0.0) - v
                        placed = True
                        break
                if not placed:
                    demand.append(shape)
        # tasks no node fits at all
        with self._cluster._lock:
            infeasible = list(self._cluster._infeasible)
        for spec in infeasible:
            demand.append(dict(getattr(spec, "resources", None)
                               or {"CPU": 1.0}))
        # pending/rescheduling placement groups: bundles without a
        # LIVE node (node death knocks CREATED PGs into RESCHEDULING —
        # their displaced bundles are demand too)
        alive = {n.node_id for n in self._cluster.alive_nodes()}
        for pg in self._cluster.pg_table():
            if pg["state"] not in ("PENDING", "RESCHEDULING"):
                continue
            for bundle, node in zip(pg["bundles"], pg["bundle_nodes"]):
                if node is None or node not in alive:
                    demand.append(dict(bundle))
        return demand

    def _fits(self, shape: Dict[str, float],
              resources: Dict[str, float]) -> bool:
        # one feasibility definition for the whole runtime (epsilon'd):
        # scheduler.fits(avail, need)
        return _fits_with_eps(resources, shape)

    def _count_type(self, name: str) -> int:
        return sum(1 for t in self._managed.values() if t == name)

    # ---------------------------------------------------------- update
    def update(self) -> None:
        """One reconcile step (call directly in tests; the background
        loop calls it on update_interval_s)."""
        now = time.monotonic()
        alive = {n.node_id for n in self._cluster.alive_nodes()}
        # forget managed nodes that died (else a crashed node counts
        # toward max_workers forever and blocks its own replacement)
        for nid in list(self._managed):
            if nid not in alive:
                self._managed.pop(nid, None)
                self._idle_since.pop(nid, None)
        # launches leave the in-flight set once the node has
        # REGISTERED with the cluster (alive or since dead — a
        # registered-then-crashed node is dead capacity, not pending
        # capacity) or the grace window lapses
        registered = {n.node_id for n in self._cluster.nodes()}
        self._in_flight_launches = [
            (nid, res, at) for nid, res, at in self._in_flight_launches
            if nid not in registered
            and now - at < self.provision_grace_s]
        # demand NO node type can satisfy fails fast instead of parking
        self._cluster.fail_type_infeasible(
            lambda shape: any(self._fits(shape, t.resources)
                              for t in self._types.values()))
        # min_workers floors
        for t in self._types.values():
            while self._count_type(t.name) < t.min_workers:
                self._scale_up(t)
        # demand-driven scale up with planned-capacity packing: fill
        # nodes launched THIS cycle before launching more (reference
        # v2 scheduler bin-packs demand into node-type bins)
        planned: List[Dict[str, float]] = [
            dict(res) for _, res, _ in self._in_flight_launches]
        for shape in self._unmet_demand():
            placed = False
            for cap in planned:
                if self._fits(shape, cap):
                    for k, v in shape.items():
                        cap[k] = cap.get(k, 0.0) - v
                    placed = True
                    break
            if placed:
                continue
            for t in self._types.values():
                if not self._fits(shape, t.resources):
                    continue
                if self._count_type(t.name) >= t.max_workers:
                    continue
                self._scale_up(t)
                cap = dict(t.resources)
                for k, v in shape.items():
                    cap[k] = cap.get(k, 0.0) - v
                planned.append(cap)
                break
        # idle scale down
        for node in self._cluster.alive_nodes():
            nid = node.node_id
            if node.is_head or nid not in self._managed:
                continue
            if not node.scheduler.is_idle():
                self._idle_since.pop(nid, None)
                continue
            first = self._idle_since.setdefault(nid, now)
            tname = self._managed[nid]
            above_floor = (self._count_type(tname)
                           > self._types[tname].min_workers)
            if above_floor and now - first > self.idle_timeout_s:
                self._scale_down(nid)

    def _scale_up(self, t: NodeTypeConfig) -> None:
        nid = self._provider.create_node(t)
        self._managed[nid] = t.name
        self._in_flight_launches.append(
            (nid, dict(t.resources), time.monotonic()))
        self.num_scale_ups += 1

    def _scale_down(self, node_id: str) -> None:
        self._provider.terminate_node(node_id)
        self._managed.pop(node_id, None)
        self._idle_since.pop(node_id, None)
        self.num_scale_downs += 1

    def stats(self) -> Dict[str, int]:
        return {"managed_nodes": len(self._managed),
                "num_scale_ups": self.num_scale_ups,
                "num_scale_downs": self.num_scale_downs}
