"""Autoscaler: demand-driven node scale-up, idle-timeout scale-down.

Parity: reference autoscaler v2 (python/ray/autoscaler/v2/ —
`autoscaler.py` + `scheduler.py` bin-packing pending demand into node
types, `instance_manager/` provisioning) — re-shaped for this stack:
the provider abstraction launches in-process nodes by default (the
fake_multi_node analogue, and the honest model for one driver managing
TPU pod hosts); a real deployment implements `NodeProvider` against its
pod/VM API.

Loop (reference autoscaler.py update cycle):
  demand = queued-but-unplaceable resources + infeasible tasks
         + pending placement-group bundles
  scale UP:   first node type whose shape covers an unmet demand unit,
              respecting max_workers
  scale DOWN: non-head nodes idle (all resources free, nothing queued)
              longer than idle_timeout_s
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from ray_tpu._private.scheduler import fits as _fits_with_eps


@dataclasses.dataclass
class NodeTypeConfig:
    name: str
    resources: Dict[str, float]          # per HOST
    min_workers: int = 0
    max_workers: int = 10                # counted in HOSTS
    # hosts per provisioned unit: a TPU pod slice is an atomic group of
    # hosts that provisions — and terminates — together (reference TPU
    # pod types in python/ray/autoscaler/_private/gcp/)
    hosts: int = 1


class NodeProvider:
    """Provisioning backend. The default launches in-process nodes on
    the driver's cluster manager (tests, single-host); subclass for
    real pods/VMs (reference NodeProvider plugins).

    ``create_node`` may return ONE node id or a LIST (an atomic
    multi-host group, e.g. a TPU pod slice); ``group_of`` reports the
    group so scale-down only retires fully-idle groups."""

    def __init__(self, cluster):
        self._cluster = cluster

    def create_node(self, node_type: NodeTypeConfig):
        rec = self._cluster.add_node(
            dict(node_type.resources),
            labels={"ray_tpu.io/node-type": node_type.name})
        return rec.node_id

    def terminate_node(self, node_id: str) -> None:
        self._cluster.remove_node(node_id, graceful=True)

    def group_of(self, node_id: str) -> Optional[List[str]]:
        """Node ids provisioned atomically with `node_id` (a pod
        slice), or None for single-host nodes."""
        return None

    def on_preemption_notice(self, node_id: str,
                             deadline_s: Optional[float] = None) -> None:
        """Cloud preemption warning for `node_id` (GCE preemption
        notice, TPU queued-resource eviction): forward to the attached
        autoscaler's drain-before-kill path. Real providers call this
        from their metadata-watcher/eviction webhook; the node is
        drained (no new work, queued work reclaimed, trainers flush
        checkpoints) and terminated on ack or deadline — instead of
        dying mid-step and costing a lineage-resubmit storm."""
        asc = getattr(self, "_autoscaler", None)
        if asc is not None:
            asc.on_preemption_notice(node_id, deadline_s)

    def shutdown(self) -> None:
        pass


class TPUCloudAPI:
    """The cloud surface a TPU-pod provider needs, stubbed behind an
    interface so deployments plug in their GCE/queued-resources calls
    (reference python/ray/autoscaler/_private/gcp/node.py TPU path:
    tpu.projects.locations.nodes.create with acceleratorType /
    runtimeVersion, delete, list). Each slice is created with
    pre-minted node ids so the autoscaler can track the hosts before
    they register."""

    def create_slice(self, slice_name: str, node_ids: List[str],
                     node_type: NodeTypeConfig,
                     head_address: tuple) -> None:
        raise NotImplementedError

    def delete_slice(self, slice_name: str) -> None:
        raise NotImplementedError


class LocalProcessTPUCloud(TPUCloudAPI):
    """Fake cloud for tests and single-host development (reference
    fake_multi_node/node_provider.py): every slice host is a REAL
    ``node_agent`` subprocess that joins the head over TCP — the full
    registration/heartbeat/object-transfer path, just without VMs."""

    def __init__(self):
        self._slices: Dict[str, list] = {}

    def create_slice(self, slice_name: str, node_ids: List[str],
                     node_type: NodeTypeConfig,
                     head_address: tuple) -> None:
        from ray_tpu.cluster_utils import NodeAgentProcess
        res = dict(node_type.resources)
        num_cpus = float(res.pop("CPU", 2.0))
        num_tpus = float(res.pop("TPU", 0.0))
        res.pop("memory", None)
        agents = []
        for nid in node_ids:
            agents.append(NodeAgentProcess(
                head_address=head_address, num_cpus=num_cpus,
                num_tpus=num_tpus, resources=res or None,
                labels={"ray_tpu.io/node-type": node_type.name,
                        "ray_tpu.io/slice": slice_name},
                node_id=nid))
        self._slices[slice_name] = agents

    def delete_slice(self, slice_name: str) -> None:
        for agent in self._slices.pop(slice_name, []):
            agent.terminate()

    def shutdown(self) -> None:
        for name in list(self._slices):
            self.delete_slice(name)


class TPUPodProvider(NodeProvider):
    """Provisions atomic pod-slice node groups through a TPUCloudAPI.
    Hosts terminate slice-at-a-time (a TPU slice cannot lose single
    hosts), which the autoscaler honours via ``group_of``."""

    def __init__(self, cloud: TPUCloudAPI, head_address: tuple):
        self._cloud = cloud
        self._head_address = tuple(head_address)
        self._node_slice: Dict[str, str] = {}     # node_id -> slice
        self._slice_nodes: Dict[str, List[str]] = {}

    def create_node(self, node_type: NodeTypeConfig) -> List[str]:
        import uuid
        slice_name = f"{node_type.name}-{uuid.uuid4().hex[:6]}"
        node_ids = ["node_" + uuid.uuid4().hex[:8]
                    for _ in range(max(1, node_type.hosts))]
        self._cloud.create_slice(slice_name, node_ids, node_type,
                                 self._head_address)
        for nid in node_ids:
            self._node_slice[nid] = slice_name
        self._slice_nodes[slice_name] = list(node_ids)
        return node_ids

    def terminate_node(self, node_id: str) -> None:
        slice_name = self._node_slice.get(node_id)
        if slice_name is None:
            return
        for nid in self._slice_nodes.pop(slice_name, []):
            self._node_slice.pop(nid, None)
        self._cloud.delete_slice(slice_name)

    def group_of(self, node_id: str) -> Optional[List[str]]:
        slice_name = self._node_slice.get(node_id)
        if slice_name is None:
            return None
        return list(self._slice_nodes.get(slice_name, []))

    def shutdown(self) -> None:
        for slice_name in list(self._slice_nodes):
            self._cloud.delete_slice(slice_name)
        self._slice_nodes.clear()
        self._node_slice.clear()


class Autoscaler:
    def __init__(self, cluster, node_types: List[NodeTypeConfig],
                 provider: Optional[NodeProvider] = None,
                 idle_timeout_s: float = 60.0,
                 update_interval_s: float = 1.0,
                 queue_latency_source=None):
        from ray_tpu._private.config import CONFIG
        self._cluster = cluster
        self._types = {t.name: t for t in node_types}
        self._provider = provider or NodeProvider(cluster)
        self.idle_timeout_s = idle_timeout_s
        self._interval = update_interval_s
        self._idle_since: Dict[str, float] = {}
        self._managed: Dict[str, str] = {}   # node_id -> type name
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self.num_scale_ups = 0
        self.num_scale_downs = 0
        # Drain-before-kill (r14): node_id -> monotonic deadline. A
        # preemption notice drains the node (cluster stops routing to
        # it, trainers flush checkpoints); the update sweep terminates
        # it on drain-ack or deadline, whichever first. Providers reach
        # on_preemption_notice through the back-reference below.
        self._draining: Dict[str, float] = {}
        self.num_preemption_notices = 0
        self.num_drained_kills = 0
        self._provider._autoscaler = self
        # Queue-latency signal (r11, RAY_TPU_AUTOSCALE_QUEUE_LATENCY_S
        # > 0 enables): scale up when the cluster task queue-wait p95
        # over the recent window exceeds the threshold — latency-SLO
        # scaling that fires even when every queued shape technically
        # fits (resource-demand scaling can't see slow drains, only
        # unplaceable shapes). `queue_latency_source` overrides where
        # the p95 comes from (tests / external SLO pipelines); the
        # default reads the runtime's cluster metrics collector.
        self.latency_threshold_s = float(
            CONFIG.autoscale_queue_latency_s)
        self.latency_window_s = float(
            CONFIG.autoscale_queue_latency_window_s)
        self.latency_cooldown_s = float(
            CONFIG.autoscale_queue_latency_cooldown_s)
        self._latency_source = (queue_latency_source
                                or self._default_latency_source)
        # None, not 0.0: a fresh host's CLOCK_MONOTONIC can be smaller
        # than the cooldown, which would suppress the first trigger
        self._last_latency_scale_up: Optional[float] = None
        self.num_latency_scale_ups = 0
        self.last_queue_wait_p95: Optional[float] = None
        # launches whose node hasn't registered yet (async providers):
        # counted as planned capacity so repeated updates don't
        # re-launch for the same demand. (node_id, resources, at)
        self._in_flight_launches: List[tuple] = []
        # TPU slice provisioning routinely takes minutes — an expired
        # launch re-triggers for still-unmet demand, so keep this well
        # above real provisioning times (late registrations are also
        # re-adopted by label, see update()).
        self.provision_grace_s = 600.0
        # Heartbeat-derived demand (pending_shapes) lags reality by up
        # to one heartbeat period: a just-finished task can look queued
        # and trigger a spurious slice launch. Such shapes must be
        # unmet in two CONSECUTIVE updates before they scale anything;
        # head-synchronous demand (infeasible list, pending PGs) stays
        # immediate.
        self._prev_hb_demand: Dict[tuple, int] = {}
        cluster.autoscaling_enabled = True
        # type-level feasibility: demand NO node type can ever satisfy
        # is a hard error, not pending demand
        cluster.autoscaler_node_types = [dict(t.resources)
                                         for t in node_types]

    # --------------------------------------------------------- control
    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="ray-tpu-autoscaler",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        self._cluster.autoscaling_enabled = False
        self._cluster.autoscaler_node_types = []

    def _loop(self) -> None:
        import sys
        while self._running:
            try:
                self.update()
            except Exception as e:  # noqa: BLE001
                sys.stderr.write(f"ray_tpu autoscaler: update failed: "
                                 f"{e!r}\n")
            time.sleep(self._interval)

    # ---------------------------------------------------------- demand
    def _unmet_demand(self) -> List[Dict[str, float]]:
        """Resource shapes that cannot be placed on current capacity."""
        demand: List[Dict[str, float]] = []
        # Queued specs beyond each node's own availability are only
        # demand if NO other alive node could absorb them either —
        # spillback (spill_delay_s) will move them before a new node
        # could boot, so simulate placement against the other nodes'
        # effective availability before counting a shape as unmet.
        alive_nodes = self._cluster.alive_nodes()
        # draining nodes can't absorb spillback (routing skips them):
        # their capacity must not mask demand for replacement hosts.
        # SUSPECT nodes (r17 gray failure in progress) are excluded
        # for the same reason — routing skips them, so counting their
        # capacity would hide real demand exactly when a node is
        # flaking; the two-consecutive-sweep stability window below
        # keeps a sub-second blip from launching hosts.
        sim_avail = {n.node_id: dict(n.scheduler.effective_avail())
                     for n in alive_nodes
                     if not getattr(n, "draining", False)
                     and not getattr(n, "suspect", False)}
        hb_unmet: List[Dict[str, float]] = []
        for node in alive_nodes:
            for shape in node.scheduler.pending_shapes():
                placed = False
                for nid, avail in sim_avail.items():
                    if nid == node.node_id:
                        continue   # pending_shapes already proved no fit
                    if self._fits(shape, avail):
                        for k, v in shape.items():
                            avail[k] = avail.get(k, 0.0) - v
                        placed = True
                        break
                if not placed:
                    hb_unmet.append(shape)
        # stability window for the heartbeat-lagged source (see
        # _prev_hb_demand): only shapes unmet twice in a row count
        key = lambda s: tuple(sorted(s.items()))  # noqa: E731
        cur: Dict[tuple, int] = {}
        budget = dict(self._prev_hb_demand)
        for shape in hb_unmet:
            k = key(shape)
            cur[k] = cur.get(k, 0) + 1
            if budget.get(k, 0) > 0:
                budget[k] -= 1
                demand.append(shape)
        self._prev_hb_demand = cur
        # tasks no node fits at all
        with self._cluster._lock:
            infeasible = list(self._cluster._infeasible)
        for spec in infeasible:
            demand.append(dict(getattr(spec, "resources", None)
                               or {"CPU": 1.0}))
        # pending/rescheduling placement groups: bundles without a
        # LIVE node (node death knocks CREATED PGs into RESCHEDULING —
        # their displaced bundles are demand too)
        alive = {n.node_id for n in self._cluster.alive_nodes()}
        for pg in self._cluster.pg_table():
            if pg["state"] not in ("PENDING", "RESCHEDULING"):
                continue
            for bundle, node in zip(pg["bundles"], pg["bundle_nodes"]):
                if node is None or node not in alive:
                    demand.append(dict(bundle))
        return demand

    def _default_latency_source(self) -> Optional[float]:
        """Cluster queue-wait p95 from the runtime's metrics collector
        (r11 metrics plane); None when metrics are off or no tasks
        waited in the window."""
        collector = getattr(getattr(self._cluster, "_rt", None),
                            "metrics", None)
        if collector is None:
            return None
        # non-blocking: the fan-out runs on the collector's own
        # thread, so a wedged agent can never stall this reconcile
        # loop (it also drives demand scale-up and launch bookkeeping)
        return collector.queue_wait_p95(window_s=self.latency_window_s,
                                        block=False)

    def _maybe_latency_scale_up(self, now: float) -> None:
        if self.latency_threshold_s <= 0:
            return
        try:
            p95 = self._latency_source()
        except Exception:
            return                  # a broken signal must never kill
        self.last_queue_wait_p95 = p95      # the reconcile loop
        if p95 is None or p95 <= self.latency_threshold_s:
            return
        if (self._last_latency_scale_up is not None
                and now - self._last_latency_scale_up
                < self.latency_cooldown_s):
            return                  # capacity from the last trigger is
                                    # still draining the backlog
        if self._in_flight_launches:
            # a launched node can't drain anything before it
            # REGISTERS: with slow providers the p95 stays breached
            # through every cooldown window, and re-firing here would
            # march to max_workers for a backlog the pending capacity
            # already covers (the demand path packs into planned
            # capacity for the same reason)
            return
        for t in self._types.values():
            if self._count_type(t.name) + t.hosts > t.max_workers:
                continue
            self._scale_up(t)
            self.num_latency_scale_ups += 1
            self._last_latency_scale_up = now
            return

    # ------------------------------------------ preemption drain (r14)
    def on_preemption_notice(self, node_id: str,
                             deadline_s: Optional[float] = None) -> None:
        """The cloud announced `node_id` will be preempted in
        ~`deadline_s` seconds (RAY_TPU_DRAIN_DEADLINE_S when None).
        Drain-before-kill: the cluster stops leasing to it and reclaims
        its queued backlog NOW (r10 revoke machinery), a DRAINING node
        event tells elastic trainers to flush a checkpoint, and the
        update sweep releases the node once the drain is acknowledged
        or the deadline lapses — never before."""
        from ray_tpu._private.config import CONFIG
        if deadline_s is None:
            deadline_s = CONFIG.drain_deadline_s
        deadline = time.monotonic() + max(0.0, float(deadline_s))
        # a pod slice preempts ATOMICALLY: terminate_node below deletes
        # the whole group, so every member must drain now — not just
        # the host the metadata watcher named
        group = self._provider.group_of(node_id) or [node_id]
        drained_any = False
        for m in group:
            if self._cluster.drain_node(m, deadline_s=float(deadline_s)):
                drained_any = True
        if not drained_any:
            # head / unknown / already-dead node: nothing was drained,
            # so nothing may be scheduled for termination either (a
            # bogus notice must not kill an undrained node at deadline)
            return
        self._draining[node_id] = deadline
        self.num_preemption_notices += 1

    def _drain_sweep(self, now: float) -> None:
        """Terminate drained nodes (every live member acked, or the
        deadline lapsed). A group that dies DURING its drain window
        just drops out of the sweep — the normal death recovery
        already ran, and keeping the entry would wedge the reconcile
        loop on a ghost."""
        for nid, deadline in list(self._draining.items()):
            # snapshot the group BEFORE terminate_node: slice providers
            # pop their membership maps on terminate, and the members
            # must still be cleaned out of _managed afterwards
            group = self._provider.group_of(nid) or [nid]
            recs = [self._cluster.get_node(m) for m in group]
            live = [r for r in recs if r is not None and r.alive]
            if not live:
                self._draining.pop(nid, None)
                continue
            if not (now >= deadline
                    or all(getattr(r, "drain_acked", False)
                           for r in live)):
                continue
            try:
                self._provider.terminate_node(nid)
            except Exception:
                # keep the entry: the node is still alive and still
                # draining cluster-side, so dropping it here would leak
                # an unschedulable host forever — retry next cycle
                # (transient cloud-API errors are the common case)
                import sys
                sys.stderr.write(f"ray_tpu autoscaler: terminate of "
                                 f"drained node {nid} failed; will "
                                 f"retry\n")
                continue
            self._draining.pop(nid, None)
            for m in group:
                self._managed.pop(m, None)
                self._idle_since.pop(m, None)
                self._draining.pop(m, None)
            self.num_drained_kills += 1

    def _fits(self, shape: Dict[str, float],
              resources: Dict[str, float]) -> bool:
        # one feasibility definition for the whole runtime (epsilon'd):
        # scheduler.fits(avail, need)
        return _fits_with_eps(resources, shape)

    def _is_draining(self, node_id: str) -> bool:
        """Draining per THIS autoscaler's sweep or per the cluster's
        drain state (covers slice members drained alongside the keyed
        notice node)."""
        if node_id in self._draining:
            return True
        probe = getattr(self._cluster, "is_draining", None)
        return bool(probe(node_id)) if probe is not None else False

    def _count_type(self, name: str) -> int:
        # Draining nodes are capacity that is already leaving: they
        # don't count toward max_workers, so a preempted node's
        # replacement can launch BEFORE the old host is released
        # (transiently max_workers + draining hosts exist — the
        # preemption overlap, not a cap violation).
        return sum(1 for nid, t in self._managed.items()
                   if t == name and not self._is_draining(nid))

    # ---------------------------------------------------------- update
    def update(self) -> None:
        """One reconcile step (call directly in tests; the background
        loop calls it on update_interval_s)."""
        now = time.monotonic()
        # preemption drains first: a node past its window must release
        # this cycle, and dead-mid-drain entries must never wedge below
        self._drain_sweep(now)
        alive = {n.node_id for n in self._cluster.alive_nodes()}
        # launches leave the in-flight set once the node has
        # REGISTERED with the cluster (alive or since dead — a
        # registered-then-crashed node is dead capacity, not pending
        # capacity) or the grace window lapses
        registered = {n.node_id for n in self._cluster.nodes()}
        self._in_flight_launches = [
            (nid, res, at) for nid, res, at in self._in_flight_launches
            if nid not in registered
            and now - at < self.provision_grace_s]
        inflight_ids = {nid for nid, _, _ in self._in_flight_launches}
        # forget managed nodes that died (else a crashed node counts
        # toward max_workers forever and blocks its own replacement) —
        # but NOT nodes still provisioning (async providers pre-mint
        # ids that register seconds later)
        for nid in list(self._managed):
            if nid not in alive and nid not in inflight_ids:
                self._managed.pop(nid, None)
                self._idle_since.pop(nid, None)
        # adopt nodes carrying our type label that we lost track of
        # (e.g. a slice that registered after the provision grace):
        # unmanaged live nodes would never scale down
        for node in self._cluster.alive_nodes():
            if node.node_id in self._managed or node.is_head:
                continue
            tname = node.labels.get("ray_tpu.io/node-type")
            if tname in self._types:
                self._managed[node.node_id] = tname
        # demand NO node type can satisfy fails fast instead of parking
        self._cluster.fail_type_infeasible(
            lambda shape: any(self._fits(shape, t.resources)
                              for t in self._types.values()))
        # min_workers floors
        for t in self._types.values():
            while self._count_type(t.name) < t.min_workers:
                self._scale_up(t)
        # demand-driven scale up with planned-capacity packing: fill
        # nodes launched THIS cycle before launching more (reference
        # v2 scheduler bin-packs demand into node-type bins)
        planned: List[Dict[str, float]] = [
            dict(res) for _, res, _ in self._in_flight_launches]
        for shape in self._unmet_demand():
            placed = False
            for cap in planned:
                if self._fits(shape, cap):
                    for k, v in shape.items():
                        cap[k] = cap.get(k, 0.0) - v
                    placed = True
                    break
            if placed:
                continue
            for t in self._types.values():
                if not self._fits(shape, t.resources):
                    continue
                if (self._count_type(t.name) + t.hosts
                        > t.max_workers):
                    continue
                caps = self._scale_up(t)
                for k, v in shape.items():
                    caps[0][k] = caps[0].get(k, 0.0) - v
                planned.extend(caps)
                break
        # queue-latency signal: scale up when the windowed queue-wait
        # p95 breaches the SLO threshold, even though every shape fits
        self._maybe_latency_scale_up(now)
        # idle scale down (an atomic multi-host group only retires once
        # EVERY member is idle past the timeout)
        idle_map = {}
        for node in self._cluster.alive_nodes():
            nid = node.node_id
            if node.is_head or nid not in self._managed:
                continue
            if self._is_draining(nid):
                continue            # the drain sweep owns its release
            if getattr(node, "suspect", False):
                # r17: a suspect node's is_idle() view is stale by
                # definition — never retire a host mid-gray-failure
                # (if it is truly dead the death path reclaims it)
                self._idle_since.pop(nid, None)
                continue
            if not node.scheduler.is_idle():
                self._idle_since.pop(nid, None)
                idle_map[nid] = False
                continue
            first = self._idle_since.setdefault(nid, now)
            idle_map[nid] = now - first > self.idle_timeout_s
        retired: set = set()
        for nid, expired in idle_map.items():
            if not expired or nid in retired or nid not in self._managed:
                continue
            group = self._provider.group_of(nid) or [nid]
            # dead group members (a crashed slice host) count as
            # retire-ready — they can never become idle, and keeping
            # the survivors alive for them leaks the whole slice. But a
            # member still PROVISIONING (in flight, not yet registered)
            # blocks retirement: terminating mid-boot would thrash.
            if not all(idle_map.get(
                    m, m not in alive and m not in inflight_ids)
                    for m in group):
                continue
            tname = self._managed[nid]
            live_members = [m for m in group if m in self._managed]
            if (self._count_type(tname) - len(live_members)
                    < self._types[tname].min_workers):
                continue
            self._scale_down(nid)
            retired.update(group)

    def _scale_up(self, t: NodeTypeConfig) -> List[Dict[str, float]]:
        """Provision one unit of `t` (1 host, or an atomic multi-host
        slice); returns the per-host planned capacities."""
        out = self._provider.create_node(t)
        nids = [out] if isinstance(out, str) else list(out)
        now = time.monotonic()
        caps = []
        for nid in nids:
            self._managed[nid] = t.name
            self._in_flight_launches.append(
                (nid, dict(t.resources), now))
            caps.append(dict(t.resources))
        self.num_scale_ups += 1
        return caps

    def _scale_down(self, node_id: str) -> None:
        group = self._provider.group_of(node_id) or [node_id]
        self._provider.terminate_node(node_id)
        for nid in group:
            self._managed.pop(nid, None)
            self._idle_since.pop(nid, None)
        self.num_scale_downs += 1

    def stats(self) -> Dict[str, int]:
        p95 = self.last_queue_wait_p95
        if p95 == float("inf"):
            p95 = None          # keep stats() strict-JSON-valid (the
                                # raw inf still trips the trigger)
        return {"managed_nodes": len(self._managed),
                "num_scale_ups": self.num_scale_ups,
                "num_scale_downs": self.num_scale_downs,
                "num_latency_scale_ups": self.num_latency_scale_ups,
                "num_preemption_notices": self.num_preemption_notices,
                "num_drained_kills": self.num_drained_kills,
                "draining_nodes": len(self._draining),
                "last_queue_wait_p95": p95}
