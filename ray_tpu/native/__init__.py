"""Native core loader: build-on-first-use C library + ctypes bindings.

`core.c` holds the GIL-free channel wait primitive, the CRC32C used by
TFRecord IO, and the r7 wire frame engine (GIL-released socket read
pump, scatter-gather writev flush, and the hot-path Envelope codec) —
see its header comment for the reference parity map. The library is
compiled once per host with the system C compiler into
``~/.ray_tpu/native/<source-hash>.so`` (override the cache root with
``RAY_TPU_NATIVE_DIR``; extra build flags via ``RAY_TPU_NATIVE_CFLAGS``,
used by tools/native_sanity.py for sanitizer builds) and loaded via
ctypes — no pybind11/setuptools dependency, and every caller keeps a
pure-Python fallback, so a host without a compiler still works
(``RAY_TPU_DISABLE_NATIVE=1`` forces the fallbacks; the wire paths
alone can be disabled with ``RAY_TPU_WIRE_NATIVE=0``).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import shlex
import subprocess
import sys
import threading
from typing import Optional

_SRC = os.path.join(os.path.dirname(__file__), "core.c")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _cache_dir() -> str:
    return os.path.expanduser(
        os.environ.get("RAY_TPU_NATIVE_DIR", "~/.ray_tpu/native"))


def _build() -> Optional[str]:
    with open(_SRC, "rb") as f:
        src = f.read()
    extra = os.environ.get("RAY_TPU_NATIVE_CFLAGS", "")
    tag = hashlib.sha1(src + extra.encode()).hexdigest()[:16]
    out = os.path.join(_cache_dir(), f"core_{tag}.so")
    if os.path.exists(out):
        return out
    cc = os.environ.get("CC") or "cc"
    os.makedirs(_cache_dir(), exist_ok=True)
    tmp = out + f".tmp{os.getpid()}"
    # -Wall -Werror: the on-demand build is this repo's only compile
    # gate for core.c, so warnings must fail it loudly rather than ride
    # silently into every host's cache.
    cmd = [cc, "-O2", "-Wall", "-Werror", "-shared", "-fPIC",
           *shlex.split(extra), "-o", tmp, _SRC]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=60)
        if proc.returncode != 0:
            sys.stderr.write(
                f"ray_tpu: native core build failed "
                f"({' '.join(cmd)}):\n{proc.stderr}\n"
                f"falling back to pure-Python paths\n")
            return None
        os.replace(tmp, out)            # atomic vs concurrent builders
        return out
    except (OSError, subprocess.TimeoutExpired):
        return None
    finally:
        import contextlib
        with contextlib.suppress(OSError):
            os.unlink(tmp)              # failure paths leave no litter


class _IOVec(ctypes.Structure):
    _fields_ = [("iov_base", ctypes.c_void_p),
                ("iov_len", ctypes.c_size_t)]


class _EnvView(ctypes.Structure):
    _fields_ = [("version", ctypes.c_uint32),
                ("rid", ctypes.c_uint64),
                ("type_off", ctypes.c_int64),
                ("type_len", ctypes.c_int64),
                ("body_off", ctypes.c_int64),
                ("body_len", ctypes.c_int64),
                ("fields_off", ctypes.c_int64),
                ("fields_len", ctypes.c_int64),
                ("batch_off", ctypes.c_int64),
                ("batch_len", ctypes.c_int64),
                ("trace_id", ctypes.c_uint64),
                ("parent_span", ctypes.c_uint64),
                ("raw_off", ctypes.c_int64),
                ("raw_len", ctypes.c_int64)]


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("RAY_TPU_DISABLE_NATIVE"):
            return None
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.rtpu_wait_u64s_ge.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_int64]
        lib.rtpu_wait_u64s_ge.restype = ctypes.c_int
        lib.rtpu_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.rtpu_crc32c.restype = ctypes.c_uint32
        lib.rtpu_masked_crc32c.argtypes = [ctypes.c_char_p,
                                           ctypes.c_size_t]
        lib.rtpu_masked_crc32c.restype = ctypes.c_uint32
        lib.rtpu_memcpy.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_size_t]
        lib.rtpu_memcpy.restype = None
        # ---- frame engine ----
        lib.rtpu_reader_new.argtypes = [ctypes.c_uint64]
        lib.rtpu_reader_new.restype = ctypes.c_void_p
        lib.rtpu_reader_free.argtypes = [ctypes.c_void_p]
        lib.rtpu_reader_free.restype = None
        lib.rtpu_reader_pump.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.rtpu_reader_pump.restype = ctypes.c_long
        lib.rtpu_reader_pump_nb.argtypes = [ctypes.c_void_p,
                                            ctypes.c_int]
        lib.rtpu_reader_pump_nb.restype = ctypes.c_long
        # ---- epoll poller (r10) ----
        lib.rtpu_poller_new.argtypes = []
        lib.rtpu_poller_new.restype = ctypes.c_int
        lib.rtpu_poller_add.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.rtpu_poller_add.restype = ctypes.c_int
        lib.rtpu_poller_del.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.rtpu_poller_del.restype = ctypes.c_int
        lib.rtpu_poller_wait.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_int), ctypes.c_long,
            ctypes.c_int]
        lib.rtpu_poller_wait.restype = ctypes.c_long
        lib.rtpu_reader_next.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_uint64)]
        lib.rtpu_reader_next.restype = ctypes.c_void_p
        lib.rtpu_writev_full.argtypes = [ctypes.c_int,
                                         ctypes.POINTER(_IOVec),
                                         ctypes.c_long]
        lib.rtpu_writev_full.restype = ctypes.c_long
        lib.rtpu_env_decode.argtypes = [ctypes.c_char_p,
                                        ctypes.c_uint64,
                                        ctypes.POINTER(_EnvView)]
        lib.rtpu_env_decode.restype = ctypes.c_int
        lib.rtpu_batch_split.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_long]
        lib.rtpu_batch_split.restype = ctypes.c_long
        lib.rtpu_env_encode.argtypes = [
            ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64]
        lib.rtpu_env_encode.restype = ctypes.c_long
        lib.rtpu_env_encode_header.argtypes = [
            ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64]
        lib.rtpu_env_encode_header.restype = ctypes.c_long
        lib.rtpu_batch_encode.argtypes = [
            ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_long,
            ctypes.c_char_p, ctypes.c_uint64]
        lib.rtpu_batch_encode.restype = ctypes.c_long
        _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


_engine_memo: tuple = (-1, False)
_config = None


def frame_engine_enabled() -> bool:
    """Whether the wire hot path (read pump / writev flush / envelope
    codec) should go native: the library is loadable AND neither
    RAY_TPU_DISABLE_NATIVE nor RAY_TPU_WIRE_NATIVE=0 is set. Memoized
    per CONFIG generation — this runs several times per frame, so it
    must cost a dict hit, not env lookups; tests and bench A/B runs
    flip modes in-process with env var + CONFIG.reload()."""
    global _engine_memo, _config
    cfg = _config
    if cfg is None:
        from ray_tpu._private.config import CONFIG
        cfg = _config = CONFIG
    gen = cfg._gen
    memo = _engine_memo
    if memo[0] == gen:
        return memo[1]
    on = (not os.environ.get("RAY_TPU_DISABLE_NATIVE")
          and bool(cfg.wire_native) and _load() is not None)
    _engine_memo = (gen, on)
    return on


def wait_u64s_ge(mv: memoryview, offset: int, count: int, value: int,
                 timeout_s: Optional[float]) -> bool:
    """Block (GIL released) until the `count` u64 words at `offset` in
    the writable buffer `mv` are all >= value. True on success, False
    on timeout. Caller guarantees the buffer outlives the call."""
    lib = _load()
    assert lib is not None, "call native.available() first"
    base = ctypes.addressof(ctypes.c_char.from_buffer(mv, offset))
    t_ns = -1 if timeout_s is None else max(0, int(timeout_s * 1e9))
    return lib.rtpu_wait_u64s_ge(base, count, value, t_ns) == 0


def buf_copy(dst, dst_off: int, src) -> int:
    """memcpy `src` (any contiguous buffer, read-only OK) into the
    WRITABLE buffer `dst` at `dst_off`, GIL released for the whole
    copy (r12 land path: multi-MB chunk bodies go wire-view -> mapped
    shm without stalling the runtime's other threads). Caller
    guarantees both buffers outlive the call; returns bytes copied."""
    lib = _load()
    assert lib is not None, "call native.available() first"
    import numpy as _np
    s = _np.frombuffer(src, dtype=_np.uint8)
    n = s.nbytes
    if n:
        base = ctypes.addressof(ctypes.c_char.from_buffer(dst, dst_off))
        lib.rtpu_memcpy(base, s.ctypes.data, n)
    return n


def crc32c(data: bytes) -> int:
    lib = _load()
    assert lib is not None
    return int(lib.rtpu_crc32c(data, len(data)))


def masked_crc32c(data: bytes) -> int:
    lib = _load()
    assert lib is not None
    return int(lib.rtpu_masked_crc32c(data, len(data)))


# ======================= frame engine bindings =======================

class PumpClosed(Exception):
    """Read pump hit EOF: the peer closed the stream."""


class PumpOversized(Exception):
    """A frame's length prefix exceeds the max-frame sanity bound:
    corrupt (or hostile) stream."""


class FrameReader:
    """Per-connection GIL-released read pump over a dup of the socket
    fd. The dup pins the open file description so a concurrent
    ``Connection.close()`` (shutdown + close of the original fd) wakes
    the blocked read with EOF instead of racing fd reuse; the dup is
    closed here, by the owning reader thread, on exit."""

    def __init__(self, fd: int, max_frame: int):
        lib = _load()
        assert lib is not None, "check frame_engine_enabled() first"
        self._lib = lib
        self._fd = os.dup(fd)
        self._handle = lib.rtpu_reader_new(max(0, int(max_frame)))
        if not self._handle:
            os.close(self._fd)
            raise MemoryError("rtpu_reader_new failed")

    @property
    def fd(self) -> int:
        """The dup'd fd the pump reads (register THIS in a poller: it
        stays valid until close(), unlike the original, which another
        thread may close at any time)."""
        return self._fd

    def _collect(self) -> list[bytes]:
        frames = []
        length = ctypes.c_uint64()
        while True:
            ptr = self._lib.rtpu_reader_next(
                self._handle, ctypes.byref(length))
            if not ptr:
                break
            frames.append(ctypes.string_at(ptr, length.value))
        return frames

    def pump(self) -> list[bytes]:
        """Block (GIL released) until at least one complete frame is
        buffered; returns all complete frame bodies. Raises PumpClosed
        on EOF, PumpOversized on a corrupt length prefix, OSError on a
        read error."""
        n = self._lib.rtpu_reader_pump(self._handle, self._fd)
        if n > 0:
            return self._collect()
        if n == 0:
            raise PumpClosed("peer closed")
        if n == -2:
            raise PumpOversized(
                "frame length prefix exceeds wire_max_frame_bytes")
        raise OSError("native frame read failed")

    def pump_nb(self) -> list[bytes]:
        """Non-blocking pump (epoll loop): drain whatever the kernel
        has via recv(MSG_DONTWAIT) and return the complete frames
        buffered so far — [] when no complete frame is ready yet (the
        level-triggered poller re-reports the fd when more arrives).
        Raises like pump()."""
        n = self._lib.rtpu_reader_pump_nb(self._handle, self._fd)
        if n > 0:
            return self._collect()
        if n == -4:
            return []
        if n == 0:
            raise PumpClosed("peer closed")
        if n == -2:
            raise PumpOversized(
                "frame length prefix exceeds wire_max_frame_bytes")
        raise OSError("native frame read failed")

    def close(self) -> None:
        if self._handle:
            self._lib.rtpu_reader_free(self._handle)
            self._handle = None
        if self._fd >= 0:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = -1


def writev_all(fd: int, bufs: list[bytes]) -> None:
    """Write every buffer to the RAW fd as one scatter-gather flush
    (GIL released; partial writes and EINTR handled in C). Raises
    OSError with the failing errno — EAGAIN means the fd's SO_SNDTIMEO
    budget expired mid-write (stream desynced, kill the connection).
    Caller owns the fd's lifetime for the duration of the call: for
    sockets shared across threads prefer ``sock.sendmsg`` (as
    protocol._sendmsg_all does) — a raw fd captured before a
    concurrent close() can be reused by an unrelated connection."""
    lib = _load()
    assert lib is not None
    n = len(bufs)
    iov = (_IOVec * n)()
    for i, b in enumerate(bufs):
        iov[i].iov_base = ctypes.cast(ctypes.c_char_p(b), ctypes.c_void_p)
        iov[i].iov_len = len(b)
    rc = lib.rtpu_writev_full(fd, iov, n)
    if rc != 0:
        raise OSError(int(-rc), os.strerror(int(-rc)))


def env_encode(version: int, mtype: bytes, rid: int,
               body: bytes) -> bytes:
    """Serialize a Python-plane Envelope (header + opaque py_body)."""
    lib = _load()
    cap = 40 + len(mtype) + len(body)
    out = ctypes.create_string_buffer(cap)
    n = lib.rtpu_env_encode(version, mtype, len(mtype), rid,
                            body, len(body), out, cap)
    assert n >= 0, "env_encode capacity bound violated"
    return ctypes.string_at(out, n)


def env_encode_header(version: int, mtype: bytes, rid: int,
                      last_tag: int, payload_len: int) -> bytes:
    """Envelope header bytes only: the payload (py_body pickle /
    batch interior) is announced via last_tag (0x2a / 0x32; 0 = none)
    + payload_len but NOT copied — the emit path sends it as its own
    writev iovec straight from the object that produced it."""
    lib = _load()
    cap = 64 + len(mtype)
    out = ctypes.create_string_buffer(cap)
    n = lib.rtpu_env_encode_header(version, mtype, len(mtype), rid,
                                   last_tag, payload_len, out, cap)
    assert n >= 0, "env_encode_header capacity bound violated"
    return ctypes.string_at(out, n)


def env_decode(data: bytes):
    """Parse the top-level Envelope fields of `data`. Returns
    ``(version, rid, type_bytes, body_bytes|None, fields_len,
    batch_off, batch_len, trace_id, parent_span, raw|None)`` with
    fields_len = -1 / batch_off = -1 when absent and trace ids 0 when
    unset, or None when the fast parser can't handle the input (the
    caller falls back to the real protobuf codec)."""
    lib = _load()
    view = _EnvView()
    if lib.rtpu_env_decode(data, len(data), ctypes.byref(view)) != 0:
        return None
    mtype = (data[view.type_off:view.type_off + view.type_len]
             if view.type_off >= 0 else b"")
    # body (and the r12 raw bulk payload) as zero-copy views: callers
    # hand them straight to pickle.loads / the shm land path, and a
    # bytes slice would copy multi-MB pull chunks a second time on
    # every frame
    body = (memoryview(data)[view.body_off:view.body_off + view.body_len]
            if view.body_off >= 0 else None)
    raw = (memoryview(data)[view.raw_off:view.raw_off + view.raw_len]
           if view.raw_off >= 0 else None)
    return (view.version, view.rid, mtype, body,
            view.fields_len if view.fields_off >= 0 else -1,
            view.batch_off, view.batch_len,
            view.trace_id, view.parent_span, raw)


def batch_split(data: bytes, off: int, length: int):
    """Split the BatchFrame submessage at data[off:off+length] into
    absolute (offset, length) sub-Envelope views, or None on malformed
    input."""
    lib = _load()
    batch = data[off:off + length]
    cap = 128
    while True:
        offs = (ctypes.c_uint64 * cap)()
        lens = (ctypes.c_uint64 * cap)()
        n = lib.rtpu_batch_split(batch, length, offs, lens, cap)
        if n < 0:
            return None
        if n <= cap:
            return [(off + offs[i], lens[i]) for i in range(n)]
        cap = n


class EpollPoller:
    """Thin wrapper over the rtpu_poller_* epoll API (r10): one
    instance drives the read side of many connections. wait() blocks
    with the GIL released (ctypes call); add/del are callable from any
    thread while a wait is in flight (kernel epoll semantics)."""

    def __init__(self):
        lib = _load()
        assert lib is not None, "check frame_engine_enabled() first"
        self._lib = lib
        self._epfd = lib.rtpu_poller_new()
        if self._epfd < 0:
            raise OSError(-self._epfd, os.strerror(-self._epfd))

    def add(self, fd: int) -> None:
        rc = self._lib.rtpu_poller_add(self._epfd, fd)
        if rc != 0:
            raise OSError(-rc, os.strerror(-rc))

    def remove(self, fd: int) -> None:
        rc = self._lib.rtpu_poller_del(self._epfd, fd)
        if rc != 0 and rc != -9:        # EBADF: fd already closed
            raise OSError(-rc, os.strerror(-rc))

    def wait(self, timeout_ms: int, max_events: int = 64) -> list[int]:
        """Ready fd numbers ([] on timeout/EINTR)."""
        out = (ctypes.c_int * max_events)()
        n = self._lib.rtpu_poller_wait(self._epfd, out, max_events,
                                       int(timeout_ms))
        if n < 0:
            raise OSError(int(-n), os.strerror(int(-n)))
        return [out[i] for i in range(n)]

    def close(self) -> None:
        if self._epfd >= 0:
            try:
                os.close(self._epfd)
            except OSError:
                pass
            self._epfd = -1


def batch_encode(version: int, mtype: bytes,
                 subs: list[bytes]) -> bytes:
    """Assemble one BatchFrame Envelope from pre-serialized sub-
    Envelope buffers."""
    lib = _load()
    n = len(subs)
    ptrs = (ctypes.c_char_p * n)(*subs)
    lens = (ctypes.c_uint64 * n)(*[len(s) for s in subs])
    cap = 40 + len(mtype) + sum(len(s) + 11 for s in subs)
    out = ctypes.create_string_buffer(cap)
    written = lib.rtpu_batch_encode(version, mtype, len(mtype),
                                    ptrs, lens, n, out, cap)
    assert written >= 0, "batch_encode capacity bound violated"
    return ctypes.string_at(out, written)
