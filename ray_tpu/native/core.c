/* ray_tpu native core: the latency/throughput-critical leaves the
 * Python runtime can't do well while holding the GIL.
 *
 * Parity intent: the reference implements its mutable-object wait
 * loops and checksum paths in C++ (src/ray/core_worker/
 * experimental_mutable_object_manager.cc waits on futex-backed
 * semaphores; src/ray/util crc32c). Here:
 *
 *  - rtpu_wait_u64s_ge: spin/backoff until `count` contiguous
 *    little-endian u64 words are all >= value. Called through ctypes,
 *    so the GIL is RELEASED for the whole wait — the Python spin loop
 *    it replaces held the GIL between checks, actively starving the
 *    peer thread/process it was waiting on (measurably so on 1-core
 *    hosts). Used for the DAG shm-channel writer ack-gate and reader
 *    seq-gate.
 *  - rtpu_crc32c / rtpu_masked_crc32c: slice-by-8 software CRC32C
 *    (Castagnoli) with the TFRecord masking, ~GB/s vs ~MB/s for the
 *    pure-Python table loop.
 *
 *  - frame engine (r7): the wire hot path of _private/protocol.py /
 *    wire.py. rtpu_reader_* is a per-connection read pump — blocking
 *    read(2) with the GIL released, EINTR retry, length-prefix
 *    reassembly in a C-owned buffer, max-frame sanity bound — that
 *    returns one-or-more complete frames per call (the reference gets
 *    this from its C++ core-worker/raylet RPC stack; the Python
 *    recv/concat loop it replaces held the GIL for every chunk).
 *    rtpu_writev_full flushes a coalesced frame burst as ONE
 *    scatter-gather syscall with zero joined-bytes copies.
 *    rtpu_env_{encode,decode} / rtpu_batch_{encode,split} are a
 *    protobuf-wire-format fast path for the hot Envelope shape
 *    (version/type/rid varint+string header, py_body bytes, BatchFrame
 *    sub-frame offset/length views) so per-frame dispatch stops paying
 *    Python protobuf object overhead; anything they can't parse falls
 *    back to the full protobuf codec.
 *
 * Built on demand by ray_tpu/native/__init__.py with the host cc; the
 * Python fallbacks remain when no compiler is available.
 */
#include <errno.h>
#include <stdint.h>
#include <stddef.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <sched.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

static inline uint64_t now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

/* Wait until words[0..count) are all >= value.
 * timeout_ns < 0 means no deadline. Returns 0 on success, 1 on
 * timeout. Words are written by other processes with aligned stores.
 * The gate words are read with ACQUIRE ordering: passing the gate must
 * order the caller's subsequent payload reads after the writer's
 * pre-publish payload stores, or a weakly-ordered CPU (aarch64) could
 * serve stale payload bytes through a freshly-opened gate. (On x86-64
 * plain loads already have acquire semantics; the builtin costs
 * nothing there.) Writers should publish the gate word with a
 * release-ordered store — CPython's mmap slice-assign stores are plain,
 * which is the remaining theoretical gap on ARM writers; the C-side
 * acquire at least restores the documented reader-side guarantee. */
int rtpu_wait_u64s_ge(const volatile uint64_t *words, int count,
                      uint64_t value, int64_t timeout_ns) {
    uint64_t deadline = 0;
    int have_deadline = timeout_ns >= 0;
    if (have_deadline)
        deadline = now_ns() + (uint64_t)timeout_ns;
    long sleep_ns = 20000;              /* 20 us */
    int spins = 0;
    for (;;) {
        int ok = 1;
        for (int i = 0; i < count; i++) {
            if (__atomic_load_n(&words[i], __ATOMIC_ACQUIRE) < value) {
                ok = 0;
                break;
            }
        }
        if (ok)
            return 0;
        if (++spins < 2000) {
            /* hot phase: burn ~tens of µs re-checking; yield so a
             * same-core peer can make progress */
            if ((spins & 63) == 0)
                sched_yield();
            continue;
        }
        if (have_deadline && now_ns() > deadline)
            return 1;
        struct timespec ts = {0, sleep_ns};
        nanosleep(&ts, NULL);
        if (sleep_ns < 1000000)         /* cap at 1 ms */
            sleep_ns += sleep_ns / 2;
    }
}

/* ---------------- CRC32C (Castagnoli), slice-by-8 ---------------- */
static uint32_t crc_table[8][256];
static int crc_ready = 0;

/* Table init runs at library load (dlopen happens under the loader's
 * Python-side lock) — a lazy flag without barriers would race two
 * GIL-released callers on weakly-ordered CPUs. */
static void crc_init(void) __attribute__((constructor));

static void crc_init(void) {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c >> 1) ^ (0x82F63B78u & (~(c & 1) + 1));
        crc_table[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = crc_table[0][i];
        for (int t = 1; t < 8; t++) {
            c = crc_table[0][c & 0xFF] ^ (c >> 8);
            crc_table[t][i] = c;
        }
    }
    crc_ready = 1;
}

uint32_t rtpu_crc32c(const uint8_t *buf, size_t len) {
    if (!crc_ready)
        crc_init();
    uint32_t crc = 0xFFFFFFFFu;
    while (len >= 8) {
        crc ^= (uint32_t)buf[0] | ((uint32_t)buf[1] << 8)
             | ((uint32_t)buf[2] << 16) | ((uint32_t)buf[3] << 24);
        uint32_t hi = (uint32_t)buf[4] | ((uint32_t)buf[5] << 8)
                    | ((uint32_t)buf[6] << 16) | ((uint32_t)buf[7] << 24);
        crc = crc_table[7][crc & 0xFF]
            ^ crc_table[6][(crc >> 8) & 0xFF]
            ^ crc_table[5][(crc >> 16) & 0xFF]
            ^ crc_table[4][crc >> 24]
            ^ crc_table[3][hi & 0xFF]
            ^ crc_table[2][(hi >> 8) & 0xFF]
            ^ crc_table[1][(hi >> 16) & 0xFF]
            ^ crc_table[0][hi >> 24];
        buf += 8;
        len -= 8;
    }
    while (len--)
        crc = crc_table[0][(crc ^ *buf++) & 0xFF] ^ (crc >> 8);
    return crc ^ 0xFFFFFFFFu;
}

/* TFRecord framing mask. */
uint32_t rtpu_masked_crc32c(const uint8_t *buf, size_t len) {
    uint32_t crc = rtpu_crc32c(buf, len);
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}

/* GIL-released bulk copy (r12 object-plane land path): Python's
 * mv[a:b] = src holds the GIL for the whole memcpy, which at multi-MB
 * chunk sizes starves every other runtime thread (reader pumps,
 * schedulers) for milliseconds per chunk. Called through ctypes the
 * copy runs with the GIL released; the caller guarantees both buffers
 * outlive the call and the ranges do not overlap. */
void rtpu_memcpy(uint8_t *dst, const uint8_t *src, size_t n) {
    memcpy(dst, src, n);
}

/* ================== frame engine: socket read pump ==================
 *
 * Wire framing (protocol.py): every frame is an 8-byte little-endian
 * length prefix followed by that many body bytes. The reader owns a
 * growable reassembly buffer; pump() blocks in read(2) — GIL released
 * via the ctypes call — until at least one COMPLETE frame is buffered,
 * then the caller iterates frames with next(). Frame pointers stay
 * valid until the following pump() (compaction happens only there). */

typedef struct {
    uint8_t *buf;
    size_t cap;
    size_t start, end;          /* valid bytes are buf[start..end) */
    uint64_t max_frame;
} rtpu_reader;

/* pump() return codes (>0 = that many complete frames are ready) */
#define RTPU_PUMP_EOF       0   /* peer closed (clean or mid-frame)   */
#define RTPU_PUMP_ERR     (-1)  /* read(2) failed (see errno caveat)  */
#define RTPU_PUMP_TOOBIG  (-2)  /* length prefix exceeds max_frame    */
#define RTPU_PUMP_NOMEM   (-3)  /* reassembly buffer grow failed      */
#define RTPU_PUMP_AGAIN   (-4)  /* pump_nb: kernel dry, no frame yet  */

rtpu_reader *rtpu_reader_new(uint64_t max_frame) {
    rtpu_reader *r = calloc(1, sizeof *r);
    if (!r)
        return NULL;
    r->cap = 1 << 16;
    r->buf = malloc(r->cap);
    if (!r->buf) {
        free(r);
        return NULL;
    }
    r->max_frame = max_frame ? max_frame : ((uint64_t)1 << 30);
    return r;
}

void rtpu_reader_free(rtpu_reader *r) {
    if (r) {
        free(r->buf);
        free(r);
    }
}

static inline uint64_t rd_u64le(const uint8_t *p) {
    uint64_t v = 0;
    for (int i = 7; i >= 0; i--)
        v = (v << 8) | p[i];
    return v;
}

/* Count complete frames buffered from start. Frames BEFORE a corrupt
 * (oversized) prefix still count — they are dispatched first and the
 * next pump reports the corruption. */
static long rd_count(const rtpu_reader *r) {
    size_t off = r->start;
    long n = 0;
    while (r->end - off >= 8) {
        uint64_t len = rd_u64le(r->buf + off);
        if (len > r->max_frame)
            return n > 0 ? n : RTPU_PUMP_TOOBIG;
        if ((uint64_t)(r->end - off - 8) < len)
            break;
        off += 8 + (size_t)len;
        n++;
    }
    return n;
}

/* Compact + size the reassembly buffer for the next read: shrink
 * after a large-frame spike, grow toward the pending frame's length.
 * Returns 0, or RTPU_PUMP_NOMEM when a required grow failed. */
static long rd_make_room(rtpu_reader *r) {
    if (r->start > 0) {
        memmove(r->buf, r->buf + r->start, r->end - r->start);
        r->end -= r->start;
        r->start = 0;
    }
    /* shrink after a large-frame spike: steady-state control
     * frames are a few hundred bytes, so a buffer grown for one
     * multi-MB state reply must not stay pinned for the
     * connection's lifetime. Shrink when the buffered remainder
     * uses under a quarter of a >1 MiB buffer; shrink-realloc
     * failure just keeps the old buffer. */
    if (r->cap > (1 << 20) && r->end < r->cap / 4) {
        size_t ncap = 1 << 16;
        while (ncap < r->end * 2)
            ncap *= 2;
        uint8_t *nbuf = realloc(r->buf, ncap);
        if (nbuf) {
            r->buf = nbuf;
            r->cap = ncap;
        }
    }
    size_t target = r->end + (1 << 16);
    if (r->end >= 8) {
        uint64_t len = rd_u64le(r->buf);        /* <= max_frame here */
        if (8 + len > (uint64_t)target)
            target = (size_t)(8 + len);
    }
    if (r->cap < target) {
        size_t ncap = r->cap;
        while (ncap < target)
            ncap *= 2;
        uint8_t *nbuf = realloc(r->buf, ncap);
        if (!nbuf)
            return RTPU_PUMP_NOMEM;
        r->buf = nbuf;
        r->cap = ncap;
    }
    return 0;
}

long rtpu_reader_pump(rtpu_reader *r, int fd) {
    for (;;) {
        long n = rd_count(r);
        if (n != 0)
            return n;                   /* frames ready, or TOOBIG */
        if ((n = rd_make_room(r)) != 0)
            return n;
        ssize_t got = read(fd, r->buf + r->end, r->cap - r->end);
        if (got < 0) {
            if (errno == EINTR)
                continue;               /* signal delivery: retry */
            return RTPU_PUMP_ERR;
        }
        if (got == 0)
            return RTPU_PUMP_EOF;
        r->end += (size_t)got;
    }
}

/* Non-blocking pump for the epoll loop (r10): recv(MSG_DONTWAIT), so
 * the fd's own flags stay untouched — the blocking send paths share
 * the open file description and must not turn non-blocking. Drains
 * the socket until at least one complete frame is buffered or the
 * kernel runs dry; RTPU_PUMP_AGAIN means "no complete frame yet,
 * wait for the next readiness event" (level-triggered epoll re-arms
 * by itself). Sockets only — every wire connection is one. */
long rtpu_reader_pump_nb(rtpu_reader *r, int fd) {
    for (;;) {
        long n = rd_count(r);
        if (n != 0)
            return n;                   /* frames ready, or TOOBIG */
        if ((n = rd_make_room(r)) != 0)
            return n;
        ssize_t got = recv(fd, r->buf + r->end, r->cap - r->end,
                           MSG_DONTWAIT);
        if (got < 0) {
            if (errno == EINTR)
                continue;
            if (errno == EAGAIN || errno == EWOULDBLOCK)
                return RTPU_PUMP_AGAIN;
            return RTPU_PUMP_ERR;
        }
        if (got == 0)
            return RTPU_PUMP_EOF;
        r->end += (size_t)got;
    }
}

/* ------------------- epoll poller (r10 event loop) -------------------
 *
 * One epoll instance drives every registered connection's read side
 * (replacing thread-per-connection reads on the head and agents). All
 * calls arrive through ctypes, so the wait blocks with the GIL
 * released. Level-triggered: a fd whose pump left buffered kernel
 * bytes is simply reported again. Registration/removal from other
 * threads while a wait is in flight is kernel-supported. */

int rtpu_poller_new(void) {
    int fd = epoll_create1(EPOLL_CLOEXEC);
    return fd >= 0 ? fd : -errno;
}

int rtpu_poller_add(int epfd, int fd) {
    struct epoll_event ev;
    memset(&ev, 0, sizeof ev);
    ev.events = EPOLLIN | EPOLLRDHUP;   /* level-triggered */
    ev.data.fd = fd;
    return epoll_ctl(epfd, EPOLL_CTL_ADD, fd, &ev) == 0 ? 0 : -errno;
}

int rtpu_poller_del(int epfd, int fd) {
    struct epoll_event ev;               /* non-NULL for old kernels */
    memset(&ev, 0, sizeof ev);
    return epoll_ctl(epfd, EPOLL_CTL_DEL, fd, &ev) == 0 ? 0 : -errno;
}

/* Wait up to timeout_ms for readiness; fills fds[0..ret) with the
 * ready fd numbers (EPOLLIN/HUP/ERR all count — the pump surfaces
 * EOF/errors itself). 0 on timeout or EINTR; -errno on failure. */
long rtpu_poller_wait(int epfd, int *fds, long max, int timeout_ms) {
    struct epoll_event evs[64];
    int cap = max < 64 ? (int)max : 64;
    if (cap <= 0)
        return 0;
    int n = epoll_wait(epfd, evs, cap, timeout_ms);
    if (n < 0)
        return errno == EINTR ? 0 : -(long)errno;
    for (int i = 0; i < n; i++)
        fds[i] = evs[i].data.fd;
    return n;
}

/* Next complete frame body (and its length), or NULL when the buffered
 * data holds no further complete frame. Consumes the frame. */
const uint8_t *rtpu_reader_next(rtpu_reader *r, uint64_t *len_out) {
    if (r->end - r->start < 8)
        return NULL;
    uint64_t len = rd_u64le(r->buf + r->start);
    if (len > r->max_frame || (uint64_t)(r->end - r->start - 8) < len)
        return NULL;
    const uint8_t *body = r->buf + r->start + 8;
    r->start += 8 + (size_t)len;
    *len_out = len;
    return body;
}

/* ------------------- scatter-gather frame flush -------------------
 * Write EVERY byte of the iovec array (mutated in place on partial
 * writes) as few writev(2) syscalls as possible, GIL released, EINTR
 * retried. Returns 0 on success or -errno (EAGAIN = the socket's
 * SO_SNDTIMEO budget expired mid-write: the stream is desynced and the
 * caller must kill the connection, matching the sendall() contract).
 * Python runs with SIGPIPE ignored, so a dead peer is -EPIPE. */
long rtpu_writev_full(int fd, struct iovec *iov, long cnt) {
    while (cnt > 0) {
        int batch = cnt > 1024 ? 1024 : (int)cnt;   /* IOV_MAX floor */
        ssize_t wrote = writev(fd, iov, batch);
        if (wrote < 0) {
            if (errno == EINTR)
                continue;
            return -(long)errno;
        }
        size_t w = (size_t)wrote;
        while (cnt > 0 && w >= iov->iov_len) {
            w -= iov->iov_len;
            iov++;
            cnt--;
        }
        if (cnt > 0 && w > 0) {
            iov->iov_base = (uint8_t *)iov->iov_base + w;
            iov->iov_len -= w;
        }
    }
    return 0;
}

/* ================ Envelope codec (protobuf wire format) ================
 *
 * Hand-rolled encoder/decoder for the ONE message shape on the hot
 * path — ray_tpu.wire.Envelope:
 *   field 1  version  uint32   (varint,  tag 0x08)
 *   field 2  type     string   (len-del, tag 0x12)
 *   field 3  rid      uint64   (varint,  tag 0x18)
 *   field 4  fields   message  (len-del, tag 0x22)  [structural plane]
 *   field 5  py_body  bytes    (len-del, tag 0x2a)
 *   field 6  batch    message  (len-del, tag 0x32)  [BatchFrame]
 *   field 7  trace_id    fixed64 (tag 0x39)  [tracing plane, MINOR 2]
 *   field 8  parent_span fixed64 (tag 0x41)
 * BatchFrame: field 1 repeated Envelope (len-del, tag 0x0a).
 *
 * The decoder returns OFFSET/LENGTH views into the caller's buffer —
 * no allocation, no copies; unknown fields (future MINORs) are
 * skipped; anything irregular (truncated varint, duplicate submessage
 * fields whose protobuf semantics are merge-not-replace) returns -1
 * and the Python side falls back to the real protobuf parser, which
 * stays the arbiter of malformed input. */

typedef struct {
    uint32_t version;
    uint64_t rid;
    int64_t type_off, type_len;
    int64_t body_off, body_len;         /* py_body */
    int64_t fields_off, fields_len;
    int64_t batch_off, batch_len;
    uint64_t trace_id, parent_span;     /* tracing plane; 0 = unset */
    int64_t raw_off, raw_len;           /* raw bulk payload (MINOR 5) */
} rtpu_env_view;

static int pb_varint(const uint8_t *b, uint64_t len, uint64_t *pos,
                     uint64_t *out) {
    uint64_t v = 0;
    int shift = 0;
    while (*pos < len && shift < 64) {
        uint8_t c = b[(*pos)++];
        v |= (uint64_t)(c & 0x7f) << shift;
        if (!(c & 0x80)) {
            *out = v;
            return 0;
        }
        shift += 7;
    }
    return -1;
}

static int pb_skip(const uint8_t *b, uint64_t len, uint64_t *pos,
                   uint32_t wt) {
    uint64_t tmp;
    switch (wt) {
    case 0:                             /* varint */
        return pb_varint(b, len, pos, &tmp);
    case 1:                             /* fixed64 */
        if (len - *pos < 8)
            return -1;
        *pos += 8;
        return 0;
    case 2:                             /* length-delimited */
        if (pb_varint(b, len, pos, &tmp))
            return -1;
        if (len - *pos < tmp)
            return -1;
        *pos += tmp;
        return 0;
    case 5:                             /* fixed32 */
        if (len - *pos < 4)
            return -1;
        *pos += 4;
        return 0;
    default:                            /* groups: unsupported */
        return -1;
    }
}

int rtpu_env_decode(const uint8_t *buf, uint64_t len, rtpu_env_view *v) {
    memset(v, 0, sizeof *v);
    v->type_off = v->body_off = v->fields_off = v->batch_off = -1;
    v->raw_off = -1;
    uint64_t pos = 0;
    while (pos < len) {
        uint64_t tag, n;
        if (pb_varint(buf, len, &pos, &tag))
            return -1;
        uint32_t fno = (uint32_t)(tag >> 3), wt = (uint32_t)(tag & 7);
        if (fno == 1 && wt == 0) {
            if (pb_varint(buf, len, &pos, &n))
                return -1;
            v->version = (uint32_t)n;   /* uint32: truncate like upb */
        } else if (fno == 3 && wt == 0) {
            if (pb_varint(buf, len, &pos, &n))
                return -1;
            v->rid = n;
        } else if ((fno == 7 || fno == 8) && wt == 1) {
            if (len - pos < 8)
                return -1;
            uint64_t x = 0;
            for (int i = 7; i >= 0; i--)
                x = (x << 8) | buf[pos + i];
            pos += 8;
            if (fno == 7)
                v->trace_id = x;
            else
                v->parent_span = x;
        } else if ((fno == 2 || fno == 4 || fno == 5 || fno == 6
                    || fno == 9) && wt == 2) {
            if (pb_varint(buf, len, &pos, &n) || len - pos < n)
                return -1;
            int64_t *off, *fl;
            switch (fno) {
            case 2:  off = &v->type_off;   fl = &v->type_len;   break;
            case 4:  off = &v->fields_off; fl = &v->fields_len; break;
            case 5:  off = &v->body_off;   fl = &v->body_len;   break;
            case 9:  off = &v->raw_off;    fl = &v->raw_len;    break;
            default: off = &v->batch_off;  fl = &v->batch_len;  break;
            }
            /* duplicate submessage/scalar-bytes fields: protobuf
             * merge/last-wins semantics — punt to the real parser */
            if (*off >= 0)
                return -1;
            *off = (int64_t)pos;
            *fl = (int64_t)n;
            pos += n;
        } else {
            if (pb_skip(buf, len, &pos, wt))
                return -1;              /* unknown field: skip */
        }
    }
    return 0;
}

/* Split a BatchFrame submessage (bytes at buf[0..len)) into sub-
 * Envelope views. Fills up to `max` (offset, length) pairs; returns
 * the TOTAL sub-frame count (caller re-calls with bigger arrays when
 * it exceeds max) or -1 on malformed input. */
long rtpu_batch_split(const uint8_t *buf, uint64_t len,
                      uint64_t *offs, uint64_t *lens, long max) {
    uint64_t pos = 0;
    long n = 0;
    while (pos < len) {
        uint64_t tag, sub;
        if (pb_varint(buf, len, &pos, &tag))
            return -1;
        if ((tag >> 3) == 1 && (tag & 7) == 2) {
            if (pb_varint(buf, len, &pos, &sub) || len - pos < sub)
                return -1;
            if (n < max) {
                offs[n] = pos;
                lens[n] = sub;
            }
            n++;
            pos += sub;
        } else {
            if (pb_skip(buf, len, &pos, (uint32_t)(tag & 7)))
                return -1;
        }
    }
    return n;
}

static inline uint64_t varint_size(uint64_t v) {
    uint64_t n = 1;
    while (v >= 0x80) {
        v >>= 7;
        n++;
    }
    return n;
}

static inline void put_varint(uint8_t **p, uint64_t v) {
    while (v >= 0x80) {
        *(*p)++ = (uint8_t)v | 0x80;
        v >>= 7;
    }
    *(*p)++ = (uint8_t)v;
}

/* Envelope HEADER encode: every field before the trailing length-
 * delimited payload, plus (when last_tag != 0) that payload field's
 * key byte and length varint — the caller appends the payload bytes
 * itself (scatter-gather emit: the pickled body / batch interior goes
 * to writev as its own iovec, never copied into the envelope buffer).
 * last_tag is 0x2a for py_body, 0x32 for batch (emitted even with
 * payload_len 0: submessage presence), 0 for no payload field.
 * Zero-valued scalar fields are omitted, matching proto3 canonical
 * output. Returns bytes written, or -1 when cap is too small. */
long rtpu_env_encode_header(uint32_t version,
                            const uint8_t *type, uint64_t type_len,
                            uint64_t rid, uint32_t last_tag,
                            uint64_t payload_len,
                            uint8_t *out, uint64_t cap) {
    uint64_t need = 0;
    if (version)
        need += 1 + varint_size(version);
    if (type_len)
        need += 1 + varint_size(type_len) + type_len;
    if (rid)
        need += 1 + varint_size(rid);
    if (last_tag)
        need += 1 + varint_size(payload_len);
    if (need > cap)
        return -1;
    uint8_t *p = out;
    if (version) {
        *p++ = 0x08;
        put_varint(&p, version);
    }
    if (type_len) {
        *p++ = 0x12;
        put_varint(&p, type_len);
        memcpy(p, type, type_len);
        p += type_len;
    }
    if (rid) {
        *p++ = 0x18;
        put_varint(&p, rid);
    }
    if (last_tag) {
        *p++ = (uint8_t)last_tag;
        put_varint(&p, payload_len);
    }
    return (long)(p - out);
}

/* Serialize a Python-plane Envelope (header + opaque py_body). Zero-
 * valued/empty fields are omitted, matching proto3 canonical output.
 * Returns bytes written, or -1 when cap is too small. */
long rtpu_env_encode(uint32_t version,
                     const uint8_t *type, uint64_t type_len,
                     uint64_t rid,
                     const uint8_t *body, uint64_t body_len,
                     uint8_t *out, uint64_t cap) {
    long n = rtpu_env_encode_header(version, type, type_len, rid,
                                    body_len ? 0x2a : 0, body_len,
                                    out, cap);
    if (n < 0 || (uint64_t)n + body_len > cap)
        return -1;
    if (body_len)
        memcpy(out + n, body, body_len);
    return n + (long)body_len;
}

/* Serialize a BatchFrame Envelope from n pre-serialized sub-Envelope
 * buffers: one C-side assembly instead of per-frame Python protobuf
 * work. Returns bytes written, or -1 when cap is too small. */
long rtpu_batch_encode(uint32_t version,
                       const uint8_t *type, uint64_t type_len,
                       const uint8_t *const *subs,
                       const uint64_t *sub_lens, long n,
                       uint8_t *out, uint64_t cap) {
    uint64_t inner = 0;
    for (long i = 0; i < n; i++)
        inner += 1 + varint_size(sub_lens[i]) + sub_lens[i];
    uint64_t need = 1 + varint_size(inner) + inner;
    if (version)
        need += 1 + varint_size(version);
    if (type_len)
        need += 1 + varint_size(type_len) + type_len;
    if (need > cap)
        return -1;
    uint8_t *p = out;
    if (version) {
        *p++ = 0x08;
        put_varint(&p, version);
    }
    if (type_len) {
        *p++ = 0x12;
        put_varint(&p, type_len);
        memcpy(p, type, type_len);
        p += type_len;
    }
    *p++ = 0x32;
    put_varint(&p, inner);
    for (long i = 0; i < n; i++) {
        *p++ = 0x0a;
        put_varint(&p, sub_lens[i]);
        memcpy(p, subs[i], sub_lens[i]);
        p += sub_lens[i];
    }
    return (long)(p - out);
}
