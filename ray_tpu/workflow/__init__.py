"""Durable workflows: step-checkpointed task graphs.

Parity: reference python/ray/workflow (workflow_executor.py — each step
persists its result; a resumed workflow replays completed steps from
storage instead of re-executing them). Re-shaped for this stack:

- `@workflow.step` wraps a function; inside a running workflow each
  invocation is one durable unit. Step identity = call order + function
  name (deterministic workflows, the reference's contract too).
- `workflow.run(entry_fn, *args, workflow_id=..., storage=...)`
  executes the entry function; every step result is pickled under
  `<storage>/<workflow_id>/steps/`.
- `workflow.resume(workflow_id, storage=...)` re-runs the entry
  function (persisted at first run); completed steps return their
  stored results without executing, so the workflow continues from the
  first incomplete step.

Steps execute as ray_tpu tasks (isolation + retries ride the task
layer). Non-step code in the entry function re-runs on resume — keep
side effects inside steps, exactly as the reference demands.
"""
from __future__ import annotations

import contextvars
import functools
import os
import pickle
from typing import Any, Callable, Optional

import cloudpickle

import ray_tpu

_DEFAULT_STORAGE = os.path.expanduser("~/ray_tpu_workflows")

_ctx: contextvars.ContextVar[Optional["_WorkflowContext"]] = (
    contextvars.ContextVar("rtpu_workflow_ctx", default=None))


class WorkflowNotFoundError(Exception):
    pass


class _WorkflowContext:
    def __init__(self, workflow_id: str, storage: str):
        self.workflow_id = workflow_id
        self.dir = os.path.join(storage, workflow_id)
        self.steps_dir = os.path.join(self.dir, "steps")
        os.makedirs(self.steps_dir, exist_ok=True)
        self.call_index = 0
        self.num_replayed = 0
        self.num_executed = 0

    def step_path(self, name: str) -> str:
        idx = self.call_index
        self.call_index += 1
        return os.path.join(self.steps_dir, f"{idx:05d}_{name}.pkl")


class WorkflowStep:
    """A durable unit. Called inside workflow.run: executes as a task
    and persists; outside a workflow: plain call."""

    def __init__(self, fn: Callable, name: Optional[str] = None,
                 max_retries: int = 3):
        self._fn = fn
        self.name = name or fn.__name__
        self._remote = ray_tpu.remote(max_retries=max_retries)(fn)
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        ctx = _ctx.get()
        if ctx is None:
            return self._fn(*args, **kwargs)
        path = ctx.step_path(self.name)
        if os.path.exists(path):
            with open(path, "rb") as f:
                ctx.num_replayed += 1
                return pickle.load(f)["result"]
        result = ray_tpu.get(self._remote.remote(*args, **kwargs))
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump({"result": result}, f)
        os.replace(tmp, path)            # atomic: crash-safe commit
        ctx.num_executed += 1
        return result


def step(fn: Optional[Callable] = None, *, name: Optional[str] = None,
         max_retries: int = 3):
    """`@workflow.step` / `@workflow.step(name=..., max_retries=...)`."""
    if fn is not None:
        return WorkflowStep(fn)
    return lambda f: WorkflowStep(f, name=name, max_retries=max_retries)


def run(entry_fn: Callable, *args, workflow_id: str,
        storage: Optional[str] = None, **kwargs) -> Any:
    """Execute a workflow to completion; durable against re-runs."""
    storage = storage or _DEFAULT_STORAGE
    ctx = _WorkflowContext(workflow_id, storage)
    # persist the entry point + args so resume() can replay it
    entry_path = os.path.join(ctx.dir, "entry.pkl")
    if not os.path.exists(entry_path):
        with open(entry_path, "wb") as f:
            cloudpickle.dump({"fn": entry_fn, "args": args,
                              "kwargs": kwargs}, f)
    global _LAST_STATS
    token = _ctx.set(ctx)
    try:
        result = entry_fn(*args, **kwargs)
    finally:
        _ctx.reset(token)
        _LAST_STATS = {"replayed": ctx.num_replayed,
                       "executed": ctx.num_executed}
    with open(os.path.join(ctx.dir, "result.pkl"), "wb") as f:
        pickle.dump({"result": result}, f)
    return result


def resume(workflow_id: str, storage: Optional[str] = None) -> Any:
    """Re-run a workflow: finished steps replay from storage; a stored
    final result short-circuits entirely."""
    storage = storage or _DEFAULT_STORAGE
    wdir = os.path.join(storage, workflow_id)
    result_path = os.path.join(wdir, "result.pkl")
    if os.path.exists(result_path):
        with open(result_path, "rb") as f:
            return pickle.load(f)["result"]
    entry_path = os.path.join(wdir, "entry.pkl")
    if not os.path.exists(entry_path):
        raise WorkflowNotFoundError(
            f"no workflow {workflow_id!r} under {storage}")
    with open(entry_path, "rb") as f:
        entry = cloudpickle.load(f)
    return run(entry["fn"], *entry["args"], workflow_id=workflow_id,
               storage=storage, **entry["kwargs"])


def get_status(workflow_id: str,
               storage: Optional[str] = None) -> dict:
    storage = storage or _DEFAULT_STORAGE
    wdir = os.path.join(storage, workflow_id)
    if not os.path.isdir(wdir):
        raise WorkflowNotFoundError(workflow_id)
    steps = sorted(os.listdir(os.path.join(wdir, "steps")))
    return {
        "workflow_id": workflow_id,
        "finished": os.path.exists(os.path.join(wdir, "result.pkl")),
        "steps_completed": len(steps),
        "steps": steps,
    }


_LAST_STATS: dict = {}


def last_run_stats() -> dict:
    """Replay/execute counters of the most recent run/resume in this
    process (observability + tests)."""
    return dict(_LAST_STATS)
