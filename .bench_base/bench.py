"""Headline benchmark: LLM training throughput on one TPU chip.

Prints ONE JSON line: tokens/sec/chip on a ~1B-param Llama-style model
(bf16, flash-attention Pallas kernel, remat, adamw), plus achieved MFU.
`vs_baseline` is MFU / 0.35 — the reference publishes no tokens/sec
number (BASELINE.md: the 35% MFU target is the driver-supplied north
star), so >=1.0 means the target is met.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

# Peak dense bf16 FLOPs/s per chip by TPU generation.
PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def _detect_peak() -> float:
    import os
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    for key, val in PEAK_FLOPS.items():
        if key in gen:
            return val
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    if "lite" in kind:  # "TPU v5 lite" = v5e
        return PEAK_FLOPS["v5e"]
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return PEAK_FLOPS["v4"]


def main():
    import optax

    from ray_tpu.models import Transformer, TransformerConfig

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # Tuned on-chip (tools/bench_sweep.py): 1024-block flash kernels,
        # no remat (activations fit HBM at this batch), unchunked loss.
        cfg = TransformerConfig(
            vocab_size=32000, d_model=2048, n_layers=16, n_heads=16,
            n_kv_heads=16, d_ff=5632, max_seq_len=2048, remat=False,
            dtype="bfloat16", param_dtype="bfloat16", loss_chunk=0,
            attn_block_q=1024, attn_block_k=1024)
        batch, seq, steps = 2, 2048, 20
    else:  # smoke mode off-TPU
        from ray_tpu.models.config import tiny
        cfg = tiny()
        batch, seq, steps = 4, 64, 3

    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = optax.adamw(1e-4)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                cfg.vocab_size)

    def _step(p, s, batch_):
        loss, g = jax.value_and_grad(model.loss)(p, batch_)
        updates, s = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s, loss

    # donate params+opt state: avoids double-buffering ~6 GB on-chip
    train_step = jax.jit(_step, donate_argnums=(0, 1))

    # compile + warmup. float() (device_get) is the sync point:
    # block_until_ready is unreliable on tunneled TPU platforms.
    params, opt_state, loss = train_step(params, opt_state,
                                         {"tokens": tokens})
    float(loss)
    params, opt_state, loss = train_step(params, opt_state,
                                         {"tokens": tokens})
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = train_step(params, opt_state,
                                             {"tokens": tokens})
    float(loss)
    dt = time.perf_counter() - t0

    tok_per_s = batch * seq * steps / dt
    flops_per_token = cfg.flops_per_token()
    mfu = tok_per_s * flops_per_token / _detect_peak()
    print(json.dumps({
        "metric": "train_tokens_per_sec_per_chip",
        "value": round(tok_per_s, 1),
        "unit": "tok/s/chip",
        "vs_baseline": round(mfu / 0.35, 4),
        "mfu": round(mfu, 4),
        "params": cfg.num_params(),
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
