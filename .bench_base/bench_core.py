"""Core-runtime microbenchmarks: named timed scenarios.

Parity: reference python/ray/_private/ray_perf.py:120-274 (tasks/s,
actor calls/s, put/get ops/s, put GB/s, wait on many refs) — the
scalability-envelope numbers SURVEY.md §4.5(e) requires in-repo.
Run: `python bench_core.py [--json]`; results land in ENVELOPE.md via
tools/update_envelope.py or the --json line.

Numbers are for THIS host (the CI box is 1 CPU core; worker spawns are
~2s each) — they are envelope shapes, not cluster limits.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def timed(fn, n: int, *, unit: str = "ops") -> dict:
    t0 = time.perf_counter()
    fn()
    dt = time.perf_counter() - t0
    return {"n": n, "seconds": round(dt, 4),
            "per_second": round(n / dt, 1), "unit": unit}


def main(as_json: bool = False) -> dict:
    import ray_tpu
    ray_tpu.init(num_cpus=4)
    results: dict = {}

    # -------------------------------------------------- tasks / second
    @ray_tpu.remote
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(10)])        # warm pool
    N = 200
    results["tasks_sync_per_s"] = timed(
        lambda: [ray_tpu.get(nop.remote()) for _ in range(N)], N)
    results["tasks_batch_per_s"] = timed(
        lambda: ray_tpu.get([nop.remote() for _ in range(N)]), N)

    # -------------------------------------------- actor calls / second
    @ray_tpu.remote
    class A:
        def ping(self):
            return None

    a = A.remote()
    ray_tpu.get(a.ping.remote())
    results["actor_calls_sync_per_s"] = timed(
        lambda: [ray_tpu.get(a.ping.remote()) for _ in range(N)], N)
    results["actor_calls_async_per_s"] = timed(
        lambda: ray_tpu.get([a.ping.remote() for _ in range(N)]), N)
    ray_tpu.kill(a)          # scenario actors must not skew later ones

    # --------------------------------------------------- object plane
    small = np.arange(16)
    results["put_small_per_s"] = timed(
        lambda: [ray_tpu.put(small) for _ in range(N)], N)
    big = np.zeros(8 * 1024 * 1024 // 8)                  # 8 MB
    M = 40
    t0 = time.perf_counter()
    refs = [ray_tpu.put(big) for _ in range(M)]
    dt = time.perf_counter() - t0
    results["put_gbps"] = {"n": M, "seconds": round(dt, 4),
                           "per_second": round(M * 8 / 1024 / dt, 3),
                           "unit": "GB"}
    t0 = time.perf_counter()
    for r in refs:
        ray_tpu.get(r)
    dt = time.perf_counter() - t0
    results["get_gbps"] = {"n": M, "seconds": round(dt, 4),
                           "per_second": round(M * 8 / 1024 / dt, 3),
                           "unit": "GB"}

    # -------------------------------------------------- wait semantics
    K = 1000
    refs = [nop.remote() for _ in range(K)]
    t0 = time.perf_counter()
    remaining = refs
    while remaining:
        done, remaining = ray_tpu.wait(
            remaining, num_returns=min(100, len(remaining)), timeout=30)
    dt = time.perf_counter() - t0
    results["wait_1k_refs"] = {"n": K, "seconds": round(dt, 4),
                               "per_second": round(K / dt, 1),
                               "unit": "refs"}

    # --------------------------- parked waiters (event-driven core)
    # 200 concurrent gets on one unsealed object from a threaded actor:
    # the driver must hold 200 blocked requests. With the event-driven
    # waiter registry this costs ZERO driver threads (thread-per-blocked
    # -get would add 200); resolve latency is one seal -> 200 replies.
    import threading as _th

    @ray_tpu.remote(max_concurrency=200)
    class Getter:
        def fetch(self, ref):
            return ray_tpu.get(ref[0])

    g = Getter.remote()
    ray_tpu.get(g.fetch.remote([ray_tpu.put(1)]))
    from ray_tpu._private.refs import ObjectRef
    pending = ObjectRef("pending_" + "0" * 12)   # not sealed yet
    ray_tpu._private.context.get_ctx().addref(pending.object_id)
    W = 200
    threads_before = _th.active_count()
    futs = [g.fetch.remote([pending]) for _ in range(W)]
    time.sleep(1.0)                     # let all 200 gets park
    threads_parked = _th.active_count()
    t0 = time.perf_counter()
    ray_tpu._private.context.get_ctx().store.put(42, object_id=pending.object_id)
    ray_tpu.get(futs, timeout=60)
    dt = time.perf_counter() - t0
    results["parked_gets_200"] = {
        "n": W, "seconds": round(dt, 4),
        "per_second": round(W / dt, 1), "unit": "resolved",
        "driver_threads_added": threads_parked - threads_before}
    ray_tpu.kill(g)          # its 200-thread pool would drag later runs

    # --------------------------- compiled DAG: channels vs ref-wired
    # (VERDICT r3 item 8: the shm-channel fast path must beat the
    # ref-wired path on per-execute latency)
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class Hop:
        def work(self, x):
            return x

    h1, h2 = Hop.remote(), Hop.remote()
    with InputNode() as inp:
        chain = h2.work.bind(h1.work.bind(inp))
    ref_dag = chain.experimental_compile()
    for i in range(5):
        ray_tpu.get(ref_dag.execute(i))           # warm
    N_DAG = 200
    t0 = time.perf_counter()
    for i in range(N_DAG):
        ray_tpu.get(ref_dag.execute(i))
    ref_lat = (time.perf_counter() - t0) / N_DAG

    h3, h4 = Hop.remote(), Hop.remote()
    with InputNode() as inp:
        chain2 = h4.work.bind(h3.work.bind(inp))
    ch_dag = chain2.experimental_compile(enable_shm_channels=True)
    for i in range(5):
        ch_dag.execute(i).get()                   # warm
    t0 = time.perf_counter()
    for i in range(N_DAG):
        ch_dag.execute(i).get()
    ch_lat = (time.perf_counter() - t0) / N_DAG
    ch_dag.teardown()
    results["dag_2hop_execute"] = {
        "n": N_DAG, "unit": "executes",
        "refwired_ms": round(ref_lat * 1e3, 3),
        "shm_channel_ms": round(ch_lat * 1e3, 3),
        "channel_speedup": round(ref_lat / ch_lat, 2)}
    # ---------------------- device channels: raw-array hot edge
    # (VERDICT r4 item 6: jax.Array hand-off between actors without a
    # host serialize on the hot edge — raw shm frame + device_put)
    h5, h6 = Hop.remote(), Hop.remote()
    with InputNode() as inp:
        chain3 = h6.work.bind(h5.work.bind(inp))
    dev_dag = chain3.experimental_compile(enable_shm_channels=True,
                                          buffer_size_bytes=16 << 20)
    arr = np.zeros((1024, 1024), dtype=np.float32)      # 4 MB
    for _ in range(3):
        dev_dag.execute(arr).get()                      # warm
    N_DEV = 50
    t0 = time.perf_counter()
    for _ in range(N_DEV):
        out = dev_dag.execute(arr).get()
    dev_lat = (time.perf_counter() - t0) / N_DEV
    assert out.shape == arr.shape
    dev_dag.teardown()
    results["dag_device_hop"] = {
        "n": N_DEV, "unit": "executes",
        "payload_mb": round(arr.nbytes / 2 ** 20, 1),
        "per_execute_ms": round(dev_lat * 1e3, 3),
        "per_second": round(1.0 / dev_lat, 1),
        "seconds": round(dev_lat * N_DEV, 4),
        # 3 channel crossings per execute: driver->h5, h5->h6, h6->driver
        "channel_gbps_total": round(
            3 * arr.nbytes / dev_lat / 2 ** 30, 2)}

    for hop in (h1, h2, h3, h4, h5, h6):
        ray_tpu.kill(hop)
    time.sleep(0.5)          # let kills land before the queue scenarios

    # ------------------------------------------- many queued tasks
    # re-warm the worker pool first: the scenario measures queue drain
    # throughput, not worker-spawn latency after the actor kills above
    for _ in range(3):
        ray_tpu.get([nop.remote() for _ in range(30)])
    K = 5000
    t0 = time.perf_counter()
    refs = [nop.remote() for _ in range(K)]
    dt_submit = time.perf_counter() - t0
    ray_tpu.get(refs, timeout=300)
    dt_total = time.perf_counter() - t0
    results["queue_5k_tasks"] = {
        "n": K, "seconds": round(dt_total, 4),
        "submit_per_second": round(K / dt_submit, 1),
        "per_second": round(K / dt_total, 1), "unit": "tasks"}

    # ----------------------------- 100k queued: O(1) submit check
    # Submission cost must not grow with backlog depth (reference
    # envelope: 1M queued tasks per node). Chunk rates across a 100k
    # backlog expose any O(n) in enqueue/demand bookkeeping. The
    # backlog is deliberately NOT drained (that measures throughput,
    # covered above; this scenario measures submit scaling) — the
    # runtime is shut down with the queue loaded.
    CH, NCH = 10_000, 10
    chunk_rates = []
    for _ in range(NCH):
        t0 = time.perf_counter()
        for _ in range(CH):
            nop.remote()
        chunk_rates.append(round(CH / (time.perf_counter() - t0), 1))
    results["queue_100k_submit"] = {
        "n": CH * NCH, "seconds": round(
            sum(CH / r for r in chunk_rates), 4),
        "per_second": round(
            CH * NCH / sum(CH / r for r in chunk_rates), 1),
        "unit": "tasks",
        "first_chunk_per_s": chunk_rates[0],
        "last_chunk_per_s": chunk_rates[-1],
        "o1_submit": chunk_rates[-1] > 0.5 * chunk_rates[0]}

    ray_tpu.shutdown()
    if as_json:
        print(json.dumps(results))
    else:
        for name, r in results.items():
            if "per_second" in r:
                print(f"{name:28s} {r['per_second']:>12} {r['unit']}/s "
                      f"(n={r['n']}, {r.get('seconds', '?')}s)")
            else:
                extra = {k: v for k, v in r.items()
                         if k not in ("n", "unit")}
                print(f"{name:28s} {extra}")
    return results


if __name__ == "__main__":
    main(as_json="--json" in sys.argv)
