"""Metrics registry (util.metrics), config system, timeline dump."""
import numpy as np
import pytest

import ray_tpu
from ray_tpu.util.metrics import (Counter, Gauge, Histogram,
                                  MetricsRegistry, timeline)


def test_counter_gauge_tags():
    reg = MetricsRegistry()
    c = Counter("requests_total", "reqs", tag_keys=("route",),
                registry=reg)
    c.inc(tags={"route": "a"})
    c.inc(2.0, tags={"route": "a"})
    c.inc(tags={"route": "b"})
    g = Gauge("queue_len", "ql", registry=reg)
    g.set(7)
    snap = reg.collect()
    assert snap["requests_total"]["series"][(("route", "a"),)] == 3.0
    assert snap["requests_total"]["series"][(("route", "b"),)] == 1.0
    assert snap["queue_len"]["series"][()] == 7.0
    with pytest.raises(ValueError):
        c.inc(-1)
    with pytest.raises(ValueError):
        c.inc(tags={"bogus": "x"})


def test_histogram_buckets_and_prometheus_text():
    reg = MetricsRegistry()
    h = Histogram("latency_s", "lat", boundaries=(0.1, 1.0, 10.0),
                  registry=reg)
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    total, count, buckets = reg.collect()["latency_s"]["series"][()]
    assert count == 4 and abs(total - 55.55) < 1e-9
    assert dict(buckets) == {0.1: 1, 1.0: 2, 10.0: 3}
    text = reg.prometheus_text()
    assert "# TYPE latency_s histogram" in text
    assert "latency_s_count" in text
    assert 'le="+Inf"} 4' in text      # mandatory +Inf bucket == count


def test_registry_rejects_type_conflicts():
    reg = MetricsRegistry()
    Counter("m1", registry=reg)
    with pytest.raises(ValueError):
        Gauge("m1", registry=reg)
    # same type re-register is a replace, not an error
    Counter("m1", registry=reg)


def test_config_env_override(monkeypatch):
    from ray_tpu._private.config import CONFIG
    CONFIG.reload()
    assert CONFIG.heartbeat_timeout_s == 3.0
    monkeypatch.setenv("RAY_TPU_HEARTBEAT_TIMEOUT_S", "9.5")
    CONFIG.reload()
    assert CONFIG.heartbeat_timeout_s == 9.5
    monkeypatch.delenv("RAY_TPU_HEARTBEAT_TIMEOUT_S")
    CONFIG.reload()
    assert CONFIG.heartbeat_timeout_s == 3.0
    with pytest.raises(AttributeError):
        CONFIG.not_a_knob
    desc = CONFIG.describe()
    assert desc["spill_delay_s"]["env"] == "RAY_TPU_SPILL_DELAY_S"
    assert all("doc" in v for v in desc.values())


def test_timeline_dump(ray_cluster, tmp_path):
    @ray_tpu.remote
    def work(x):
        return x + 1

    ray_tpu.get([work.remote(i) for i in range(3)])
    out = tmp_path / "trace.json"
    events = timeline(str(out))
    assert out.exists()
    complete = [e for e in events if e["ph"] == "X"]
    assert len(complete) >= 3
    assert all(e["dur"] >= 0 for e in complete)
    import json
    json.load(open(out))            # valid chrome trace json
