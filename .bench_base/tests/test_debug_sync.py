"""Lock-order-inversion detector (SURVEY §5.2 race-detection parity —
the TSan-deadlock-detector analogue for the pure-Python runtime)."""
import os
import subprocess
import sys
import threading

import pytest


def _fresh_detector(monkeypatch, mode="raise"):
    monkeypatch.setenv("RAY_TPU_DEBUG_LOCKS", mode)
    from ray_tpu._private import debug_sync
    debug_sync.reset_lock_graph()
    return debug_sync


def test_inversion_detected_without_deadlock(monkeypatch):
    """A->B in one thread and B->A in another is flagged at acquisition
    time even though no actual deadlock happens this run."""
    ds = _fresh_detector(monkeypatch)
    a = ds.make_lock("A")
    b = ds.make_lock("B")

    with a:
        with b:
            pass

    err = []

    def reverse():
        try:
            with b:
                with a:
                    pass
        except ds.LockOrderInversion as e:
            err.append(e)

    t = threading.Thread(target=reverse)
    t.start()
    t.join(10)
    assert err, "reverse-order acquisition was not flagged"
    assert "lock-order inversion" in str(err[0])
    assert ds.lock_report()["inversions"]


def test_consistent_order_and_reentrancy_clean(monkeypatch):
    ds = _fresh_detector(monkeypatch)
    a = ds.make_lock("A2", reentrant=True)
    b = ds.make_lock("B2")
    for _ in range(3):
        with a:
            with a:          # reentrant: no self-edge
                with b:
                    pass
    # same order from another thread: fine
    t = threading.Thread(target=lambda: a.acquire() and (
        b.acquire(), b.release(), a.release()))
    t.start()
    t.join(10)
    assert not ds.lock_report()["inversions"]


def test_condition_wait_releases_held_stack(monkeypatch):
    """While cv.wait() sleeps, the lock must not count as held — a
    notifier taking other locks then this one is NOT an inversion."""
    ds = _fresh_detector(monkeypatch)
    lk = ds.make_lock("CVL", reentrant=True)
    cv = threading.Condition(lk)
    other = ds.make_lock("OTHER")
    ready = threading.Event()
    woke = threading.Event()

    def waiter():
        with cv:
            ready.set()
            cv.wait(10)
        woke.set()

    t = threading.Thread(target=waiter)
    t.start()
    assert ready.wait(10)
    with other:              # OTHER -> CVL order
        with cv:
            cv.notify_all()
    assert woke.wait(10)
    # reverse order CVL -> OTHER would now be an inversion; the wait
    # path above must not have produced one by itself
    assert not ds.lock_report()["inversions"]
    t.join(10)


def test_disabled_returns_plain_locks(monkeypatch):
    monkeypatch.delenv("RAY_TPU_DEBUG_LOCKS", raising=False)
    from ray_tpu._private import debug_sync
    lk = debug_sync.make_lock("X")
    assert type(lk).__name__ == "lock"          # threading.Lock


_DRIVER = r"""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
import ray_tpu
ray_tpu.init(num_cpus=4)

@ray_tpu.remote
def add(a, b):
    return a + b

@ray_tpu.remote
def outer(n):
    return sum(ray_tpu.get([add.remote(i, 1) for i in range(n)]))

@ray_tpu.remote
class C:
    def __init__(self):
        self.v = 0
    def inc(self):
        self.v += 1
        return self.v

assert ray_tpu.get([add.remote(i, i) for i in range(8)]) == [
    2 * i for i in range(8)]
assert ray_tpu.get(outer.remote(3), timeout=120) == 6
c = C.remote()
assert ray_tpu.get([c.inc.remote() for _ in range(5)]) == [1, 2, 3, 4, 5]
ref = ray_tpu.put({"x": 1})
assert ray_tpu.get(ref) == {"x": 1}
ray_tpu.shutdown()

from ray_tpu._private.debug_sync import lock_report
rep = lock_report()
print("EDGES", sum(len(v) for v in rep["edges"].values()))
print("INVERSIONS", len(rep["inversions"]))
for inv in rep["inversions"]:
    print(inv["cycle"])
"""


def test_runtime_is_inversion_free_under_detector(tmp_path):
    """Run a real driver (tasks, nested tasks, actors, objects) with
    the detector in warn mode: the exercised runtime paths must hold
    the core locks in a consistent global order."""
    script = tmp_path / "driver.py"
    script.write_text(_DRIVER)
    env = dict(os.environ)
    env["RAY_TPU_DEBUG_LOCKS"] = "warn"
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get(
        "PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, str(script)], env=env, capture_output=True,
        text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "INVERSIONS 0" in out.stdout, (out.stdout, out.stderr[-2000:])
    # the detector actually watched something
    edges = [ln for ln in out.stdout.splitlines()
             if ln.startswith("EDGES")]
    assert edges and int(edges[0].split()[1]) > 0
