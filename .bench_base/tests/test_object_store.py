"""Object store: serialization, shm, capacity/LRU spill-to-disk.

Parity target: reference plasma eviction_policy.cc (LRU) +
raylet/local_object_manager.cc (spill/restore), exercised directly on
LocalStore.
"""
import os

import numpy as np
import pytest

from ray_tpu._private.object_store import (LocalStore, deserialize,
                                           serialize)

MB = 1024 * 1024


def _big(i, mb=1):
    return np.full(mb * MB // 8, float(i))


def test_serialize_roundtrip_shm_and_inline():
    v = {"small": np.arange(10), "big": _big(7)}
    obj = serialize(v)
    assert obj.shm_names            # big buffer went to shm
    back = deserialize(obj)
    np.testing.assert_array_equal(back["big"], v["big"])
    np.testing.assert_array_equal(back["small"], v["small"])
    for name in obj.shm_names:
        from ray_tpu._private.object_store import unlink_segment
        unlink_segment(name)


def test_capacity_spills_lru_and_restores(tmp_path):
    store = LocalStore(capacity_bytes=int(2.5 * MB),
                       spill_dir=str(tmp_path / "spill"))
    ids = [store.put(_big(i)) for i in range(4)]   # 4 MB total
    stats = store.stats()
    assert stats["bytes"] <= 2.5 * MB
    assert stats["num_spilled"] >= 1
    assert stats["num_objects"] == 4               # nothing lost
    # oldest objects were chosen (LRU = insertion order here)
    spilled_files = os.listdir(tmp_path / "spill")
    assert ids[0] in spilled_files
    # restore transparently, value intact
    got = deserialize(store.get_stored(ids[0], timeout=0))
    np.testing.assert_array_equal(got, _big(0))
    store.shutdown()


def test_lru_touch_changes_spill_victim(tmp_path):
    store = LocalStore(capacity_bytes=int(2.5 * MB),
                       spill_dir=str(tmp_path / "s"))
    a = store.put(_big(1))
    b = store.put(_big(2))
    store.get_stored(a, timeout=0)        # touch a: b becomes LRU
    c = store.put(_big(3))
    assert b in store._spilled
    assert a not in store._spilled
    store.shutdown()


def test_pinned_objects_never_spill(tmp_path):
    pinned = set()
    store = LocalStore(capacity_bytes=int(1.5 * MB),
                       spill_dir=str(tmp_path / "s"),
                       pinned_fn=lambda: pinned)
    a = store.put(_big(1))
    pinned.add(a)
    b = store.put(_big(2))
    c = store.put(_big(3))
    assert a not in store._spilled        # pinned survived the pressure
    assert a in store._objects
    store.shutdown()


def test_delete_spilled_removes_file(tmp_path):
    store = LocalStore(capacity_bytes=MB, spill_dir=str(tmp_path / "s"))
    a = store.put(_big(1))
    b = store.put(_big(2))               # a spills
    assert a in store._spilled
    path = store._spilled[a].path
    assert os.path.exists(path)
    store.delete(a)
    assert not os.path.exists(path)
    assert not store.contains(a)
    store.shutdown()


def test_unbounded_store_never_spills(tmp_path):
    store = LocalStore(spill_dir=str(tmp_path / "s"))
    for i in range(5):
        store.put(_big(i))
    assert store.stats()["num_spilled"] == 0
    store.shutdown()


def test_reap_object_segments_cleans_orphans():
    """A worker killed between sealing result shm and delivering
    TASK_DONE leaves orphan segments named rtpu_<return_id>_<i>; the
    driver reaps them when it records the task's failure."""
    import _posixshmem

    from ray_tpu._private.object_store import (_create_segment,
                                               _local_tag,
                                               reap_object_segments)
    rid = "deadbeef01r0"
    tag = _local_tag()
    for i in range(3):
        _create_segment(f"rtpu_{tag}_{rid}_{i}", memoryview(b"x" * 128))
    assert reap_object_segments(rid) == 3
    # gone — and reaping again is a no-op
    assert reap_object_segments(rid) == 0
    with pytest.raises(FileNotFoundError):
        _posixshmem.shm_open(f"/rtpu_{tag}_{rid}_0", 0, mode=0o600)
