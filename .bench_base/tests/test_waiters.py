"""Event-driven waiter core: registry semantics + no-thread parking.

Covers the replacement of thread-per-blocked-get (reference model:
raylet wait_manager.cc notification-driven waits)."""
import threading
import time

import pytest

from ray_tpu._private.waiters import WaiterRegistry


def test_get_waiter_resolves_on_notify():
    present = set()
    reg = WaiterRegistry(lambda o: o in present)
    hits = []
    reg.add_get("a", lambda w, to: hits.append(("a", to)), timeout=5)
    assert hits == []
    present.add("a")
    reg.notify("a")
    assert hits == [("a", False)]
    reg.shutdown()


def test_get_waiter_timeout():
    reg = WaiterRegistry(lambda o: False)
    hits = []
    reg.add_get("x", lambda w, to: hits.append(to), timeout=0.1)
    deadline = time.time() + 3
    while not hits and time.time() < deadline:
        time.sleep(0.01)
    assert hits == [True]
    reg.shutdown()


def test_get_waiter_immediate_when_present():
    reg = WaiterRegistry(lambda o: True)
    hits = []
    reg.add_get("y", lambda w, to: hits.append(to), timeout=None)
    assert hits == [False]          # resolved synchronously
    assert reg.stats()["watched_ids"] == 0
    reg.shutdown()


def test_wait_waiter_threshold_and_order():
    present = set()
    reg = WaiterRegistry(lambda o: o in present)
    out = []
    reg.add_wait(["a", "b", "c"], 2, lambda w, r: out.append(r),
                 timeout=5)
    present.add("c")
    reg.notify("c")
    assert out == []                # 1 of 2
    present.add("a")
    reg.notify("a")
    assert out == [["a", "c"]]      # input order preserved
    reg.shutdown()


def test_wait_timeout_returns_partial():
    present = {"b"}
    reg = WaiterRegistry(lambda o: o in present)
    out = []
    reg.add_wait(["a", "b"], 2, lambda w, r: out.append(r), timeout=0.1)
    deadline = time.time() + 3
    while not out and time.time() < deadline:
        time.sleep(0.01)
    assert out == [["b"]]
    reg.shutdown()


def test_on_done_called_once():
    present = set()
    reg = WaiterRegistry(lambda o: o in present)
    done = []
    reg.add_get("z", lambda w, to: None, timeout=0.1,
                on_done=lambda: done.append(1))
    deadline = time.time() + 3
    while not done and time.time() < deadline:
        time.sleep(0.01)
    present.add("z")
    reg.notify("z")                 # late notify must not re-fire
    assert done == [1]
    reg.shutdown()


def test_parked_gets_add_no_driver_threads(rt):
    """20 worker-side gets blocked on one unsealed object must park in
    the registry, not in driver threads; sealing resolves all."""
    import ray_tpu
    from ray_tpu._private import context
    from ray_tpu._private.refs import ObjectRef

    @ray_tpu.remote(max_concurrency=20)
    class Getter:
        def fetch(self, box):
            return ray_tpu.get(box[0]) + 1

    g = Getter.remote()
    assert ray_tpu.get(g.fetch.remote([ray_tpu.put(0)])) == 1

    ctx = context.get_ctx()
    pending = ObjectRef("pend_" + "0" * 15)
    ctx.addref(pending.object_id)
    futs = [g.fetch.remote([pending]) for _ in range(20)]
    deadline = time.time() + 10
    while ctx.waiters.stats()["watched_ids"] == 0 and time.time() < deadline:
        time.sleep(0.05)
    before = threading.active_count()
    time.sleep(0.3)
    assert threading.active_count() <= before   # no per-get threads
    ctx.store.put(41, object_id=pending.object_id)
    assert ray_tpu.get(futs, timeout=30) == [42] * 20
    assert ctx.waiters.stats()["watched_ids"] == 0


@pytest.fixture
def rt():
    import ray_tpu
    if ray_tpu.is_initialized():       # one runtime per process
        ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()
