"""Mesh/sharding/collective tests on the virtual 8-device CPU platform."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

from ray_tpu.parallel import (MeshSpec, prepare_mesh, collectives,
                              logical_sharding, param_shardings,
                              shard_pytree, with_logical_constraint)
from ray_tpu.parallel.sharding import logical_spec


def test_mesh_resolve_wildcard():
    assert MeshSpec(dp=-1, tp=2).resolve(8) == (1, 4, 1, 1, 1, 2)
    assert MeshSpec(dp=2, fsdp=2, tp=2).resolve(8) == (1, 2, 2, 1, 1, 2)
    with pytest.raises(ValueError):
        MeshSpec(dp=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(dp=-1, fsdp=-1).resolve(8)


def test_prepare_mesh_axes():
    mesh = prepare_mesh(dp=4, tp=2)
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2
    assert mesh.devices.size == 8


def test_logical_spec_drops_trivial_axes():
    mesh = prepare_mesh(dp=8)
    # tp has size 1 -> mlp axis replicates
    assert logical_spec(("embed", "mlp"), mesh=mesh) == P(None, None)
    assert logical_spec(("batch", "seq"), mesh=mesh) == P("dp", None)


def test_param_shardings_and_placement():
    mesh = prepare_mesh(dp=2, fsdp=2, tp=2)
    logical = {"w": ("embed", "mlp"), "b": ("mlp",)}
    sh = param_shardings(mesh, logical)
    assert isinstance(sh["w"], NamedSharding)
    assert sh["w"].spec == P("fsdp", "tp")
    params = {"w": np.ones((8, 16), np.float32), "b": np.zeros(16, np.float32)}
    placed = shard_pytree(params, sh)
    assert placed["w"].sharding.spec == P("fsdp", "tp")
    np.testing.assert_allclose(np.asarray(placed["w"]), params["w"])


def test_collectives_in_shard_map():
    mesh = prepare_mesh(dp=8)
    x = jnp.arange(8.0)

    def body(x):
        s = collectives.allreduce(x, "dp")
        g = collectives.allgather(x, "dp")
        r = collectives.ppermute_ring(x, "dp", shift=1)
        b = collectives.broadcast(x, "dp", root=3)
        return s, g, r, b

    f = shard_map(body, mesh=mesh,
                  in_specs=P("dp"),
                  out_specs=(P("dp"), P(), P("dp"), P("dp")),
                  check_vma=False)
    s, g, r, b = jax.jit(f)(x)
    np.testing.assert_allclose(np.asarray(s), np.full(8, 28.0))
    np.testing.assert_allclose(np.asarray(g), np.arange(8.0))
    # ring shift: device i receives from i-1 (src i sends to i+1)
    np.testing.assert_allclose(np.asarray(r), np.roll(np.arange(8.0), 1))
    np.testing.assert_allclose(np.asarray(b), np.full(8, 3.0))


def test_reducescatter():
    mesh = prepare_mesh(dp=8)
    x = jnp.arange(64.0)

    f = shard_map(lambda x: collectives.reducescatter(x, "dp"),
                  mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    out = jax.jit(f)(x)
    assert out.shape == (8,)
    # element d = sum_k x[8k + d] = 8*28 + 8d
    np.testing.assert_allclose(np.asarray(out), 224.0 + 8.0 * np.arange(8))


def test_with_logical_constraint_in_jit():
    mesh = prepare_mesh(dp=4, tp=2)

    @jax.jit
    def f(x):
        return with_logical_constraint(x * 2, ("batch", "mlp"), mesh=mesh)

    x = jnp.ones((8, 4))
    out = f(x)
    assert out.sharding.spec == P(("dp",), "tp") or out.sharding.spec == P("dp", "tp")


def test_broadcast_ignores_nonroot_nan():
    mesh = prepare_mesh(dp=8)
    x = jnp.arange(8.0).at[5].set(jnp.nan)
    f = shard_map(lambda x: collectives.broadcast(x, "dp", root=3),
                  mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    np.testing.assert_allclose(np.asarray(jax.jit(f)(x)), np.full(8, 3.0))


def test_send_recv_nonparticipants_keep_buffers():
    mesh = prepare_mesh(dp=8)
    x = jnp.arange(10.0, 18.0)
    f = shard_map(lambda x: collectives.send_recv(x, "dp", [(0, 1)]),
                  mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    expect = np.arange(10.0, 18.0)
    expect[1] = 10.0
    np.testing.assert_allclose(np.asarray(jax.jit(f)(x)), expect)


def test_barrier_threads_value():
    mesh = prepare_mesh(dp=8)
    x = jnp.arange(8.0)
    f = shard_map(lambda x: collectives.barrier("dp", x),
                  mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    out = jax.jit(f)(x)
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0))
    hlo = jax.jit(f).lower(x).compile().as_text()
    assert "all-reduce" in hlo  # fence not dead-code-eliminated


def test_unknown_logical_axis_raises():
    with pytest.raises(ValueError, match="unknown logical axis"):
        logical_spec(("embd",))


def test_all_to_all_ulysses():
    # seq-sharded -> head-sharded re-layout, the Ulysses primitive.
    mesh = prepare_mesh(sp=8)
    x = jnp.arange(8 * 16 * 4.0).reshape(8, 16, 4)  # (seq, heads, d)

    def body(x):  # local (1, 16, 4) -> (8, 2, 4)
        return collectives.all_to_all(x, "sp", split_dim=1, concat_dim=0)

    f = shard_map(body, mesh=mesh, in_specs=P("sp", None, None),
                  out_specs=P(None, "sp", None))
    out = jax.jit(f)(x)
    assert out.shape == (8, 16, 4)
    # content preserved under permutation of (seq, head) blocks
    np.testing.assert_allclose(np.sort(np.asarray(out).ravel()),
                               np.sort(np.asarray(x).ravel()))


# ---------------------------------------------------------- hybrid DCN mesh
def test_split_hybrid_factors_outer_axis():
    from ray_tpu.parallel.mesh import _split_hybrid
    # (pp, dp, fsdp, sp, ep, tp) = (1, 4, 2, 1, 1, 1), 2 slices of 4.
    dcn, ici = _split_hybrid((1, 4, 2, 1, 1, 1), 2, 4)
    assert dcn == (1, 2, 1, 1, 1, 1)
    assert ici == (1, 2, 2, 1, 1, 1)


def test_split_hybrid_rejects_inner_only_mesh():
    from ray_tpu.parallel.mesh import _split_hybrid
    with pytest.raises(ValueError, match="slices"):
        # All axes trivial except tp (innermost, ICI-only): the 2 slices
        # have nowhere to go.
        _split_hybrid((1, 1, 1, 1, 1, 2), 2, 1)


def test_prepare_mesh_hybrid_path_with_fake_slices(monkeypatch):
    """Devices carrying distinct slice_index route through
    create_hybrid_device_mesh with the (dcn, ici) factorisation."""
    from ray_tpu.parallel import mesh as mesh_mod

    calls = {}

    def fake_hybrid(ici_shape, dcn_shape, devices=None):
        calls["ici"] = tuple(ici_shape)
        calls["dcn"] = tuple(dcn_shape)
        from jax.experimental import mesh_utils
        full = tuple(i * d for i, d in zip(ici_shape, dcn_shape))
        return mesh_utils.create_device_mesh(full, devices=devices)

    monkeypatch.setattr(mesh_mod, "_num_slices", lambda devs: 2)
    monkeypatch.setattr(mesh_mod.mesh_utils, "create_hybrid_device_mesh",
                        fake_hybrid)
    m = mesh_mod.prepare_mesh(MeshSpec(dp=4, tp=2))
    assert calls["dcn"] == (1, 2, 1, 1, 1, 1)   # dp axis split over DCN
    assert calls["ici"] == (1, 2, 1, 1, 1, 2)
    assert m.shape["dp"] == 4 and m.shape["tp"] == 2


# ------------------------------------------------------------ pipeline
def test_gpipe_pipeline_matches_unpipelined_transformer():
    """GPipe over pp=2 (composed with dp and tp) must reproduce the
    plain layer-scan transformer: hidden states, loss AND grads
    (VERDICT r2 missing 4 — the pp axis now has an implementation)."""
    import dataclasses

    from ray_tpu.models import Transformer
    from ray_tpu.models.config import tiny

    cfg = dataclasses.replace(tiny(), pipeline_microbatches=4)
    mesh = MeshSpec(dp=2, pp=2, tp=2).build()
    ref_model = Transformer(dataclasses.replace(cfg,
                                                pipeline_microbatches=0))
    params = ref_model.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.default_rng(5).integers(
        0, cfg.vocab_size, (8, 32)), jnp.int32)

    pp_model = Transformer(cfg, mesh=mesh)
    ref = jax.jit(ref_model.hidden)(params, tokens)
    out = jax.jit(pp_model.hidden)(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)

    batch = {"tokens": tokens}
    l_ref, g_ref = jax.value_and_grad(ref_model.loss)(params, batch)
    l_pp, g_pp = jax.value_and_grad(pp_model.loss)(params, batch)
    assert abs(float(l_ref) - float(l_pp)) < 1e-5
    for a, b in zip(jax.tree_util.tree_leaves(g_ref),
                    jax.tree_util.tree_leaves(g_pp)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=1e-5, rtol=1e-4)


def test_pipeline_validation_errors():
    from ray_tpu.parallel.pipeline import pipeline_apply, split_stages
    mesh = MeshSpec(dp=4, pp=2).build()
    with pytest.raises(ValueError, match="not divisible"):
        split_stages({"w": jnp.zeros((3, 4))}, 2)
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_apply(mesh, lambda p, x: x, {"w": jnp.zeros((2, 4))},
                       jnp.zeros((5, 4)), 3)


def test_pipeline_1f1b_parity_with_direct_autodiff():
    """VERDICT r3 item 10 gate: the 1F1B schedule's loss AND grads
    match plain value_and_grad of the unpipelined stack, across stage
    counts and microbatch counts (incl. M close to S)."""
    from ray_tpu.parallel.pipeline import pipeline_grads_1f1b
    L, D, B = 8, 12, 24
    kw, kx, kt = jax.random.split(jax.random.PRNGKey(0), 3)
    params = {"w": jax.random.normal(kw, (L, D, D)) * 0.2,
              "b": jnp.zeros((L, D))}
    x = jax.random.normal(kx, (B, D))
    targets = jax.random.normal(kt, (B, D))

    def stage_fn(p, h):
        def layer(h, wb):
            w, b = wb
            return jnp.tanh(h @ w + b), None
        h, _ = jax.lax.scan(layer, h, (p["w"], p["b"]))
        return h

    def loss_fn(y, t):
        return jnp.sum((y - t) ** 2)

    for S, M in ((2, 8), (4, 8), (4, 4), (8, 4)):
        def full_loss(p, M=M):
            y = stage_fn(p, x)
            return jnp.sum((y - targets) ** 2) / M
        gt_loss, gt_grads = jax.value_and_grad(full_loss)(params)
        from jax.sharding import Mesh
        mesh = Mesh(np.array(jax.devices()[:S]).reshape(S), ("pp",))
        loss, grads = pipeline_grads_1f1b(
            mesh, stage_fn, loss_fn, params, x, targets, M)
        np.testing.assert_allclose(float(loss), float(gt_loss),
                                   rtol=1e-5)
        for k in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(grads[k]), np.asarray(gt_grads[k]),
                rtol=1e-4, atol=1e-6, err_msg=f"S={S} M={M} leaf={k}")
