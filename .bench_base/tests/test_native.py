"""Native core (ray_tpu/native/core.c): GIL-free channel waits +
CRC32C, built on demand with the host compiler, ctypes-bound, with
pure-Python fallbacks everywhere it is used."""
import mmap
import struct
import threading
import time

import pytest

from ray_tpu import native


pytestmark = pytest.mark.skipif(
    not native.available(),
    reason="no C compiler on this host (pure-Python fallbacks active)")


def test_crc32c_matches_python_reference():
    from ray_tpu.data.datasource import _crc32c as py_crc
    assert native.crc32c(b"123456789") == 0xE3069283   # known answer
    for blob in (b"", b"\x00", bytes(range(256)) * 33,
                 b"tfrecord" * 1000):
        assert native.crc32c(blob) == py_crc(blob)
        py_masked = (((py_crc(blob) >> 15) | (py_crc(blob) << 17))
                     + 0xA282EAD8) & 0xFFFFFFFF
        assert native.masked_crc32c(blob) == py_masked


def test_wait_u64s_ge_success_and_timeout():
    buf = mmap.mmap(-1, 4096)
    mv = memoryview(buf)
    struct.pack_into("<QQQ", mv, 0, 1, 1, 0)
    # word[2] lags: waiting on all three must block until it is set
    def flip():
        time.sleep(0.12)
        struct.pack_into("<Q", mv, 16, 9)

    threading.Thread(target=flip).start()
    t0 = time.perf_counter()
    assert native.wait_u64s_ge(mv, 0, 3, 1, 5.0)
    assert 0.08 < time.perf_counter() - t0 < 2.0
    # timeout path returns False and respects the deadline
    t0 = time.perf_counter()
    assert not native.wait_u64s_ge(mv, 0, 3, 10**9, 0.15)
    assert time.perf_counter() - t0 < 1.5


def test_channel_roundtrip_native_and_fallback(monkeypatch):
    """The shm channel works identically on the native wait path and
    the pure-Python fallback."""
    import numpy as np

    from ray_tpu.experimental import channel as chmod

    for force_fallback in (False, True):
        if force_fallback:
            monkeypatch.setattr(chmod, "_wait_words",
                                lambda ch, off, count, value, timeout,
                                what: chmod._wait(
                                    lambda: all(
                                        ch._u64(off + 8 * i) >= value
                                        for i in range(count)),
                                    timeout, what))
        ch = chmod.Channel.create(capacity=1 << 16, n_readers=1)
        try:
            w = chmod.ChannelWriter(ch)
            r = chmod.ChannelReader(ch, 0)
            out = []
            t = threading.Thread(
                target=lambda: [out.append(r.read(10.0))
                                for _ in range(3)])
            t.start()
            w.write({"k": 1})
            w.write(np.arange(6, dtype=np.float32))
            w.write("done")
            t.join(20)
            assert out[0] == {"k": 1}
            np.testing.assert_array_equal(
                out[1], np.arange(6, dtype=np.float32))
            assert out[2] == "done"
        finally:
            ch.destroy()


def test_disable_env_forces_fallback(tmp_path):
    import subprocess
    import sys
    out = subprocess.run(
        [sys.executable, "-c",
         "import ray_tpu.native as n; print(n.available())"],
        env={"PATH": "/usr/bin:/bin", "RAY_TPU_DISABLE_NATIVE": "1",
             "PYTHONPATH": "/root/repo"},
        capture_output=True, text=True, timeout=60)
    assert out.stdout.strip() == "False", out.stderr
