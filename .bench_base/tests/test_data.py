"""ray_tpu.data: blocks, datasets, streaming execution, train ingestion.

Mirrors the reference's data test strategy (python/ray/data/tests/):
small on-disk datasets, transform chains, shard/split semantics, and the
iterator edge that feeds training (here: sharded jax.Arrays on the
virtual 8-device CPU mesh from conftest).
"""
import json
import os
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.data.block import (block_concat, block_from_rows,
                                block_num_rows, block_slice, block_take,
                                rebatch_blocks)


# ------------------------------------------------------------- blocks
def test_block_from_rows_and_back():
    rows = [{"a": 1, "b": "x"}, {"a": 2, "b": "y"}]
    blk = block_from_rows(rows)
    assert blk["a"].tolist() == [1, 2]
    assert list(blk["b"]) == ["x", "y"]
    from ray_tpu.data.block import block_to_rows
    assert [dict(r) for r in block_to_rows(blk)][0]["a"] == 1


def test_block_from_rows_heterogeneous_keys():
    """Optional JSONL fields: union of keys, None-filled (object col)."""
    rows = [{"a": 1, "b": 2}, {"a": 3}, {"a": 4, "c": 9}]
    blk = block_from_rows(rows)
    assert set(blk) == {"a", "b", "c"}
    assert blk["a"].tolist() == [1, 3, 4]
    assert blk["b"][1] is None and blk["b"][0] == 2
    assert blk["c"][2] == 9 and blk["c"][0] is None


def test_block_concat_heterogeneous_keys_across_blocks():
    """A nullable column absent from a whole chunk must survive concat
    (union keys, None-filled) in BOTH orders."""
    b1 = {"a": np.array([1, 2])}
    b2 = {"a": np.array([3]), "b": np.array([9])}
    for blocks in ([b1, b2], [b2, b1]):
        out = block_concat(blocks)
        assert set(out) == {"a", "b"}
        assert sorted(out["a"].tolist()) == [1, 2, 3]
        assert sum(v is None for v in out["b"]) == 2


def test_rebatch_blocks_boundaries():
    blocks = [{"x": np.arange(3)}, {"x": np.arange(3, 5)},
              {"x": np.arange(5, 11)}]
    batches = list(rebatch_blocks(iter(blocks), 4))
    assert [b["x"].tolist() for b in batches] == [
        [0, 1, 2, 3], [4, 5, 6, 7], [8, 9, 10]]
    batches = list(rebatch_blocks(iter(blocks), 4, drop_last=True))
    assert len(batches) == 2


def test_block_ops():
    blk = {"x": np.arange(10)}
    assert block_num_rows(block_slice(blk, 2, 5)) == 3
    assert block_take(blk, np.array([0, 9]))["x"].tolist() == [0, 9]
    assert block_concat([blk, blk])["x"].shape == (20,)


# ------------------------------------------------- dataset (local path)
def test_range_count_take_schema():
    ds = rd.range(100, override_num_blocks=7)
    assert ds.num_partitions() == 7
    assert ds.count() == 100
    assert [r["id"] for r in ds.take(3)] == [0, 1, 2]
    assert ds.schema() == {"id": "int64"}


def test_map_filter_flat_map_chain():
    ds = (rd.range(20)
          .map(lambda r: {"id": r["id"], "sq": int(r["id"]) ** 2})
          .filter(lambda r: r["id"] % 2 == 0)
          .flat_map(lambda r: [r, r]))
    rows = ds.take_all()
    assert len(rows) == 20            # 10 evens duplicated
    assert rows[0]["sq"] == 0 and rows[2]["sq"] == 4


def test_map_batches_with_batch_size():
    """batch_size re-chunks WITHIN a partition (each read task executes
    its op chain independently — reference semantics are per-task too)."""
    def double(batch):
        assert len(batch["id"]) <= 10
        return {"id": batch["id"] * 2, "bs": np.full(len(batch["id"]),
                                                     len(batch["id"]))}

    ds = rd.range(25, override_num_blocks=2).map_batches(double,
                                                         batch_size=10)
    out = ds.take_all()
    assert len(out) == 25
    # partitions of 13/12 rows -> batches 10,3 and 10,2
    assert sorted({int(r["bs"]) for r in out}) == [2, 3, 10]
    assert out[-1]["id"] == 48


def test_iter_batches_and_shuffle_seeded():
    ds = rd.range(64, override_num_blocks=4)
    batches = list(ds.iter_batches(batch_size=16))
    assert [block_num_rows(b) for b in batches] == [16, 16, 16, 16]
    a = [r["id"] for b in rd.range(64).iter_batches(
        batch_size=64, local_shuffle_buffer_size=32, seed=5) for r in [b]]
    b_ = [r["id"] for b in rd.range(64).iter_batches(
        batch_size=64, local_shuffle_buffer_size=32, seed=5) for r in [b]]
    assert np.array_equal(a[0], b_[0])          # deterministic w/ seed
    assert not np.array_equal(a[0], np.arange(64))  # actually shuffled
    assert sorted(a[0].tolist()) == list(range(64))  # a permutation


def test_split_and_repartition():
    ds = rd.range(30, override_num_blocks=6)
    shards = ds.split(3)
    assert [s.num_partitions() for s in shards] == [2, 2, 2]
    ids = sorted(r["id"] for s in shards for r in s.take_all())
    assert ids == list(range(30))
    with pytest.raises(ValueError):
        rd.range(4, override_num_blocks=2).split(3)
    rep = rd.range(10, override_num_blocks=2).repartition(5)
    assert rep.num_partitions() == 5
    assert rep.count() == 10


def test_from_items_and_from_numpy():
    ds = rd.from_items([{"v": i} for i in range(7)], override_num_blocks=2)
    assert ds.count() == 7
    ds2 = rd.from_numpy({"x": np.arange(12), "y": np.ones(12)})
    assert ds2.count() == 12
    assert ds2.schema()["y"] == "float64"


# --------------------------------------------------------------- files
def test_jsonl_roundtrip(tmp_path):
    p = tmp_path / "in.jsonl"
    with open(p, "w") as f:
        for i in range(10):
            f.write(json.dumps({"text": f"doc{i}", "n": i}) + "\n")
    ds = rd.read_json(str(p))
    assert ds.count() == 10
    assert ds.take(1)[0]["text"] == "doc0"
    out = ds.write_jsonl(str(tmp_path / "out"))
    back = rd.read_json(out)
    assert back.count() == 10


def test_jsonl_heterogeneous_fields(tmp_path):
    p = tmp_path / "opt.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"a": 1, "b": 2}) + "\n")
        f.write(json.dumps({"a": 3}) + "\n")
    rows = rd.read_json(str(p)).take_all()
    assert rows[1]["b"] is None


def test_parquet_roundtrip(tmp_path):
    pytest.importorskip("pyarrow")
    src = rd.from_numpy({"x": np.arange(20), "s": np.array(
        [f"r{i}" for i in range(20)], dtype=object)})
    files = src.write_parquet(str(tmp_path / "pq"))
    ds = rd.read_parquet(files)
    assert ds.count() == 20
    assert ds.take(2)[1]["s"] == "r1"
    only_x = rd.read_parquet(files, columns=["x"])
    assert set(only_x.schema()) == {"x"}


def test_csv_read(tmp_path):
    pytest.importorskip("pyarrow")
    p = tmp_path / "t.csv"
    with open(p, "w") as f:
        f.write("a,b\n1,x\n2,y\n3,z\n")
    ds = rd.read_csv(str(p))
    assert ds.count() == 3
    assert ds.take_all()[2]["b"] == "z"


# ------------------------------------------------------ jax ingestion
def test_iter_jax_batches_sharded_and_stats():
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = np.array(jax.devices("cpu")[:8]).reshape(8)
    mesh = Mesh(devs, ("dp",))
    ds = rd.from_numpy({"tokens": np.arange(64 * 4).reshape(64, 4)})
    stats = {}
    got = list(ds.iterator().iter_jax_batches(
        batch_size=16, sharding=NamedSharding(mesh, P("dp")),
        dtypes={"tokens": "int32"}, stats=stats))
    assert len(got) == 4
    assert got[0]["tokens"].shape == (16, 4)
    assert got[0]["tokens"].dtype == np.int32
    assert len(got[0]["tokens"].sharding.device_set) == 8
    assert stats["num_batches"] == 4
    assert "input_wait_s" in stats


def test_iter_jax_batches_abandoned_consumer_no_hang():
    """Breaking out of the loop early must retire the producer threads.
    Checks by thread name, not absolute count — unrelated runtime
    threads may start concurrently during the window."""
    def data_threads():
        return [t for t in threading.enumerate()
                if t.name.startswith(("data-prefetch", "data-producer"))]
    ds = rd.from_numpy({"x": np.arange(4096)})
    it = iter(ds.iterator().iter_jax_batches(batch_size=8,
                                             prefetch_depth=1))
    next(it)
    it.close()                       # abandon mid-stream
    deadline = time.time() + 5
    while data_threads() and time.time() < deadline:
        time.sleep(0.05)
    assert not data_threads()


# ----------------------------------------------- remote streaming path
def test_stream_blocks_remote_execution(ray_cluster):
    calls = []

    def tag(batch):
        # runs inside a ray_tpu worker: record the process
        return {"id": batch["id"], "pid": np.full(len(batch["id"]),
                                                  os.getpid())}

    ds = rd.range(40, override_num_blocks=4).map_batches(tag)
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == list(range(40))
    pids = {int(r["pid"]) for r in rows}
    assert os.getpid() not in pids   # executed remotely, not driver-side


def test_dataset_errors_propagate(ray_cluster):
    def boom(batch):
        raise RuntimeError("bad batch fn")

    with pytest.raises(Exception, match="bad batch fn"):
        rd.range(8).map_batches(boom).take_all()


# ------------------------------------------------- train integration
def test_trainer_consumes_dataset_shards(ray_cluster, tmp_path):
    """End-to-end: on-disk jsonl -> tokenize -> per-worker shards ->
    2-worker JaxTrainer reading via get_dataset_shard (the SURVEY §7
    step-7 read->map->iter_batches->train path)."""
    from ray_tpu.train import (JaxConfig, JaxTrainer, RunConfig,
                               ScalingConfig)

    p = tmp_path / "corpus.jsonl"
    with open(p, "w") as f:
        for i in range(64):
            f.write(json.dumps({"text": " ".join(["tok"] * 8),
                                "doc": i}) + "\n")

    def tokenize(batch):
        n = len(batch["doc"])
        return {"tokens": np.stack([np.arange(8) + d
                                    for d in batch["doc"]]),
                "doc": batch["doc"]}

    ds = rd.read_json(str(p), rows_per_block=8).map_batches(tokenize)

    def loop(config):
        from ray_tpu import train as rt_train
        shard = rt_train.get_dataset_shard("train")
        seen = 0
        docs = []
        for batch in shard.iter_batches(batch_size=4):
            assert batch["tokens"].shape == (4, 8)
            seen += len(batch["doc"])
            docs.extend(int(d) for d in batch["doc"])
        rt_train.report({"seen": seen, "first_doc": docs[0]})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="data_e2e",
                             storage_path=str(tmp_path / "results")),
        backend_config=JaxConfig(distributed=False),
        datasets={"train": ds})
    result = trainer.fit()
    assert result.error is None
    # each worker saw half the corpus
    assert result.metrics["seen"] == 32


# ------------------------------------------ per-operator streaming
def test_streaming_staged_execution(ray_cluster):
    """An op with its own resources gets its own physical stage;
    results and ordering match the fused path, stats expose stages."""
    def double(b):
        return {"id": b["id"] * 2}

    def add_one(b):
        return {"id": b["id"] + 1}

    ds = (rd.range(40, override_num_blocks=4)
          .map_batches(double)                       # fuses into read
          .map_batches(add_one, num_cpus=1, concurrency=2))  # own stage
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == [2 * i + 1 for i in range(40)]
    st = ds.stats()
    assert st is not None and len(st.stages) == 2
    assert st.stages[0]["ops"] == ["map_batches"]    # read+double fused
    assert st.stages[1]["concurrency"] == 2
    assert st.stages[1]["tasks"] == 4                # one per partition
    assert st.stages[1]["blocks_out"] >= 4


def test_streaming_stage_actor_pool(ray_cluster):
    """A per-op ActorPoolStrategy scopes the pool to that stage only;
    callable-class state persists across partitions within the pool."""
    class Tagger:
        def __init__(self, base):
            self.base = base
            self.seen = 0

        def __call__(self, b):
            self.seen += 1
            return {"id": b["id"], "seen": np.full(len(b["id"]),
                                                   self.seen),
                    "base": np.full(len(b["id"]), self.base)}

    ds = (rd.range(24, override_num_blocks=6)
          .map_batches(Tagger, fn_constructor_args=(7,),
                       compute=rd.ActorPoolStrategy(2),
                       concurrency=2))
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == list(range(24))
    assert all(r["base"] == 7 for r in rows)
    # 6 partitions over a 2-actor pool: some actor saw >1 partition
    assert max(r["seen"] for r in rows) > 1
    st = ds.stats()
    assert st.stages[1]["actor_pool"] is True


def test_streaming_backpressure_bounds_inflight(ray_cluster):
    """A slow downstream stage must throttle the upstream reader: the
    upstream may run ahead only by its window + the bounded backlog."""
    import ray_tpu as rt

    class TouchCounter:
        def __init__(self):
            self.n = 0

        def touch(self):
            self.n += 1

        def peak(self):
            return self.n

    counter = rt.remote(TouchCounter).remote()

    def track(b):
        rt.get(counter.touch.remote())
        return b

    def slow(b):
        time.sleep(0.15)
        return b

    ds = (rd.range(64, override_num_blocks=16)
          .map_batches(track)
          .map_batches(slow, concurrency=1))
    it = ds.iter_blocks()
    next(it)  # pull ONE output block, then stop consuming
    high = rt.get(counter.peak.remote())
    # fused read stage window (4) + backlog slack; far below 16
    assert high <= 12, high
    for _ in it:
        pass
    assert rt.get(counter.peak.remote()) == 16  # all eventually ran


def test_streaming_stage0_keeps_dataset_actor_pool(ray_cluster):
    """A dataset-level ActorPoolStrategy (attached by a spec-less
    stateful map_batches) must survive the switch to staged execution:
    stage 0 runs on a persistent pool, not one-shot tasks."""
    class Counter:
        def __init__(self):
            self.seen = 0

        def __call__(self, b):
            self.seen += 1
            return {"id": b["id"], "seen": np.full(len(b["id"]),
                                                   self.seen)}

    ds = (rd.range(24, override_num_blocks=6)
          .map_batches(Counter, compute=rd.ActorPoolStrategy(2))
          .map_batches(lambda b: b, concurrency=2))   # forces staging
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == list(range(24))
    # persistent pool => some instance saw more than one partition
    assert max(r["seen"] for r in rows) > 1
    st = ds.stats()
    assert st.stages[0]["actor_pool"] is True


def test_streaming_local_fallback_no_runtime(tmp_path):
    ds = (rd.range(10, override_num_blocks=2)
          .map_batches(lambda b: {"id": b["id"] + 1},
                       num_cpus=1, concurrency=2))
    assert sorted(r["id"] for r in ds.take_all()) == list(range(1, 11))


# ------------------------------------------------ datasource breadth
def test_read_text_and_binary(tmp_path):
    p = tmp_path / "a.txt"
    p.write_text("alpha\nbeta\ngamma\n")
    rows = rd.read_text(str(p)).take_all()
    assert [r["text"] for r in rows] == ["alpha", "beta", "gamma"]

    b = tmp_path / "blob.bin"
    b.write_bytes(b"\x00\x01binary")
    rows = rd.read_binary_files(str(b)).take_all()
    assert rows[0]["bytes"] == b"\x00\x01binary"
    assert rows[0]["path"].endswith("blob.bin")


def test_read_images(tmp_path):
    from PIL import Image
    for i, shape in enumerate([(8, 6), (10, 12)]):
        img = Image.fromarray(
            (np.arange(shape[0] * shape[1] * 3) % 255).astype(
                np.uint8).reshape(shape[0], shape[1], 3))
        img.save(tmp_path / f"im{i}.png")
    # resized: dense batched column
    rows = rd.read_images(str(tmp_path / "*.png"), size=(4, 5),
                          include_paths=True).take_all()
    assert len(rows) == 2
    assert all(r["image"].shape == (4, 5, 3) for r in rows)
    assert all(r["image"].dtype == np.uint8 for r in rows)
    assert {os.path.basename(r["path"]) for r in rows} == {"im0.png",
                                                           "im1.png"}


def test_tfrecords_roundtrip(tmp_path):
    ds1 = rd.from_items([
        {"name": "a", "score": 1.5, "count": 7,
         "vec": np.asarray([1.0, 2.0, 3.0], dtype=np.float32),
         "raw": b"\x00\xff"},
        {"name": "b", "score": -2.25, "count": -3,
         "vec": np.asarray([4.0, 5.0, 6.0], dtype=np.float32),
         "raw": b"xyz"},
    ], override_num_blocks=1)
    (out,) = ds1.write_tfrecords(str(tmp_path / "tfr"))
    rows = sorted(rd.read_tfrecords(out).take_all(),
                  key=lambda r: r["name"])
    assert [r["name"] for r in rows] == [b"a", b"b"]  # tf semantics:
    assert rows[0]["raw"] == b"\x00\xff"              # strings = bytes
    assert rows[0]["count"] == 7 and rows[1]["count"] == -3
    assert abs(rows[1]["score"] - (-2.25)) < 1e-6
    np.testing.assert_allclose(rows[0]["vec"], [1, 2, 3])


def test_tfrecord_crc_is_real_crc32c(tmp_path):
    # known-answer test: crc32c("123456789") == 0xE3069283
    from ray_tpu.data.datasource import _crc32c
    assert _crc32c(b"123456789") == 0xE3069283


def test_write_csv_roundtrip(tmp_path):
    ds1 = rd.from_items([{"x": i, "y": f"s{i}"} for i in range(5)],
                        override_num_blocks=2)
    (out,) = ds1.write_csv(str(tmp_path / "csv"))
    rows = sorted(rd.read_csv(out).take_all(), key=lambda r: r["x"])
    assert [int(r["x"]) for r in rows] == list(range(5))
    assert [r["y"] for r in rows] == [f"s{i}" for i in range(5)]
