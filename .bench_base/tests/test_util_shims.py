"""Integration shims: ActorPool, Queue, state API (reference P17/P21)."""
import numpy as np
import pytest

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Empty, Full, Queue


def _make_worker():
    @ray_tpu.remote
    class PoolWorker:
        def __init__(self, factor):
            self.factor = factor

        def ping(self):
            return "pong"

        def mul(self, x):
            return x * self.factor

        def slow_mul(self, x):
            import time
            time.sleep(0.05 * (x % 3))
            return x * self.factor
    return PoolWorker


def test_actor_pool_ordered_map(ray_cluster):
    W = _make_worker()
    pool = ActorPool([W.remote(10) for _ in range(3)])
    out = list(pool.map(lambda a, v: a.mul.remote(v), range(8)))
    assert out == [v * 10 for v in range(8)]       # submission order
    assert pool.num_idle == 3


def test_actor_pool_unordered_and_backpressure(ray_cluster):
    W = _make_worker()
    pool = ActorPool([W.remote(2) for _ in range(2)])
    # 6 submissions over 2 actors: 4 queue host-side
    out = sorted(pool.map_unordered(
        lambda a, v: a.slow_mul.remote(v), range(6)))
    assert out == [v * 2 for v in range(6)]
    assert pool.num_pending == 0


def test_actor_pool_submit_get_next(ray_cluster):
    W = _make_worker()
    pool = ActorPool([W.remote(1)])
    pool.submit(lambda a, v: a.mul.remote(v), 7)
    pool.submit(lambda a, v: a.mul.remote(v), 8)   # queued (1 actor)
    assert pool.has_next()
    assert pool.get_next() == 7
    assert pool.get_next() == 8
    assert not pool.has_next()
    with pytest.raises(StopIteration):
        pool.get_next()


def test_queue_roundtrip_cross_process(ray_cluster):
    q = Queue(maxsize=4)
    q.put({"a": 1})
    q.put(np.arange(3))

    @ray_tpu.remote
    def consume(q):
        item1 = q.get(timeout=10)
        item2 = q.get(timeout=10)
        q.put("reply")
        return item1["a"], int(item2.sum())

    a, s = ray_tpu.get(consume.remote(q))
    assert (a, s) == (1, 3)
    assert q.get(timeout=10) == "reply"
    q.shutdown()


def test_queue_full_empty_semantics(ray_cluster):
    q = Queue(maxsize=1)
    q.put(1)
    with pytest.raises(Full):
        q.put(2, block=False)
    assert q.full()
    assert q.get() == 1
    with pytest.raises(Empty):
        q.get_nowait()
    assert q.empty()
    q.put(1)
    assert q.get_nowait_batch(5) == [1]
    q.shutdown()


def test_state_api_lists(ray_cluster):
    from ray_tpu.util import state

    @ray_tpu.remote
    def touch():
        return 1

    ray_tpu.get(touch.remote())
    tasks = state.list_tasks()
    assert any(e["state"] == "FINISHED" for e in tasks)
    assert isinstance(state.summarize_tasks(), dict)
    nodes = state.list_nodes()
    assert nodes and nodes[0]["alive"]
    assert state.cluster_resources().get("CPU", 0) > 0
    assert "bytes" in state.object_store_stats()
    workers = state.list_workers()
    assert workers and all(w["worker_id"] for w in workers)
    busy = state.list_workers(filters=[("state", "!=", "missing")])
    assert len(busy) == len(workers)
    assert state.usage_stats()["workers"] == len(workers)


def test_worker_side_task_events_and_host_stats(ray_cluster):
    """Workers buffer EXEC_* events locally and flush them batched to
    the head (reference task_event_buffer.cc); node listings carry the
    per-node reporter sample from heartbeats."""
    import time as _t

    from ray_tpu.util import state

    @ray_tpu.remote
    def work():
        _t.sleep(0.05)
        return 1

    ray_tpu.get([work.remote() for _ in range(3)])
    # flush interval is 2s; poll until the batch lands
    deadline = _t.time() + 10
    evs = []
    while _t.time() < deadline:
        # task name is the qualname (here: <test fn>.<locals>.work)
        evs = [e for e in state.list_tasks()
               if e["state"].startswith("EXEC_")
               and e.get("name", "").endswith("work")]
        if sum(e["state"] == "EXEC_FINISHED" for e in evs) >= 3:
            break
        _t.sleep(0.25)
    finished = [e for e in evs if e["state"] == "EXEC_FINISHED"]
    assert len(finished) >= 3
    assert all(e["duration_s"] >= 0.05 for e in finished)
    assert all(e["worker_id"] for e in finished)

    nodes = state.list_nodes()
    hs = nodes[0]["host_stats"]
    assert hs["mem_total_mb"] > 0 and hs["num_cpus"] >= 1
    assert "workers_rss_mb" in hs
