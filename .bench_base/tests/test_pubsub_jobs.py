"""Pubsub channels (N9) + job submission (P18)."""
import sys
import time

import pytest

import ray_tpu
from ray_tpu.job_submission import (FAILED, STOPPED, SUCCEEDED,
                                    JobSubmissionClient)


# --------------------------------------------------------------- pubsub
def test_pubsub_cursor_semantics():
    from ray_tpu._private.pubsub import Publisher
    pub = Publisher()
    pub.publish("c", {"a": 1})
    pub.publish("c", {"a": 2})
    msgs, cur = pub.poll("c", cursor=0)
    assert [m["a"] for m in msgs] == [1, 2]
    msgs2, cur2 = pub.poll("c", cursor=cur)
    assert msgs2 == []                 # nothing new
    pub.publish("c", {"a": 3})
    msgs3, _ = pub.poll("c", cursor=cur)
    assert [m["a"] for m in msgs3] == [3]


def test_pubsub_long_poll_blocks_until_publish():
    import threading

    from ray_tpu._private.pubsub import Publisher
    pub = Publisher()
    got = {}

    def consumer():
        got["msgs"], _ = pub.poll("evt", cursor=0, timeout=10.0)

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.3)
    pub.publish("evt", "hello")
    t.join(timeout=10)
    assert got["msgs"] == ["hello"]


def test_actor_lifecycle_published(ray_cluster):
    from ray_tpu._private import context
    from ray_tpu._private.pubsub import ACTOR_CHANNEL

    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    ray_tpu.get(a.ping.remote())
    ray_tpu.kill(a)
    deadline = time.time() + 15
    states = set()
    cursor = 0
    ctx = context.get_ctx()
    while time.time() < deadline and "DEAD" not in states:
        msgs, cursor = ctx.state_op("pubsub_poll", channel=ACTOR_CHANNEL,
                                    cursor=cursor, timeout=1.0)
        states |= {m["state"] for m in msgs}
    assert "ALIVE" in states and "DEAD" in states


# ----------------------------------------------------------------- jobs
def test_job_submission_lifecycle(tmp_path):
    client = JobSubmissionClient(log_dir=str(tmp_path))
    jid = client.submit_job(
        entrypoint=f'{sys.executable} -c "import os; '
                   f"print('job says', os.environ['GREETING'], "
                   f"os.environ['RAY_TPU_JOB_ID'])\"",
        runtime_env={"env_vars": {"GREETING": "hi"}},
        metadata={"owner": "test"})
    assert client.wait_until_finished(jid, timeout=60) == SUCCEEDED
    logs = client.get_job_logs(jid)
    assert "job says hi" in logs and jid in logs
    info = client.get_job_info(jid)
    assert info.return_code == 0 and info.metadata["owner"] == "test"
    assert len(client.list_jobs()) == 1


def test_job_failure_and_stop(tmp_path):
    client = JobSubmissionClient(log_dir=str(tmp_path))
    bad = client.submit_job(
        entrypoint=f'{sys.executable} -c "raise SystemExit(3)"')
    assert client.wait_until_finished(bad, timeout=60) == FAILED
    assert client.get_job_info(bad).return_code == 3

    slow = client.submit_job(
        entrypoint=f'{sys.executable} -c "import time; time.sleep(600)"')
    time.sleep(0.5)
    assert client.stop_job(slow)
    assert client.wait_until_finished(slow, timeout=60) == STOPPED
    with pytest.raises(ValueError):
        client.get_job_status("nope")


def test_pubsub_stale_cursor_raises():
    from ray_tpu._private.pubsub import Publisher, StaleCursorError
    pub = Publisher(maxlen_per_channel=4)
    for i in range(10):
        pub.publish("c", i)
    with pytest.raises(StaleCursorError):
        pub.poll("c", cursor=2)          # seqs 0..5 evicted
    msgs, _ = pub.poll("c", cursor=6)    # oldest retained
    assert msgs == [6, 7, 8, 9]
