"""Compiled DAGs (P9): bind/compile/execute over actor pipelines."""
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import CompiledDAG, InputNode, MultiOutputNode


def _stage_cls():
    @ray_tpu.remote
    class Stage:
        def __init__(self, tag):
            self.tag = tag
            self.calls = 0

        def ping(self):
            return "pong"

        def work(self, x):
            self.calls += 1
            return f"{x}->{self.tag}"

        def merge(self, a, b):
            return f"({a}+{b})"

        def num_calls(self):
            return self.calls
    return Stage


def test_dag_linear_pipeline(ray_cluster):
    Stage = _stage_cls()
    a, b, c = Stage.remote("a"), Stage.remote("b"), Stage.remote("c")
    with InputNode() as inp:
        x = a.work.bind(inp)
        y = b.work.bind(x)
        z = c.work.bind(y)
    dag = z.experimental_compile()
    assert isinstance(dag, CompiledDAG)
    out = ray_tpu.get(dag.execute("in"), timeout=60)
    assert out == "in->a->b->c"
    # reusable: consecutive executes pipeline through the same actors
    refs = [dag.execute(i) for i in range(5)]
    assert ray_tpu.get(refs, timeout=60) == [
        f"{i}->a->b->c" for i in range(5)]
    assert dag.num_executions == 6


def test_dag_fan_in_fan_out(ray_cluster):
    Stage = _stage_cls()
    a, b, m = Stage.remote("a"), Stage.remote("b"), Stage.remote("m")
    with InputNode() as inp:
        left = a.work.bind(inp)
        right = b.work.bind(inp)
        merged = m.merge.bind(left, right)
        dag = MultiOutputNode([merged, left]).experimental_compile()
    out_ref, left_ref = dag.execute("x")
    assert ray_tpu.get(out_ref, timeout=60) == "(x->a+x->b)"
    assert ray_tpu.get(left_ref, timeout=60) == "x->a"


def test_dag_validation(ray_cluster):
    Stage = _stage_cls()
    a = Stage.remote("a")
    with InputNode() as inp:
        x = a.work.bind(inp)
    dag = x.experimental_compile()
    with pytest.raises(TypeError, match="exactly 1 input"):
        dag.execute()
    with pytest.raises(TypeError, match="exactly 1 input"):
        dag.execute(1, 2)
    # cycles are rejected
    n1 = a.work.bind("seed")
    n1.upstream.append(n1)
    with pytest.raises(ValueError, match="cycle"):
        n1.experimental_compile()


def test_dag_constant_args_without_input(ray_cluster):
    Stage = _stage_cls()
    a, b = Stage.remote("a"), Stage.remote("b")
    dag = b.work.bind(a.work.bind("k")).experimental_compile()
    assert ray_tpu.get(dag.execute(), timeout=60) == "k->a->b"


# --------------------------------------------- shm-channel fast path
def test_channel_dag_chain_and_pipelining(ray_cluster):
    """VERDICT r3 item 8 gate: zero-copy mutable shm channels — a
    compiled chain executes with no per-hop task submission, results
    arrive in order, pipelined executes overlap."""
    Stage = _stage_cls()
    a, b = Stage.remote("a"), Stage.remote("b")
    with InputNode() as inp:
        y = b.work.bind(a.work.bind(inp))
    dag = y.experimental_compile(enable_shm_channels=True)
    try:
        for i in range(4):
            assert dag.execute(f"m{i}").get() == f"m{i}->a->b"
        refs = [dag.execute(f"p{i}") for i in range(4)]
        assert [r.get() for r in refs] == [f"p{i}->a->b"
                                           for i in range(4)]
        # ray_tpu.get understands CompiledDAGRef
        assert ray_tpu.get(dag.execute("z")) == "z->a->b"
    finally:
        dag.teardown()


def test_channel_dag_multi_output_and_fanout(ray_cluster):
    Stage = _stage_cls()
    a, b, m = Stage.remote("a"), Stage.remote("b"), Stage.remote("m")
    with InputNode() as inp:
        u = a.work.bind(inp)
        dag = MultiOutputNode([b.work.bind(u), m.work.bind(u)]
                              ).experimental_compile(
                                  enable_shm_channels=True)
    try:
        assert dag.execute("x").get() == ["x->a->b", "x->a->m"]
    finally:
        dag.teardown()


def test_channel_dag_error_propagates_and_pipeline_survives(ray_cluster):
    @ray_tpu.remote
    class Flaky:
        def work(self, x):
            if x == "bad":
                raise ValueError("boom-x")
            return f"ok:{x}"

    f = Flaky.remote()
    with InputNode() as inp:
        dag = f.work.bind(inp).experimental_compile(
            enable_shm_channels=True)
    try:
        with pytest.raises(RuntimeError, match="boom-x"):
            dag.execute("bad").get()
        # the exec loop survives the error and keeps serving
        assert dag.execute("fine").get() == "ok:fine"
    finally:
        dag.teardown()


def test_channel_dag_capacity_and_teardown(ray_cluster):
    import os
    Stage = _stage_cls()
    a = Stage.remote("a")
    with InputNode() as inp:
        dag = a.work.bind(inp).experimental_compile(
            enable_shm_channels=True, buffer_size_bytes=1 << 12)
    try:
        with pytest.raises(ValueError, match="exceeds channel capacity"):
            dag.execute("y" * (1 << 13))
    finally:
        dag.teardown()
    # teardown unlinked the channel segments
    names = [n for n in os.listdir("/dev/shm") if "_ch_" in n]
    for ch in dag._channels.values():
        assert ch.name not in names


def test_channel_dag_raw_array_fast_path(ray_cluster):
    """Device channels: ndarrays/jax.Arrays ride a raw shm frame (one
    memcpy in, device_put out) instead of a pickle stream; jax arrays
    round-trip as jax arrays (reference torch_tensor_nccl_channel.py
    intent, re-designed for TPU host processes)."""
    import numpy as np

    import ray_tpu
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class Scale:
        def work(self, x):
            return x * 2.0

    @ray_tpu.remote
    class Shift:
        def work(self, x):
            import jax.numpy as jnp
            return jnp.asarray(x) + 1.0     # returns a jax.Array

    a, b = Scale.remote(), Shift.remote()
    with InputNode() as inp:
        out = b.work.bind(a.work.bind(inp))
    dag = out.experimental_compile(enable_shm_channels=True,
                                   buffer_size_bytes=8 << 20)
    try:
        x = np.arange(16384, dtype=np.float32).reshape(128, 128)
        # first get covers the actor's cold jax import + compile
        got = dag.execute(x).get(timeout=240.0)
        for trial in range(2):              # slot reuse across executes
            got = dag.execute(x).get(timeout=60.0)
            expect = x * 2.0 + 1.0
            assert np.allclose(np.asarray(got), expect)
        # jax output type survives the channel hop back to the driver
        import jax
        assert isinstance(got, jax.Array)
    finally:
        dag.teardown()


# ------------------------------------------------- collective nodes
def test_dag_allreduce_collective_nodes(ray_cluster):
    """allreduce_bind: per-actor shards reduce inside the DAG; each
    participant continues with the reduced value (reference aDAG
    collective nodes, torch_tensor_nccl_channel / collective ops)."""
    from ray_tpu.dag import MultiOutputNode, allreduce_bind

    @ray_tpu.remote
    class Shard:
        def __init__(self, scale):
            self.scale = scale

        def compute(self, x):
            return np.asarray(x, dtype=np.float64) * self.scale

        def tag(self, reduced):
            return (self.scale, np.asarray(reduced))

    actors = [Shard.remote(s) for s in (1.0, 2.0, 3.0)]
    with InputNode() as inp:
        shards = [a.compute.bind(inp) for a in actors]
        reduced = allreduce_bind(shards, op="sum")
        outs = [a.tag.bind(r) for a, r in zip(actors, reduced)]
        dag_out = MultiOutputNode(outs)

    dag = dag_out.experimental_compile()
    try:
        x = np.array([1.0, 10.0])
        for round_i in range(2):          # group reused across executes
            results = ray_tpu.get(dag.execute(x + round_i), timeout=120)
            want = (x + round_i) * 6.0    # 1x + 2x + 3x
            scales = sorted(s for s, _ in results)
            assert scales == [1.0, 2.0, 3.0]
            for _s, arr in results:
                np.testing.assert_allclose(arr, want)
    finally:
        dag.teardown()

    # mixed ops + validation
    with pytest.raises(ValueError, match="distinct actors"):
        with InputNode() as inp:
            s0 = actors[0].compute.bind(inp)
            s1 = actors[0].compute.bind(inp)
            allreduce_bind([s0, s1])


def test_dag_allreduce_ops(ray_cluster):
    from ray_tpu.dag import MultiOutputNode, allreduce_bind

    @ray_tpu.remote
    class A:
        def __init__(self, v):
            self.v = v

        def emit(self, _):
            return np.array([self.v], dtype=np.float64)

    actors = [A.remote(v) for v in (4.0, 6.0)]
    for op, want in (("max", 6.0), ("mean", 5.0), ("prod", 24.0)):
        with InputNode() as inp:
            outs = allreduce_bind([a.emit.bind(inp) for a in actors],
                                  op=op)
            dag_out = MultiOutputNode(outs)
        dag = dag_out.experimental_compile()
        try:
            r = ray_tpu.get(dag.execute(0), timeout=120)
            assert all(abs(float(arr[0]) - want) < 1e-9 for arr in r), (
                op, r)
        finally:
            dag.teardown()
