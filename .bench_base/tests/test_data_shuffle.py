"""ray_tpu.data shuffle-backed relations: groupby/aggregate, sort,
random_shuffle, zip/union, actor-pool compute.

Mirrors the reference's aggregation tests (python/ray/data/tests/
test_all_to_all.py, test_sort.py): correctness vs numpy ground truth at
>1 partition, both local (no runtime) and remote (tasks/actors) paths.
"""
import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.data.aggregate import Count, Max, Mean, Min, Std, Sum


def _make_ds(n=200, parts=5, seed=0):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 13, size=n)
    vals = rng.normal(size=n)
    return (rd.from_numpy({"k": keys, "v": vals},
                          override_num_blocks=parts), keys, vals)


def _ground_truth(keys, vals, fn):
    return {int(k): fn(vals[keys == k]) for k in np.unique(keys)}


# ------------------------------------------------------------ groupby
def test_groupby_aggregates_local():
    ds, keys, vals = _make_ds()
    out = ds.groupby("k").aggregate(
        Count(), Sum("v"), Min("v"), Max("v"), Mean("v"), Std("v"))
    rows = out.take_all()
    assert len(rows) == len(np.unique(keys))
    gt_mean = _ground_truth(keys, vals, np.mean)
    gt_std = _ground_truth(keys, vals, lambda v: np.std(v, ddof=1))
    for r in rows:
        k = int(r["k"])
        assert r["count()"] == int((keys == k).sum())
        np.testing.assert_allclose(r["sum(v)"], vals[keys == k].sum())
        np.testing.assert_allclose(r["min(v)"], vals[keys == k].min())
        np.testing.assert_allclose(r["max(v)"], vals[keys == k].max())
        np.testing.assert_allclose(r["mean(v)"], gt_mean[k])
        np.testing.assert_allclose(r["std(v)"], gt_std[k], rtol=1e-10)


def test_groupby_string_keys_multi_partition():
    names = ["ab", "cd", "ef", "gh"] * 25
    vals = np.arange(100.0)
    ds = rd.from_numpy({"name": np.array(names, dtype=object),
                        "v": vals}, override_num_blocks=4)
    rows = ds.groupby("name").sum("v").take_all()
    got = {r["name"]: r["sum(v)"] for r in rows}
    for nm in set(names):
        want = vals[[i for i, x in enumerate(names) if x == nm]].sum()
        np.testing.assert_allclose(got[nm], want)


def test_groupby_multi_key():
    ds = rd.from_numpy({"a": np.array([0, 0, 1, 1, 0]),
                        "b": np.array([0, 1, 0, 1, 0]),
                        "v": np.array([1., 2., 3., 4., 5.])},
                       override_num_blocks=2)
    rows = ds.groupby(["a", "b"]).sum("v").take_all()
    got = {(int(r["a"]), int(r["b"])): r["sum(v)"] for r in rows}
    assert got == {(0, 0): 6.0, (0, 1): 2.0, (1, 0): 3.0, (1, 1): 4.0}


def test_groupby_map_groups():
    ds, keys, vals = _make_ds(60, parts=3)
    out = ds.groupby("k").map_groups(
        lambda g: {"k": g["k"][:1], "spread": [g["v"].max() - g["v"].min()]})
    rows = out.take_all()
    gt = _ground_truth(keys, vals, lambda v: v.max() - v.min())
    assert {int(r["k"]): pytest.approx(r["spread"]) for r in rows} == \
        {k: pytest.approx(v) for k, v in gt.items()}


def test_groupby_remote(ray_cluster):
    ds, keys, vals = _make_ds(120, parts=4)
    rows = ds.groupby("k").mean("v").take_all()
    gt = _ground_truth(keys, vals, np.mean)
    assert len(rows) == len(gt)
    for r in rows:
        np.testing.assert_allclose(r["mean(v)"], gt[int(r["k"])])


def test_unique():
    ds = rd.from_numpy({"x": np.array([3, 1, 2, 3, 1, 3])},
                       override_num_blocks=3)
    assert sorted(ds.unique("x")) == [1, 2, 3]


# ----------------------------------------------------- global aggregate
def test_global_aggregates():
    ds, _, vals = _make_ds(80, parts=4)
    np.testing.assert_allclose(ds.sum("v"), vals.sum())
    np.testing.assert_allclose(ds.mean("v"), vals.mean())
    np.testing.assert_allclose(ds.min("v"), vals.min())
    np.testing.assert_allclose(ds.max("v"), vals.max())
    np.testing.assert_allclose(ds.std("v"), np.std(vals, ddof=1),
                               rtol=1e-10)


# ----------------------------------------------------------------- sort
def test_sort_local_multi_partition():
    ds, _, vals = _make_ds(150, parts=6)
    got = [r["v"] for r in ds.sort("v").take_all()]
    np.testing.assert_allclose(got, np.sort(vals))
    got_d = [r["v"] for r in ds.sort("v", descending=True).take_all()]
    np.testing.assert_allclose(got_d, np.sort(vals)[::-1])


def test_sort_remote(ray_cluster):
    ds, _, vals = _make_ds(100, parts=4, seed=7)
    got = [r["v"] for r in ds.sort("v").take_all()]
    np.testing.assert_allclose(got, np.sort(vals))


def test_sort_preserves_row_alignment():
    ds = rd.from_numpy({"k": np.array([3, 1, 2]),
                        "tag": np.array(["c", "a", "b"], dtype=object)},
                       override_num_blocks=2)
    rows = ds.sort("k").take_all()
    assert [r["tag"] for r in rows] == ["a", "b", "c"]


# ------------------------------------------------------- random shuffle
def test_random_shuffle_is_permutation():
    ds = rd.range(100, override_num_blocks=5)
    rows = [r["id"] for r in ds.random_shuffle(seed=3).take_all()]
    assert sorted(rows) == list(range(100))
    assert rows != list(range(100))


# ------------------------------------------------------------ zip/union
def test_zip_aligned():
    a = rd.from_numpy({"x": np.arange(10)}, override_num_blocks=3)
    b = rd.from_numpy({"y": np.arange(10) * 2}, override_num_blocks=2)
    rows = a.zip(b).take_all()
    assert all(r["y"] == 2 * r["x"] for r in rows)


def test_zip_name_collision_and_mismatch():
    a = rd.from_numpy({"x": np.arange(4)})
    b = rd.from_numpy({"x": np.arange(4) + 10})
    rows = a.zip(b).take_all()
    assert [r["x_1"] - r["x"] for r in rows] == [10] * 4
    c = rd.from_numpy({"x": np.arange(5)})
    # surfaces directly (local) or wrapped in TaskError (remote worker)
    with pytest.raises(Exception, match="row counts"):
        a.zip(c).take_all()


def test_union_fuses_op_chains():
    a = rd.range(5).map(lambda r: {"id": r["id"] * 10})
    b = rd.range(3)
    rows = sorted(r["id"] for r in a.union(b).take_all())
    assert rows == [0, 0, 1, 2, 10, 20, 30, 40]
    assert a.union(b).count() == 8


# ----------------------------------------------------- actor-pool compute
class _Enricher:
    """Stateful transform: counts how many batches this instance saw."""

    def __init__(self, offset):
        self.offset = offset
        self.calls = 0

    def __call__(self, batch):
        self.calls += 1
        import os
        return {"id": batch["id"] + self.offset,
                "pid": np.full(len(batch["id"]), os.getpid()),
                "call": np.full(len(batch["id"]), self.calls)}


def test_map_batches_callable_class():
    ds = rd.range(40, override_num_blocks=4).map_batches(
        _Enricher, fn_constructor_args=(100,))
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == list(range(100, 140))
    # stateful: some instance saw more than one partition (single local
    # cache -> 4; actor pool of 2 -> >=2)
    assert max(r["call"] for r in rows) > 1


def test_map_batches_actor_pool_remote(ray_cluster):
    import os
    ds = rd.range(60, override_num_blocks=6).map_batches(
        _Enricher, fn_constructor_args=(1000,),
        compute=rd.ActorPoolStrategy(size=2))
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == list(range(1000, 1060))
    pids = {int(r["pid"]) for r in rows}
    assert os.getpid() not in pids       # ran on actors
    assert len(pids) <= 2                # pool-sized
    # statefulness: some actor processed >1 partition with the SAME
    # instance (calls > 1 observed)
    assert max(int(r["call"]) for r in rows) > 1


# --------------------------------------------- review-finding regressions
def test_single_partition_shuffle_remote(ray_cluster):
    """num_out == 1 exchange: sort/groupby on a 1-partition dataset must
    not crash (num_returns=1 stores the whole list as one object)."""
    ds = rd.from_numpy({"k": np.array([2, 1, 2]),
                        "v": np.array([1., 2., 3.])},
                       override_num_blocks=1)
    got = [r["k"] for r in ds.sort("k").take_all()]
    assert got == [1, 2, 2]
    rows = ds.groupby("k", num_partitions=1).sum("v").take_all()
    assert {int(r["k"]): r["sum(v)"] for r in rows} == {1: 2.0, 2: 4.0}


def test_groupby_negative_zero_key():
    """-0.0 and 0.0 are equal keys and must land in ONE group even when
    scattered across partitions."""
    ds = rd.from_numpy({"k": np.array([0.0, -0.0, 1.0, -0.0]),
                        "v": np.array([1., 2., 3., 4.])},
                       override_num_blocks=4)
    rows = ds.groupby("k").sum("v").take_all()
    got = {float(r["k"]): r["sum(v)"] for r in rows}
    assert got == {0.0: 7.0, 1.0: 3.0}


def test_std_large_mean_stability():
    """Catastrophic cancellation guard: values ~1e8 with std ~1."""
    rng = np.random.default_rng(0)
    vals = 1e8 + rng.normal(size=400)
    keys = np.repeat([0, 1], 200)
    ds = rd.from_numpy({"k": keys, "v": vals}, override_num_blocks=4)
    rows = ds.groupby("k").std("v").take_all()
    for r in rows:
        want = np.std(vals[keys == int(r["k"])], ddof=1)
        np.testing.assert_allclose(r["std(v)"], want, rtol=1e-6)
    np.testing.assert_allclose(ds.std("v"), np.std(vals, ddof=1),
                               rtol=1e-6)


def test_seeded_shuffle_decorrelates_equal_named_partitions():
    """from_items names every task identically; seeded shuffles must
    still draw DIFFERENT bucket streams per partition (review
    regression: name-derived seeds co-located row i of every
    partition)."""
    ds = rd.from_items(list(range(100)), override_num_blocks=5)
    out = ds.random_shuffle(seed=3)
    blocks = list(out.iter_blocks())
    # same-index rows of the 5 input partitions (0,20,40,60,80):
    # with per-index seeds they almost surely spread across blocks
    landing = {}
    for bi, b in enumerate(blocks):
        for v in b["item"]:
            landing[int(v)] = bi
    aligned = {landing[i] for i in (0, 20, 40, 60, 80)}
    assert len(aligned) > 1, landing
    # determinism under the same seed
    again = [int(v) for b in ds.random_shuffle(seed=3).iter_blocks()
             for v in b["item"]]
    first = [int(v) for b in blocks for v in b["item"]]
    assert again == first
