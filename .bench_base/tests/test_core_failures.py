"""Fault-tolerance tests: worker death, task retries, actor restarts.

Models the reference's kill-based fault injection strategy
(python/ray/_private/test_utils.py WorkerKillerActor:1597) with
self-terminating tasks instead of external killer actors.
"""
import os
import tempfile
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import ActorDiedError, TaskError, WorkerDiedError


def _attempt_file():
    f = tempfile.NamedTemporaryFile(prefix="rtpu_attempt_", delete=False)
    f.write(b"0")
    f.close()
    return f.name


def test_task_retry_on_worker_death(fresh_cluster):
    path = _attempt_file()

    @ray_tpu.remote(max_retries=2)
    def flaky(p):
        n = int(open(p).read())
        open(p, "w").write(str(n + 1))
        if n == 0:
            os._exit(1)  # simulate worker crash on first attempt
        return n

    assert ray_tpu.get(flaky.remote(path), timeout=60) == 1
    os.unlink(path)


def test_task_failure_after_retries_exhausted(fresh_cluster):
    @ray_tpu.remote(max_retries=0)
    def die():
        os._exit(1)

    with pytest.raises(TaskError) as ei:
        ray_tpu.get(die.remote(), timeout=60)
    assert isinstance(ei.value.cause, WorkerDiedError)


def test_actor_restart(fresh_cluster):
    # max_task_retries=0: the crashing call must NOT replay after restart
    # (it would deterministically crash the restarted actor too).
    @ray_tpu.remote(max_restarts=1, max_task_retries=0)
    class Phoenix:
        def __init__(self):
            self.calls = 0

        def call(self):
            self.calls += 1
            return self.calls

        def crash(self):
            os._exit(1)

    a = Phoenix.remote()
    assert ray_tpu.get(a.call.remote(), timeout=60) == 1
    crash_ref = a.crash.remote()
    # Calls in flight during the crash fail (max_task_retries=0); wait for
    # the restart to complete before checking state reset.
    time.sleep(3.0)
    # After restart, state is reset (fresh __init__), like the reference.
    assert ray_tpu.get(a.call.remote(), timeout=60) == 1
    with pytest.raises(TaskError):
        ray_tpu.get(crash_ref, timeout=60)


def test_actor_dead_after_max_restarts(fresh_cluster):
    @ray_tpu.remote(max_restarts=0)
    class Mortal:
        def crash(self):
            os._exit(1)

        def ping(self):
            return "pong"

    a = Mortal.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
    a.crash.remote()
    time.sleep(1.0)
    with pytest.raises(TaskError) as ei:
        ray_tpu.get(a.ping.remote(), timeout=60)
    assert isinstance(ei.value.cause, ActorDiedError)


def test_kill_actor(fresh_cluster):
    @ray_tpu.remote(max_restarts=5)
    class Immortal:
        def ping(self):
            return "pong"

    a = Immortal.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
    ray_tpu.kill(a)  # no_restart=True overrides max_restarts
    time.sleep(1.0)
    with pytest.raises(TaskError):
        ray_tpu.get(a.ping.remote(), timeout=60)


def test_actor_init_failure(fresh_cluster):
    @ray_tpu.remote
    class Broken:
        def __init__(self):
            raise RuntimeError("bad init")

        def ping(self):
            return "pong"

    a = Broken.remote()
    with pytest.raises(TaskError):
        ray_tpu.get(a.ping.remote(), timeout=60)
