"""Core task/actor/object API tests.

Models the reference's python/ray/tests/test_basic*.py coverage: task
round-trips, object semantics, actor ordering, error propagation.
"""
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError, TaskError


@ray_tpu.remote
def add(a, b):
    return a + b


@ray_tpu.remote
def identity(x):
    return x


def test_task_roundtrip(ray_cluster):
    assert ray_tpu.get(add.remote(1, 2)) == 3


def test_put_get(ray_cluster):
    ref = ray_tpu.put({"k": [1, 2, 3]})
    assert ray_tpu.get(ref) == {"k": [1, 2, 3]}


def test_large_object_shm(ray_cluster):
    arr = np.random.rand(512, 512)
    out = ray_tpu.get(identity.remote(arr))
    np.testing.assert_array_equal(arr, out)


def test_object_ref_args(ray_cluster):
    a = ray_tpu.put(10)
    b = ray_tpu.put(20)
    assert ray_tpu.get(add.remote(a, b)) == 30


def test_chained_tasks(ray_cluster):
    r = add.remote(1, 1)
    for _ in range(5):
        r = add.remote(r, 1)
    assert ray_tpu.get(r) == 7


def test_nested_refs_pass_through(ray_cluster):
    @ray_tpu.remote
    def takes_list(refs):
        return sum(ray_tpu.get(refs))

    refs = [ray_tpu.put(i) for i in range(4)]
    assert ray_tpu.get(takes_list.remote(refs)) == 6


def test_num_returns(ray_cluster):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    r1, r2, r3 = three.remote()
    assert ray_tpu.get([r1, r2, r3]) == [1, 2, 3]


def test_parallel_tasks(ray_cluster):
    refs = [add.remote(i, i) for i in range(16)]
    assert ray_tpu.get(refs) == [2 * i for i in range(16)]


def test_wait(ray_cluster):
    refs = [add.remote(i, 0) for i in range(4)]
    ready, not_ready = ray_tpu.wait(refs, num_returns=4, timeout=30)
    assert len(ready) == 4 and not not_ready


def test_wait_caps_num_returns(ray_cluster):
    refs = [add.remote(i, 0) for i in range(5)]
    ray_tpu.get(refs)  # all finished
    ready, not_ready = ray_tpu.wait(refs, num_returns=1)
    assert len(ready) == 1 and len(not_ready) == 4


def test_fire_and_forget_results_evicted(ray_cluster):
    import gc
    from ray_tpu._private import context
    rt = context.get_ctx()
    for _ in range(5):
        add.remote(1, 1)  # refs dropped immediately
    gc.collect()
    time.sleep(2.0)
    stats = rt.state_op("object_store_stats")
    # Results of dropped-ref tasks must not accumulate. Other tests' objects
    # may exist; bound is loose but catches unbounded growth.
    before = stats["num_objects"]
    for _ in range(10):
        add.remote(2, 2)
    gc.collect()
    time.sleep(2.0)
    after = rt.state_op("object_store_stats")["num_objects"]
    assert after <= before + 2


def test_cancel_pending_task(ray_cluster):
    from ray_tpu.exceptions import TaskCancelledError

    @ray_tpu.remote
    def slow():
        time.sleep(3)
        return 1

    # Saturate CPUs so the victim stays queued.
    blockers = [slow.remote() for _ in range(4)]
    victim = slow.remote()
    time.sleep(0.2)
    ray_tpu.cancel(victim)
    with pytest.raises(TaskError) as ei:
        ray_tpu.get(victim, timeout=30)
    assert isinstance(ei.value.cause, TaskCancelledError)
    ray_tpu.get(blockers)


def test_wait_timeout(ray_cluster):
    @ray_tpu.remote
    def slow():
        time.sleep(5)
        return 1

    ref = slow.remote()
    ready, not_ready = ray_tpu.wait([ref], num_returns=1, timeout=0.1)
    assert not ready and not_ready == [ref]


def test_get_timeout(ray_cluster):
    @ray_tpu.remote
    def slow():
        time.sleep(5)

    with pytest.raises(GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.1)


def test_error_propagation(ray_cluster):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    with pytest.raises(TaskError) as ei:
        ray_tpu.get(boom.remote())
    assert isinstance(ei.value.cause, ValueError)
    assert "kaboom" in str(ei.value)


def test_error_through_dependency(ray_cluster):
    @ray_tpu.remote
    def boom():
        raise RuntimeError("upstream")

    # A task consuming a failed upstream ref fails at dependency resolution.
    with pytest.raises(TaskError):
        ray_tpu.get(add.remote(boom.remote(), 1))


def test_nested_task_submission(ray_cluster):
    @ray_tpu.remote
    def outer(n):
        return sum(ray_tpu.get([add.remote(i, 1) for i in range(n)]))

    assert ray_tpu.get(outer.remote(3)) == 6


def test_options_override(ray_cluster):
    f = add.options(name="my_add", max_retries=0)
    assert ray_tpu.get(f.remote(2, 2)) == 4


def test_call_directly_raises(ray_cluster):
    with pytest.raises(TypeError):
        add(1, 2)


def test_cluster_resources(ray_cluster):
    res = ray_tpu.cluster_resources()
    assert res["CPU"] == 4.0


# ---------------- actors ----------------
@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.v = start

    def inc(self, n=1):
        self.v += n
        return self.v

    def read(self):
        return self.v

    def boom(self):
        raise KeyError("actor-err")


def test_actor_basic(ray_cluster):
    c = Counter.remote(5)
    assert ray_tpu.get(c.inc.remote()) == 6
    assert ray_tpu.get(c.read.remote()) == 6


def test_actor_ordering(ray_cluster):
    c = Counter.remote()
    refs = [c.inc.remote() for _ in range(10)]
    assert ray_tpu.get(refs) == list(range(1, 11))


def test_actor_error(ray_cluster):
    c = Counter.remote()
    with pytest.raises(TaskError) as ei:
        ray_tpu.get(c.boom.remote())
    assert isinstance(ei.value.cause, KeyError)
    # Actor survives method errors.
    assert ray_tpu.get(c.inc.remote()) == 1


def test_named_actor(ray_cluster):
    Counter.options(name="global_counter").remote(100)
    h = ray_tpu.get_actor("global_counter")
    assert ray_tpu.get(h.inc.remote()) == 101


def test_actor_handle_to_task(ray_cluster):
    c = Counter.remote()

    @ray_tpu.remote
    def bump(handle):
        return ray_tpu.get(handle.inc.remote())

    assert ray_tpu.get(bump.remote(c)) == 1


def test_actor_method_num_returns(ray_cluster):
    @ray_tpu.remote
    class Splitter:
        @ray_tpu.method(num_returns=2)
        def split(self, pair):
            return pair[0], pair[1]

    s = Splitter.remote()
    a, b = s.split.remote((7, 9))
    assert ray_tpu.get([a, b]) == [7, 9]


def test_async_actor(ray_cluster):
    import asyncio

    @ray_tpu.remote
    class AsyncActor:
        async def work(self, x):
            await asyncio.sleep(0.01)
            return x * 2

    a = AsyncActor.remote()
    assert ray_tpu.get([a.work.remote(i) for i in range(4)]) == [0, 2, 4, 6]


def test_actor_instantiation_direct_raises(ray_cluster):
    with pytest.raises(TypeError):
        Counter()


def test_state_api_lists_actors(ray_cluster):
    from ray_tpu._private import context
    actors = context.get_ctx().state_op("list_actors")
    assert isinstance(actors, list) and len(actors) >= 1
    assert {"actor_id", "state", "name"} <= set(actors[0])


# -------------------------------------------------------- runtime envs
def test_runtime_env_env_vars_task(ray_cluster):
    """env_vars apply inside the task and are REVERTED afterwards (the
    pooled worker is reused); reference _private/runtime_env semantics."""
    @ray_tpu.remote(runtime_env={"env_vars": {"RTPU_TEST_FLAG": "on"}})
    def probe():
        import os
        return os.environ.get("RTPU_TEST_FLAG")

    @ray_tpu.remote
    def probe_clean():
        import os
        return os.environ.get("RTPU_TEST_FLAG")

    assert ray_tpu.get(probe.remote()) == "on"
    assert ray_tpu.get(probe_clean.remote()) is None


def test_runtime_env_working_dir_task(ray_cluster, tmp_path):
    d = tmp_path / "wd"
    d.mkdir()
    (d / "marker.txt").write_text("here")

    @ray_tpu.remote(runtime_env={"working_dir": str(d)})
    def read_marker():
        return open("marker.txt").read()

    assert ray_tpu.get(read_marker.remote()) == "here"


def test_runtime_env_actor_env_vars(ray_cluster):
    @ray_tpu.remote(runtime_env={"env_vars": {"RTPU_ACTOR_VAR": "42"}})
    class EnvActor:
        def probe(self):
            import os
            return os.environ["RTPU_ACTOR_VAR"]

    a = EnvActor.remote()
    assert ray_tpu.get(a.probe.remote()) == "42"


def test_runtime_env_unsupported_keys_raise(ray_cluster):
    with pytest.raises(ValueError, match="unsupported runtime_env"):
        ray_tpu.remote(runtime_env={"nfs_mount": "/x"})(lambda: 1)

    with pytest.raises(TypeError, match="env_vars"):
        ray_tpu.remote(runtime_env={"env_vars": {"A": 1}})(lambda: 1)

    with pytest.raises(ValueError, match="working_dir"):
        ray_tpu.remote(runtime_env={"working_dir": "/nonexistent_xyz"})(
            lambda: 1)


# ------------------------------------------------------------- cancel
def test_cancel_running_task_nonforce(ray_cluster):
    """Non-force cancel raises TaskCancelledError inside the running
    task (reference CancelTask); pure-Python loops observe it."""
    from ray_tpu.exceptions import TaskCancelledError, TaskError

    @ray_tpu.remote
    def spin(n):
        import time
        t0 = time.time()
        x = 0
        while time.time() - t0 < n:   # bytecode loop: async-exc lands
            x += 1
        return x

    ref = spin.remote(60)
    import time
    time.sleep(2.0)                   # let it start executing
    ray_tpu.cancel(ref)
    with pytest.raises(TaskError) as ei:
        ray_tpu.get(ref, timeout=30)
    assert isinstance(ei.value.cause, TaskCancelledError)


def test_cancel_running_task_force_no_retry(ray_cluster):
    """force=True kills the worker; the task must NOT be retried even
    with retries budgeted (cancel beats recovery)."""
    from ray_tpu.exceptions import TaskCancelledError, TaskError

    @ray_tpu.remote(max_retries=3)
    def sleep_forever():
        import time
        time.sleep(600)

    ref = sleep_forever.remote()
    import time
    time.sleep(2.0)
    ray_tpu.cancel(ref, force=True)
    with pytest.raises(TaskError) as ei:
        ray_tpu.get(ref, timeout=30)
    assert isinstance(ei.value.cause, TaskCancelledError)


def test_cancel_infeasible_parked_task(ray_cluster):
    """A task parked as infeasible (no node can fit it) must still be
    cancellable — it sits in no node queue."""
    from ray_tpu.exceptions import TaskCancelledError, TaskError

    @ray_tpu.remote(num_cpus=10_000)
    def impossible():
        return 1

    ref = impossible.remote()
    import time
    time.sleep(0.3)
    ray_tpu.cancel(ref)
    with pytest.raises(TaskError) as ei:
        ray_tpu.get(ref, timeout=20)
    assert isinstance(ei.value.cause, TaskCancelledError)


def test_pipelined_task_stolen_from_blocked_worker(fresh_cluster):
    """Deadlock regression: a task pipelined behind another task on the
    same worker's FIFO, where the front task then blocks in a nested
    get() on the queued one. The scheduler must steal the queued task
    back (UNQUEUE_TASK) and run it elsewhere — without that, the get
    waits on a task that can never start (its exec thread is the one
    blocking)."""
    import time as _t

    @ray_tpu.remote(num_cpus=0)
    def inner():
        return 7

    @ray_tpu.remote(num_cpus=0)
    def outer():
        ref = inner.remote()
        # give the scheduler time to pipeline `inner` behind us on this
        # worker (num_cpus=0 on a cold pool -> we are the only worker)
        _t.sleep(0.5)
        return ray_tpu.get(ref)

    assert ray_tpu.get(outer.remote(), timeout=90) == 7
