"""Host-side collective group across actor processes.

Parity target: reference util/collective tests (allreduce/allgather/
broadcast/reducescatter/send/recv between actors over the gloo CPU
backend) — here over the coordinator-actor + shm transport.
"""
import numpy as np
import pytest

import ray_tpu


def _make_worker():
    @ray_tpu.remote
    class Worker:
        def __init__(self, rank, world, group):
            self._rank = rank
            self._group = group
            from ray_tpu.util import collective
            collective.init_collective_group(world, rank,
                                             group_name=group)

        def ping(self):
            return "pong"

        def do_allreduce(self, op="sum"):
            from ray_tpu.util import collective
            return collective.allreduce(
                np.full(4, float(self._rank + 1)), op=op,
                group_name=self._group)

        def do_allgather(self):
            from ray_tpu.util import collective
            return collective.allgather(np.array([self._rank]),
                                        group_name=self._group)

        def do_broadcast(self):
            from ray_tpu.util import collective
            return collective.broadcast(
                np.full(3, float(self._rank)), src_rank=1,
                group_name=self._group)

        def do_reducescatter(self):
            from ray_tpu.util import collective
            return collective.reducescatter(
                np.arange(6, dtype=np.float64), group_name=self._group)

        def do_p2p(self):
            from ray_tpu.util import collective
            if self._rank == 0:
                collective.send(np.array([41.0]), dst_rank=1,
                                group_name=self._group)
                collective.send(np.array([42.0]), dst_rank=1,
                                group_name=self._group)
                return None
            a = collective.recv(0, group_name=self._group)
            b = collective.recv(0, group_name=self._group)
            return [float(a[0]), float(b[0])]

        def do_barrier(self):
            from ray_tpu.util import collective
            collective.barrier(group_name=self._group)
            return True
    return Worker


def test_collective_allreduce_allgather_broadcast(ray_cluster):
    Worker = _make_worker()
    # rank 0 first (it creates the coordinator), then the rest
    ws = [Worker.remote(r, 3, "g1") for r in range(3)]
    ray_tpu.get([w.ping.remote() for w in ws])

    out = ray_tpu.get([w.do_allreduce.remote() for w in ws])
    for o in out:
        np.testing.assert_array_equal(o, np.full(4, 6.0))  # 1+2+3

    out = ray_tpu.get([w.do_allreduce.remote("max") for w in ws])
    for o in out:
        np.testing.assert_array_equal(o, np.full(4, 3.0))

    out = ray_tpu.get([w.do_allgather.remote() for w in ws])
    for o in out:
        assert [int(x[0]) for x in o] == [0, 1, 2]

    out = ray_tpu.get([w.do_broadcast.remote() for w in ws])
    for o in out:
        np.testing.assert_array_equal(o, np.full(3, 1.0))  # src_rank=1

    out = ray_tpu.get([w.do_barrier.remote() for w in ws])
    assert out == [True, True, True]
    for w in ws:
        ray_tpu.kill(w)


def test_collective_reducescatter_and_p2p(ray_cluster):
    Worker = _make_worker()
    ws = [Worker.remote(r, 2, "g2") for r in range(2)]
    ray_tpu.get([w.ping.remote() for w in ws])

    out = ray_tpu.get([w.do_reducescatter.remote() for w in ws])
    np.testing.assert_array_equal(out[0], np.array([0., 2., 4.]))
    np.testing.assert_array_equal(out[1], np.array([6., 8., 10.]))

    res = ray_tpu.get([w.do_p2p.remote() for w in ws])
    assert res[1] == [41.0, 42.0]      # ordered p2p delivery
    for w in ws:
        ray_tpu.kill(w)


def test_collective_requires_init(ray_cluster):
    from ray_tpu.util import collective
    with pytest.raises(RuntimeError, match="not initialized"):
        collective.allreduce(np.ones(2), group_name="nope")
