"""The driver entry points must work on a host without n real chips.

Round-1 regression: dryrun_multichip(8) crashed on the 1-chip bench host
because it sliced jax.devices()[:n] without provisioning virtual CPU
devices (MULTICHIP_r01.json rc=1). These tests run under the conftest's
8-device virtual CPU platform, same as the driver's validation pass.
"""
import sys
from pathlib import Path

import jax
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def test_entry_compiles_single_device():
    import __graft_entry__ as g
    fn, args = g.entry()
    out = jax.jit(fn).lower(*args).compile()(*args)
    assert out.shape[:2] == args[1].shape


@pytest.mark.slow
def test_dryrun_multichip_8():
    import __graft_entry__ as g
    g.dryrun_multichip(8)


def test_provision_devices_virtual_cpu():
    import __graft_entry__ as g
    devs = g._provision_devices(8)
    assert len(devs) == 8


def test_mesh_specs_cover_all_axes():
    import __graft_entry__ as g
    axes_seen = set()
    for spec in g._mesh_specs_for(8):
        shape = dict(zip(("pp", "dp", "fsdp", "sp", "ep", "tp"),
                         spec.resolve(8)))
        axes_seen |= {a for a, s in shape.items() if s > 1}
    assert {"dp", "fsdp", "tp", "sp"} <= axes_seen
