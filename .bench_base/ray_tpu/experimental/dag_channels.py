"""Channel-backed compiled DAG execution (the aDAG fast path).

Parity: reference python/ray/dag/compiled_dag_node.py (CompiledDAG with
persistent per-actor exec loops :135-224, execute :2118 returning
CompiledDAGRef) over shared_memory_channel transport — re-designed for
this stack: compilation allocates one mutable shm channel per producer
node (single writer, one reader slot per consumer, plus the driver for
outputs), then installs a long-running exec loop on every actor via the
``__rtpu_apply__`` escape hatch. `execute()` writes the input into the
input channel and returns a CompiledDAGRef whose `get()` reads the
output channel — no task submission, object store traffic, or driver
hop between stages.
"""
from __future__ import annotations

import struct
import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

import ray_tpu
from ray_tpu.experimental.channel import (Channel, ChannelClosed,
                                          ChannelReader, ChannelTimeout,
                                          ChannelWriter)


class AbortFlag:
    """One shared u64 in shm that exec loops poll between bounded channel
    reads, so a dead upstream actor can never wedge a loop forever: the
    driver raises the flag at teardown and every surviving loop exits at
    its next poll (reference CompiledDAG cancels exec loops instead)."""

    def __init__(self, name: str):
        self.name = name
        self._mv = None

    @classmethod
    def create(cls) -> "AbortFlag":
        from ray_tpu._private.object_store import _create_segment
        from ray_tpu._private.specs import SESSION_TAG
        name = f"rtpu_{SESSION_TAG}_abort_{uuid.uuid4().hex[:12]}"
        _create_segment(name, memoryview(bytes(8)))
        return cls(name)

    def _map(self):
        if self._mv is None:
            from ray_tpu._private.object_store import _map_segment
            self._mv = _map_segment(self.name, 8)
        return self._mv

    def set(self) -> None:
        struct.pack_into("<Q", self._map(), 0, 1)

    def is_set(self) -> bool:
        try:
            return struct.unpack_from("<Q", self._map(), 0)[0] != 0
        except BaseException:
            return True                # segment gone == abort

    def destroy(self) -> None:
        from ray_tpu._private.object_store import unlink_segment
        self._mv = None
        unlink_segment(self.name)

    def __reduce__(self):
        return (AbortFlag, (self.name,))


class _Err:
    """Error envelope forwarded through downstream channels so one
    failing node poisons the execution, not the pipeline."""

    def __init__(self, repr_: str):
        self.repr = repr_


def _exec_loop(instance, method_name: str, in_channels: List[Channel],
               in_reader_idx: List[int], arg_spec: List[Tuple],
               kw_spec: Dict[str, Tuple], out_channel: Channel,
               abort: AbortFlag) -> int:
    """Runs INSIDE the actor (one long-lived call): read inputs, run the
    method, write the result; repeats until the upstream closes or the
    driver raises the abort flag (bounded reads — a dead peer can't
    wedge this loop forever)."""
    readers = [ChannelReader(ch, i)
               for ch, i in zip(in_channels, in_reader_idx)]
    writer = ChannelWriter(out_channel)

    def bounded(fn, *a, **kw):
        while True:
            try:
                return fn(*a, timeout=1.0, **kw)
            except ChannelTimeout:
                if abort.is_set():
                    raise ChannelClosed("aborted") from None

    executed = 0
    while True:
        vals: List[Any] = [None] * len(readers)
        err: Any = None
        try:
            if len(readers) == 1:
                vals[0] = bounded(readers[0].read)
            else:
                # overlap schedule (reference dag_node_operation.py
                # intent): consume multi-node inputs in ARRIVAL order —
                # a slow upstream never head-of-line-blocks the inputs
                # that are already published
                pending = set(range(len(readers)))
                poll = 0.005
                while pending:
                    progressed = False
                    for i in list(pending):
                        try:
                            vals[i] = readers[i].read(timeout=poll)
                            pending.discard(i)
                            progressed = True
                        except ChannelTimeout:
                            pass
                    if progressed:
                        poll = 0.005
                    else:
                        # idle between executes: back the poll off so
                        # a parked DAG doesn't burn a core
                        poll = min(poll * 2, 0.25)
                        if abort.is_set():
                            raise ChannelClosed("aborted")
        except ChannelClosed:
            # short ack wait: at teardown the driver may never ack the
            # final output, and a 5s stall here would outlive the
            # driver's loop-exit budget and get this actor killed
            writer.close(timeout=0.5)
            return executed
        for v in vals:
            if isinstance(v, _Err):
                err = v
                break
        if err is None:
            def resolve(spec):
                kind, payload = spec
                return vals[payload] if kind == "n" else payload
            try:
                args = [resolve(s) for s in arg_spec]
                kwargs = {k: resolve(s) for k, s in kw_spec.items()}
                result = getattr(instance, method_name)(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001
                import traceback
                result = _Err("".join(traceback.format_exception(e)))
        else:
            result = err
        try:
            bounded(writer.write, result)
        except ChannelClosed:
            return executed
        executed += 1


class CompiledDAGRef:
    """Result handle for one execute() (reference CompiledDAGRef):
    `get()` reads the output channel(s) in order. ray_tpu.get() accepts
    it directly."""

    def __init__(self, dag: "ChannelCompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._consumed = False

    def get(self, timeout: Optional[float] = 30.0):
        if self._consumed:
            raise ValueError("CompiledDAGRef can only be read once")
        value = self._dag._fetch(self._seq, timeout)
        self._consumed = True          # only after a successful fetch
        if isinstance(value, _Err):
            raise RuntimeError(f"compiled DAG node failed:\n{value.repr}")
        if isinstance(value, list):
            for v in value:
                if isinstance(v, _Err):
                    raise RuntimeError(
                        f"compiled DAG node failed:\n{v.repr}")
        return value


class ChannelCompiledDAG:
    """Channel-transport compiled DAG (single InputNode, every actor
    hosts at most one node)."""

    # executes in flight beyond this are drained into the fetched-
    # results buffer first — each channel slot holds ONE message, so
    # unbounded in-flight writes would deadlock the input writer
    MAX_IN_FLIGHT = 2

    def __init__(self, output, buffer_size_bytes: int = 1 << 20):
        from ray_tpu.dag import (ClassMethodNode, CompiledDAG, InputNode,
                                 MultiOutputNode)
        self._buffer = buffer_size_bytes
        base = CompiledDAG(output)          # reuse toposort + validation
        self._order = base._order
        self._input = base._input
        if self._input is None:
            raise ValueError("channel-mode DAG needs an InputNode")
        self._output = output
        nodes = [n for n in self._order
                 if isinstance(n, ClassMethodNode)]
        if not nodes:
            raise ValueError("channel-mode DAG needs actor nodes")
        actors = [n.actor for n in nodes]
        if len({a._actor_id for a in actors}) != len(actors):
            raise ValueError(
                "channel mode requires each actor to host exactly one "
                "DAG node (an actor's exec loop owns it exclusively)")
        out_nodes = (list(output.outputs)
                     if isinstance(output, MultiOutputNode) else [output])
        for o in out_nodes:
            if not isinstance(o, ClassMethodNode):
                raise ValueError("DAG outputs must be actor nodes")
        self._out_nodes = out_nodes

        # --- consumers per producer (input node included)
        consumers: Dict[int, List] = {id(self._input): []}
        for n in nodes:
            consumers[id(n)] = []
        for n in nodes:
            seen_up = set()
            for up in n.upstream:
                # dedup: a node passing the same upstream twice still
                # reads it through ONE reader slot
                if id(up) in seen_up:
                    continue
                seen_up.add(id(up))
                if isinstance(up, (ClassMethodNode, InputNode)):
                    consumers[id(up)].append(n)
        # the driver reads every output node's channel
        n_extra = {id(n): 0 for n in nodes}
        for o in out_nodes:
            n_extra[id(o)] += 1

        # --- allocate channels
        self._channels: Dict[int, Channel] = {}
        for key, cons in consumers.items():
            extra = n_extra.get(key, 0)
            n_readers = len(cons) + extra
            if n_readers == 0:
                continue
            self._channels[key] = Channel.create(
                capacity=buffer_size_bytes, n_readers=n_readers)
        # reader slot assignment: consumers take slots in order; the
        # driver takes the last slot(s)
        slot: Dict[Tuple[int, int], int] = {}
        for key, cons in consumers.items():
            for i, c in enumerate(cons):
                slot[(key, id(c))] = i

        # --- install exec loops
        self._abort = AbortFlag.create()
        self._loop_refs = []
        self._loop_actors = []
        from ray_tpu.actor import ActorMethod
        for n in nodes:
            in_chs, in_idx, arg_spec, kw_spec = [], [], [], {}
            seen_inputs: Dict[int, int] = {}

            def input_index(up) -> int:
                if id(up) not in seen_inputs:
                    seen_inputs[id(up)] = len(in_chs)
                    in_chs.append(self._channels[id(up)])
                    in_idx.append(slot[(id(up), id(n))])
                return seen_inputs[id(up)]

            for a in n.args:
                if isinstance(a, (ClassMethodNode, InputNode)):
                    arg_spec.append(("n", input_index(a)))
                else:
                    arg_spec.append(("c", a))
            for k, v in n.kwargs.items():
                if isinstance(v, (ClassMethodNode, InputNode)):
                    kw_spec[k] = ("n", input_index(v))
                else:
                    kw_spec[k] = ("c", v)
            method = ActorMethod(n.actor, "__rtpu_apply__", {})
            self._loop_refs.append(method.remote(
                cloudpickle.dumps(_exec_loop), n.method_name, in_chs,
                in_idx, arg_spec, kw_spec, self._channels[id(n)],
                self._abort))
            self._loop_actors.append(n.actor)

        # --- driver endpoints
        self._in_writer = ChannelWriter(self._channels[id(self._input)])
        self._out_readers = []
        taken: Dict[int, int] = {}
        for o in out_nodes:
            ch = self._channels[id(o)]
            base_slot = len(consumers[id(o)]) + taken.get(id(o), 0)
            taken[id(o)] = taken.get(id(o), 0) + 1
            self._out_readers.append(ChannelReader(ch, base_slot))
        self._multi = isinstance(output, MultiOutputNode)
        self._lock = threading.Lock()
        self._next_seq = 0
        self._fetched: Dict[int, Any] = {}
        self._partial_row: List[Any] = []
        self._read_seq = 0
        self.num_executions = 0
        self._torn_down = False

    # ------------------------------------------------------------- api
    def execute(self, *args) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("DAG was torn down")
        if len(args) != 1:
            raise TypeError(f"DAG takes exactly 1 input, got {len(args)}")
        with self._lock:
            # self-drain: pull finished results into _fetched so the
            # pipeline's single-slot channels never back up into an
            # unbounded blocking input write
            while self._next_seq - self._read_seq >= self.MAX_IN_FLIGHT:
                while len(self._partial_row) < len(self._out_readers):
                    r = self._out_readers[len(self._partial_row)]
                    self._partial_row.append(r.read(60.0))
                outs, self._partial_row = self._partial_row, []
                self._fetched[self._read_seq] = (
                    outs if self._multi else outs[0])
                self._read_seq += 1
            self._in_writer.write(args[0], timeout=60.0)
            seq = self._next_seq
            self._next_seq += 1
            self.num_executions += 1
        return CompiledDAGRef(self, seq)

    def _fetch(self, seq: int, timeout: Optional[float]):
        with self._lock:
            while self._read_seq <= seq:
                # _partial_row survives a timeout mid-row: each reader's
                # read consumes its single slot, so a retry must RESUME
                # at the first unread output, never re-read consumed ones
                while len(self._partial_row) < len(self._out_readers):
                    r = self._out_readers[len(self._partial_row)]
                    self._partial_row.append(r.read(timeout))
                outs, self._partial_row = self._partial_row, []
                self._fetched[self._read_seq] = (
                    outs if self._multi else outs[0])
                self._read_seq += 1
            return self._fetched.pop(seq)

    def teardown(self) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        try:
            self._in_writer.close()
        except BaseException:
            pass
        # abort flag unwedges loops blocked on a dead peer's channel
        try:
            self._abort.set()
        except BaseException:
            pass
        remaining = list(zip(self._loop_refs, self._loop_actors))
        try:
            ray_tpu.get(self._loop_refs, timeout=5.0)
            remaining = []
        except BaseException:
            pass
        # kill loops that still haven't exited — destroying segments
        # under a live reader would leave its thread stuck for the
        # actor's lifetime
        for ref, actor in remaining:
            try:
                done, _ = ray_tpu.wait([ref], timeout=0.1)
                if not done:
                    ray_tpu.kill(actor)
            except BaseException:
                pass
        for ch in self._channels.values():
            ch.destroy()
        try:
            self._abort.destroy()
        except BaseException:
            pass

    def __del__(self):
        try:
            self.teardown()
        except BaseException:
            pass
