"""ray_tpu.experimental: channels and other pre-stable APIs (reference
python/ray/experimental/)."""
from ray_tpu.experimental.channel import (Channel, ChannelClosed,
                                          ChannelReader, ChannelTimeout,
                                          ChannelWriter)

__all__ = ["Channel", "ChannelReader", "ChannelWriter", "ChannelClosed",
           "ChannelTimeout"]
