"""RMSNorm / LayerNorm with fused Pallas forward.

Memory-bound ops: the win over XLA's default lowering is avoiding the
extra HBM round-trip between the moment computation and the scale apply.
Backward is left to XLA via a reference-recompute custom_vjp — the
recompute is VMEM-resident and fuses into the surrounding backward.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANE = 128


def _interpret() -> bool:
    from ray_tpu.ops.dispatch import on_tpu
    return not on_tpu()


# ---------------------------------------------------------------- rmsnorm
def rms_norm_reference(x: jax.Array, w: jax.Array,
                       eps: float = 1e-6) -> jax.Array:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * (1.0 + w.astype(jnp.float32))).astype(dtype)


def _rms_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * (1.0 + w_ref[:].astype(jnp.float32))).astype(o_ref.dtype)


def _rms_fwd_pallas(x2d: jax.Array, w: jax.Array, eps: float,
                    block_rows: int) -> jax.Array:
    rows, d = x2d.shape
    grid = (pl.cdiv(rows, block_rows),)
    return pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((rows, d), x2d.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        interpret=_interpret(),
    )(x2d, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm(x: jax.Array, w: jax.Array, eps: float = 1e-6) -> jax.Array:
    """y = x * rsqrt(mean(x^2) + eps) * (1 + w), fused.

    Follows the (1 + w) convention (gemma/llama3 style) so a zero-init
    scale is the identity. Accepts any leading shape; normalises the
    last axis.
    """
    lead = x.shape[:-1]
    d = x.shape[-1]
    x2d = x.reshape(-1, d)
    rows = x2d.shape[0]
    block = min(rows, 256)
    if rows % block:
        return rms_norm_reference(x, w, eps)
    out = _rms_fwd_pallas(x2d, w, eps, block)
    return out.reshape(*lead, d)


def _rms_fwd_rule(x, w, eps):
    return rms_norm(x, w, eps), (x, w)


def _rms_bwd_rule(eps, res, g):
    x, w = res
    _, vjp = jax.vjp(lambda x_, w_: rms_norm_reference(x_, w_, eps), x, w)
    return vjp(g)


rms_norm.defvjp(_rms_fwd_rule, _rms_bwd_rule)


# -------------------------------------------------------------- layernorm
def layer_norm(x: jax.Array, w: jax.Array, b: jax.Array,
               eps: float = 1e-6) -> jax.Array:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * w.astype(jnp.float32) + b.astype(jnp.float32)).astype(dtype)
