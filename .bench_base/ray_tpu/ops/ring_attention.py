"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Net-new capability relative to the reference, which has no sequence/context
parallelism in-tree (SURVEY.md §2.4, §5.7). Each device holds a sequence
shard of Q/K/V; K/V chunks rotate around the `sp` ring via
`lax.ppermute` while every device accumulates its Q shard's attention
with online-softmax merging — O(seq/n) memory per device, compute
overlapped with ICI transfer by XLA's latency-hiding scheduler.

Call :func:`ring_attention` inside `shard_map` (it uses collective axis
ops), or :func:`ring_attention_sharded` for a jit-level entry point that
wraps the shard_map with standard (batch, heads, seq) specs.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import DEFAULT_MASK_VALUE, flash_attention

NEG_INF = -jnp.inf


def _chunk_attention(q, k, v, q_off, k_off, causal, sm_scale):
    """Attention of a Q shard against one K/V chunk; returns (o, lse) f32.

    GQA-aware: q has (b, h, sq, d) with h = g * kvh; k/v stay at their
    raw kv-head count and are matched via a grouped einsum, so the ring
    never transfers or stores repeated K/V. Offsets are *global* token
    positions of the shard starts, so the causal mask is exact across
    ring steps. Fully-masked rows yield lse = -inf and a zero output,
    which the merge treats as "no mass".
    """
    b, h, sq, d = q.shape
    kvh, sk = k.shape[1], k.shape[2]
    g = h // kvh
    qf = q.astype(jnp.float32).reshape(b, kvh, g, sq, d)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    s = jnp.einsum("bngqd,bnkd->bngqk", qf, kf,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        qi = q_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        ki = k_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(qi >= ki, s, DEFAULT_MASK_VALUE)
    m = jnp.max(s, axis=-1)                                 # (b,n,g,sq)
    # Rows with every entry masked: treat as zero mass.
    dead = m <= DEFAULT_MASK_VALUE / 2
    m_safe = jnp.where(dead, 0.0, m)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(dead[..., None], 0.0, p)
    l = jnp.sum(p, axis=-1)
    # Normalised partial output: _merge expects each partial to be a
    # proper softmax-weighted average with its mass carried in lse.
    o = jnp.einsum("bngqk,bnkd->bngqd", p, vf) / jnp.maximum(
        l, 1e-37)[..., None]
    lse = jnp.where(dead | (l == 0.0), NEG_INF, m_safe + jnp.log(
        jnp.maximum(l, 1e-37)))
    return o.reshape(b, h, sq, d), lse.reshape(b, h, sq)


def _merge(o1, lse1, o2, lse2):
    """Combine two partial attention results via their log-sum-exps."""
    m = jnp.maximum(lse1, lse2)
    m_safe = jnp.where(jnp.isinf(m) & (m < 0), 0.0, m)
    w1 = jnp.where(jnp.isinf(lse1) & (lse1 < 0), 0.0,
                   jnp.exp(lse1 - m_safe))
    w2 = jnp.where(jnp.isinf(lse2) & (lse2 < 0), 0.0,
                   jnp.exp(lse2 - m_safe))
    tot = w1 + w2
    safe_tot = jnp.maximum(tot, 1e-37)
    o = (o1 * w1[..., None] + o2 * w2[..., None]) / safe_tot[..., None]
    o = jnp.where(tot[..., None] == 0.0, 0.0, o)
    lse = jnp.where(tot == 0.0, NEG_INF, m_safe + jnp.log(safe_tot))
    return o, lse


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis: str = "sp", causal: bool = True,
                   sm_scale: Optional[float] = None) -> jax.Array:
    """Exact attention over seq shards; call inside shard_map.

    q (b, h, s_local, d); k/v (b, kvh, s_local, d). The number of ring
    steps is the static mesh axis size, so the loop unrolls at trace
    time and XLA overlaps each step's ppermute with the previous step's
    compute.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    h, kvh = q.shape[1], k.shape[1]
    if h % kvh:
        raise ValueError(
            f"num_heads ({h}) must be a multiple of num_kv_heads ({kvh})")
    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    s_local = q.shape[2]
    q_off = idx * s_local

    perm = [(i, (i + 1) % n) for i in range(n)]
    o = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    lse = jnp.full(q.shape[:3], NEG_INF, jnp.float32)
    kr, vr = k, v
    # Remat each chunk so backward recomputes the (s_local, s_local)
    # scores instead of saving them per ring step — keeps the O(seq/n)
    # memory claim true under jax.grad.
    chunk = jax.checkpoint(_chunk_attention, static_argnums=(5, 6))
    for r in range(n):
        # chunk currently held arrived from device (idx - r) mod n
        k_off = ((idx - r) % n) * s_local
        o_r, lse_r = chunk(q, kr, vr, q_off, k_off, causal, sm_scale)
        o, lse = _merge(o, lse, o_r, lse_r)
        if r != n - 1:
            kr = lax.ppermute(kr, axis, perm)
            vr = lax.ppermute(vr, axis, perm)
    return o.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, causal: bool = True,
                           sm_scale: Optional[float] = None,
                           axis: str = "sp") -> jax.Array:
    """jit-level wrapper: shards seq over `axis`, batch over the data
    axes present in the mesh (dp/fsdp), heads over tp when present, and
    runs the ring. Falls back to flash/reference attention when the
    sequence axis is trivial.

    Works on any user-built Mesh: specs are assembled from the axes the
    mesh actually has, so a mesh lacking dp/fsdp/tp (e.g. a bare
    ``Mesh(devs, ("sp",))``) shards only the sequence axis.
    """
    if mesh.shape.get(axis, 1) == 1:
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    # Only reference axes that exist in the mesh AND are nontrivial —
    # a spec naming an absent axis raises inside shard_map.
    batch_axes = tuple(a for a in ("dp", "fsdp")
                       if a != axis and mesh.shape.get(a, 1) > 1)
    head_axis = "tp" if (axis != "tp"
                         and mesh.shape.get("tp", 1) > 1) else None
    tp = mesh.shape[head_axis] if head_axis else 1
    h, kvh = q.shape[1], k.shape[1]
    spec_q = P(batch_axes or None, head_axis, axis, None)
    if kvh % tp == 0:
        # kv heads shard over tp alongside q heads.
        spec_kv = spec_q
    elif kvh == 1:
        # MQA: the single kv head replicates over tp; every query head
        # maps to it, so the local-shape grouping in _chunk_attention is
        # trivially correct. (General kvh>1 replication is NOT safe:
        # spec_q gives each tp device a contiguous global head block,
        # and the chunk kernel's local grouping would misalign q groups
        # to kv heads — so any other non-divisible case falls through to
        # the explicit repeat below.)
        spec_kv = P(batch_axes or None, None, axis, None)
    else:
        # Last resort: materialise the GQA repeat so K/V carry Q's head
        # spec. Costs n_heads/kv_heads x in K/V memory and ring-transfer
        # volume — prefer kv_heads % tp == 0 configs on real workloads.
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
        spec_kv = spec_q
    fn = jax.shard_map(
        lambda q_, k_, v_: ring_attention(q_, k_, v_, axis=axis,
                                          causal=causal, sm_scale=sm_scale),
        mesh=mesh, in_specs=(spec_q, spec_kv, spec_kv), out_specs=spec_q,
        check_vma=False)
    return fn(q, k, v)
