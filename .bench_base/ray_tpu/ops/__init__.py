"""TPU kernel layer: Pallas kernels for the hot ops, JAX references for CPU.

The reference framework has no kernel layer of its own (it orchestrates
torch/CUDA); ray_tpu's compute path is JAX/XLA and the ops here are where
hand-written Pallas beats XLA's default lowering — attention above all.
Every op has a pure-JAX reference implementation used (a) on CPU, (b) as
the ground truth in tests; Pallas kernels run in interpreter mode on CPU
so the same code path is testable without hardware.
"""
from ray_tpu.ops.norms import rms_norm, layer_norm  # noqa: F401
from ray_tpu.ops.rope import apply_rope, rope_frequencies  # noqa: F401
from ray_tpu.ops.losses import softmax_cross_entropy  # noqa: F401
from ray_tpu.ops.attention import (  # noqa: F401
    flash_attention,
    mha_reference,
)
from ray_tpu.ops.ring_attention import ring_attention  # noqa: F401
