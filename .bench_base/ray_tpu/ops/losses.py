"""Loss ops. Cross-entropy is computed in f32 with the max-subtracted
log-sum-exp; supports a vocab-sharded (tp) variant where each shard holds
a slice of the logits and the reduction runs over the mesh axis.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          mask: Optional[jax.Array] = None,
                          z_loss: float = 0.0):
    """Token-level CE. logits (..., vocab) f32/bf16; labels int (...,).

    Returns (mean_loss, per_token_loss). `mask` (same shape as labels,
    1=count) excludes padding from the mean. `z_loss` adds the standard
    logsumexp^2 regulariser (stabilises f32->bf16 logits drift).
    """
    logits = logits.astype(jnp.float32)
    # No stop_gradient on the max: the two m-terms must cancel in the
    # VJP (a half-stopped max adds a spurious one_hot(argmax) to the
    # gradient of every token).
    m = jnp.max(logits, axis=-1, keepdims=True)
    shifted = logits - m
    lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) + m[..., 0]
    label_logit = jnp.take_along_axis(
        logits, labels[..., None], axis=-1)[..., 0]
    per_token = lse - label_logit
    if z_loss:
        per_token = per_token + z_loss * jnp.square(lse)
    if mask is None:
        return jnp.mean(per_token), per_token
    mask = mask.astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.sum(per_token * mask) / denom, per_token


def chunked_lm_loss(x: jax.Array, head: jax.Array, labels: jax.Array,
                    mask: Optional[jax.Array] = None,
                    chunk_size: int = 512):
    """LM head projection + CE, scanned over sequence chunks with remat.

    Avoids materialising the full (b, s, vocab) f32 logits (the dominant
    activation on 30k+ vocabs): each chunk's logits exist only inside a
    rematerialised scan step, cutting peak memory by s/chunk_size.
    x: (b, s, e) final hidden states; head: (e, vocab); labels (b, s).
    Returns mean loss over unmasked positions.
    """
    b, s, e = x.shape
    if mask is None:
        mask = jnp.ones((b, s), jnp.float32)
    if s % chunk_size:
        # pad the tail chunk (mask 0 excludes padding from the loss)
        pad = chunk_size - s % chunk_size
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
        s += pad
    n = s // chunk_size
    xs = x.reshape(b, n, chunk_size, e).transpose(1, 0, 2, 3)
    ls = labels.reshape(b, n, chunk_size).transpose(1, 0, 2)
    ms = mask.astype(jnp.float32).reshape(
        b, n, chunk_size).transpose(1, 0, 2)

    def body(carry, blk):
        xc, lc, mc = blk
        logits = (xc @ head).astype(jnp.float32)
        _, per_token = softmax_cross_entropy(logits, lc)
        return (carry[0] + jnp.sum(per_token * mc),
                carry[1] + jnp.sum(mc)), None

    (total, denom), _ = lax.scan(
        jax.checkpoint(body, prevent_cse=False), (0.0, 0.0),
        (xs, ls, ms))
    return total / jnp.maximum(denom, 1.0)


def sharded_softmax_cross_entropy(local_logits: jax.Array,
                                  labels: jax.Array,
                                  axis: str,
                                  vocab_shard_size: int,
                                  mask: Optional[jax.Array] = None):
    """CE when the vocab dim is sharded over mesh `axis` (inside shard_map).

    Each device holds logits[..., lo:lo+shard]; the logsumexp and the
    label-logit gather are psum-reduced so no device materialises the
    full vocab — the tp-sharded LM head never all-gathers its output.
    """
    local_logits = local_logits.astype(jnp.float32)
    lo = lax.axis_index(axis) * vocab_shard_size
    gmax = lax.pmax(jnp.max(local_logits, axis=-1), axis)
    shifted = local_logits - gmax[..., None]
    sumexp = lax.psum(jnp.sum(jnp.exp(shifted), axis=-1), axis)
    lse = jnp.log(sumexp) + gmax
    local_label = labels - lo
    in_shard = (local_label >= 0) & (local_label < vocab_shard_size)
    safe = jnp.clip(local_label, 0, vocab_shard_size - 1)
    picked = jnp.take_along_axis(local_logits, safe[..., None],
                                 axis=-1)[..., 0]
    label_logit = lax.psum(jnp.where(in_shard, picked, 0.0), axis)
    per_token = lse - label_logit
    if mask is None:
        return jnp.mean(per_token), per_token
    mask = mask.astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.sum(per_token * mask) / denom, per_token
