"""Rotary position embeddings (RoPE).

Pure JAX: RoPE is elementwise sin/cos mul-add and XLA fuses it into the
surrounding QK projections; a hand kernel buys nothing here. Supports an
absolute `positions` argument so sequence-parallel shards (each holding a
seq slice) rotate with their *global* positions — required for ring
attention (ray_tpu/ops/ring_attention.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float = 10000.0) -> jax.Array:
    """Inverse frequencies for each rotated pair, shape (head_dim//2,)."""
    if head_dim % 2:
        raise ValueError(f"head_dim must be even, got {head_dim}")
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta ** exponent)


def rope_cos_sin(positions: jax.Array, head_dim: int,
                 theta: float = 10000.0):
    """Precompute (cos, sin), each (..., seq, 1, head_dim//2) f32.

    Compute once per forward pass and reuse across layers/remat passes —
    the transcendentals are VPU-expensive and identical for every layer.
    """
    inv_freq = rope_frequencies(head_dim, theta)
    angles = positions[..., None].astype(jnp.float32) * inv_freq
    angles = angles[..., None, :]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope_cached(x: jax.Array, cos: jax.Array,
                      sin: jax.Array) -> jax.Array:
    """Rotate x (..., seq, heads, head_dim) by precomputed cos/sin."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                          axis=-1)
    return out.astype(x.dtype)


def apply_rope(x: jax.Array, positions: jax.Array,
               theta: float = 10000.0) -> jax.Array:
    """Rotate x of shape (..., seq, heads, head_dim) by per-token angles.

    positions: integer array broadcastable to x.shape[:-2] + (seq,) —
    usually (batch, seq) or (seq,). Split-halves convention (llama):
    the first half of head_dim pairs with the second half.
    """
    cos, sin = rope_cos_sin(positions, x.shape[-1], theta)
    return apply_rope_cached(x, cos, sin)
