"""Target-platform resolution for Pallas kernel dispatch.

Pallas TPU kernels must run in interpret mode on CPU, and the decision
has to follow the devices the computation will actually run on — not
`jax.default_backend()`. On a TPU host that builds a virtual CPU mesh
(the multi-chip dry run, tests), the default backend says "tpu" while
the mesh says "cpu"; keying off the default backend then lowers a
compiled TPU kernel onto CPU, which XLA rejects.

Ops call `on_tpu()`; code that knows its target devices (a model bound
to a mesh, a trainer) wraps tracing in `compute_platform(...)`. The
override is a contextvar read at *trace* time, so it composes with jit:
whatever platform is active while the function is being traced wins.
"""
from __future__ import annotations

import contextlib
import contextvars
from typing import Iterator, Optional

import jax

_PLATFORM_OVERRIDE: contextvars.ContextVar[Optional[str]] = (
    contextvars.ContextVar("ray_tpu_compute_platform", default=None))


def mesh_platform(mesh) -> str:
    """Platform string ("tpu"/"cpu"/...) of a Mesh's devices."""
    return mesh.devices.flat[0].platform


@contextlib.contextmanager
def compute_platform(platform: Optional[str]) -> Iterator[None]:
    """Pin the platform ops should compile for while tracing under this
    context. `None` is a no-op (defer to the default backend)."""
    if platform is None:
        yield
        return
    token = _PLATFORM_OVERRIDE.set(platform)
    try:
        yield
    finally:
        _PLATFORM_OVERRIDE.reset(token)


def target_platform() -> str:
    override = _PLATFORM_OVERRIDE.get()
    if override is not None:
        return override
    return jax.default_backend()


def on_tpu() -> bool:
    return target_platform() == "tpu"
