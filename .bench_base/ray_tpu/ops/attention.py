"""Flash attention: Pallas TPU kernels, forward AND backward.

The hot op of the model zoo. Forward is an online-softmax kernel that
streams K/V blocks through VMEM on a (batch, head, q-block, k-block)
grid — O(seq) memory, MXU-shaped matmuls, causal blocks above the
diagonal skipped. Backward is two Pallas kernels sharing the flash
recomputation: a dK/dV kernel on a (b, h, k-block, q-block) grid and a
dQ kernel on (b, h, q-block, k-block), both computing scores in the
TRANSPOSED (block_k, block_q) orientation so the per-row stats (lse,
delta) broadcast along sublanes — the cheap direction — instead of
needing lane-expanded copies; dQ is produced as (b, h, d, s) and
transposed once by XLA. A blockwise lax.scan backward is kept as the
cross-check/fallback path (`_flash_bwd_xla`).

Layout: (batch, num_heads, seq, head_dim). GQA supported: K/V may have
fewer heads (num_kv_heads must divide num_heads) — the kernel maps query
head h to kv head h // (num_heads // num_kv_heads) in the BlockSpec
index map, no materialised repeat.

On non-TPU backends the public `flash_attention` falls back to the
reference einsum implementation; the kernel itself still runs anywhere
via the Pallas interpreter (used by tests).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


# ------------------------------------------------------------- reference
def mha_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                  causal: bool = True,
                  sm_scale: Optional[float] = None,
                  bias: Optional[jax.Array] = None) -> jax.Array:
    """Plain einsum attention; ground truth + CPU path.

    q: (b, h, s, d); k/v: (b, kvh, s, d) with kvh | h.
    """
    b, h, sq, d = q.shape
    kvh, sk = k.shape[1], k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if kvh != h:
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if bias is not None:
        logits = logits + bias
    if causal:
        qi = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        ki = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        logits = jnp.where(qi >= ki, logits, DEFAULT_MASK_VALUE)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


# ----------------------------------------------------------- forward krn
def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref, *,
                      sm_scale: float, causal: bool,
                      block_q: int, block_k: int, seq_k: int):
    i = pl.program_id(2)           # q block
    j = pl.program_id(3)           # k block
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Causal: skip blocks strictly above the diagonal.
    run = (not causal) or (j * block_k <= i * block_q + block_q - 1)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (bq, bk)
        ki = j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            qi = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(qi >= ki, s, DEFAULT_MASK_VALUE)
        if seq_k % block_k:
            # tail K block: mask padding columns past the true length,
            # and zero V's padding rows — they hold garbage and p=0
            # does not neutralise NaN (0 * NaN = NaN).
            s = jnp.where(ki < seq_k, s, DEFAULT_MASK_VALUE)
            vrows = j * block_k + lax.broadcasted_iota(
                jnp.int32, v.shape, 0)
            v = jnp.where(vrows < seq_k, v, 0)
        m_prev = m_ref[:, :1]                      # (bq, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)            # rescale factor
        p = jnp.exp(s - m_new)                     # (bq, bk)
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _final():
        l = l_ref[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0, :, :] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse = m_ref[:, :1] + jnp.log(safe_l)            # (bq, 1)
        # lse laid out (b, h, 8, sq): an (8, block_q) block keeps the
        # last-two-dims (8, 128) Mosaic tiling rule; sublanes broadcast.
        lse_ref[0, 0, :, :] = jnp.broadcast_to(lse[:, 0][None, :],
                                               (8, lse.shape[0]))


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    b, h, sq, d = q.shape
    kvh, sk = k.shape[1], k.shape[2]
    if h % kvh:
        raise ValueError(
            f"num_heads ({h}) must be a multiple of num_kv_heads ({kvh})")
    group = h // kvh
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    grid = (b, h, pl.cdiv(sq, block_q), pl.cdiv(sk, block_k))
    kernel = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_k=sk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, i, j: (b_, h_ // group, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, i, j: (b_, h_ // group, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, 8, block_q),
                         lambda b_, h_, i, j: (b_, h_, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, 8, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),     # acc
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((block_q, 128), jnp.float32),   # running sum
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[:, :, 0, :]


# ---------------------------------------------------- backward (pallas)
def _flash_bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref,
                           dk_ref, dv_ref, dk_acc, dv_acc, *,
                           sm_scale: float, causal: bool,
                           block_q: int, block_k: int, seq_q: int):
    j = pl.program_id(2)           # k block (parallel)
    i = pl.program_id(3)           # q block (inner scan)
    nq = pl.num_programs(3)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    # Causal: k block j only sees q blocks whose max q index reaches it.
    run = (not causal) or (i * block_q + block_q - 1 >= j * block_k)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        if seq_q % block_q:
            # q/do padding rows hold garbage and are CONTRACTED into
            # dk/dv below — zero them (p=0 does not neutralise NaN).
            qrows = i * block_q + lax.broadcasted_iota(
                jnp.int32, q.shape, 0)
            q = jnp.where(qrows < seq_q, q, 0)
            do = jnp.where(qrows < seq_q, do, 0)
        lse = lse_ref[0, 0, 0:1, :]            # (1, block_q) f32
        dlt = dlt_ref[0, 0, 0:1, :]            # (1, block_q) f32
        # Transposed scores: rows = k positions, cols = q positions.
        st = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # (bk, bq)
        rows = j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 0)
        cols = i * block_q + lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 1)
        valid = None
        if causal:
            valid = rows <= cols
        if seq_q % block_q:
            vq = cols < seq_q                  # q-tail: garbage columns
            valid = vq if valid is None else (valid & vq)
        pt = jnp.exp(st - lse)                 # (bk, bq)
        if valid is not None:
            pt = jnp.where(valid, pt, 0.0)
        dv_acc[:] += jax.lax.dot_general(
            pt.astype(do.dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bk, d)
        dpt = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bk, bq)
        dst = pt * (dpt - dlt) * sm_scale
        if valid is not None:                  # kill 0*inf NaNs from tails
            dst = jnp.where(valid, dst, 0.0)
        dk_acc[:] += jax.lax.dot_general(
            dst.astype(q.dtype), q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bk, d)

    @pl.when(i == nq - 1)
    def _final():
        dk_ref[0, 0, :, :] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref,
                         dqt_ref, dqt_acc, *,
                         sm_scale: float, causal: bool,
                         block_q: int, block_k: int, seq_k: int):
    i = pl.program_id(2)           # q block (parallel)
    j = pl.program_id(3)           # k block (inner scan)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dqt_acc[:] = jnp.zeros_like(dqt_acc)

    run = (not causal) or (j * block_k <= i * block_q + block_q - 1)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        if seq_k % block_k:
            # k padding rows are contracted into dq — zero the garbage.
            krows = j * block_k + lax.broadcasted_iota(
                jnp.int32, k.shape, 0)
            k = jnp.where(krows < seq_k, k, 0)
        lse = lse_ref[0, 0, 0:1, :]
        dlt = dlt_ref[0, 0, 0:1, :]
        st = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # (bk, bq)
        rows = j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 0)
        cols = i * block_q + lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 1)
        valid = None
        if causal:
            valid = rows <= cols
        if seq_k % block_k:
            vk = rows < seq_k                  # k-tail: garbage rows feed
            valid = vk if valid is None else (valid & vk)  # the contraction
        pt = jnp.exp(st - lse)
        if valid is not None:
            pt = jnp.where(valid, pt, 0.0)
        dpt = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bk, bq)
        dst = pt * (dpt - dlt) * sm_scale
        if valid is not None:
            dst = jnp.where(valid, dst, 0.0)
        # dq^T accumulation: (d, bq) = k^T (d, bk) @ ds^T (bk, bq).
        dqt_acc[:] += jax.lax.dot_general(
            k, dst.astype(k.dtype), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _final():
        dqt_ref[0, 0, :, :] = dqt_acc[:].astype(dqt_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, do, causal, sm_scale,
                      block_q, block_k, interpret):
    """Full Pallas backward: returns (dq, dk, dv)."""
    b, h, sq, d = q.shape
    kvh, sk = k.shape[1], k.shape[2]
    group = h // kvh
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    nq, nk = pl.cdiv(sq, block_q), pl.cdiv(sk, block_k)

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                                  # (b, h, sq)
    # Sublane-broadcast stats layout (b, h, 8, sq): tiles (8, block_q)
    # satisfy Mosaic's (8, 128) rule; kernels read row 0 as (1, block_q).
    lse8 = jnp.broadcast_to(lse[:, :, None, :], (b, h, 8, sq))
    dlt8 = jnp.broadcast_to(delta[:, :, None, :], (b, h, 8, sq))

    # -------- dk/dv: grid (b, h, k-block, q-block), q innermost --------
    dkdv_out_dtype = jnp.float32 if group > 1 else k.dtype
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkdv_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, seq_q=sq),
        grid=(b, h, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, j, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, j, i: (b_, h_ // group, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, j, i: (b_, h_ // group, j, 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, j, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, 8, block_q),
                         lambda b_, h_, j, i: (b_, h_, 0, i)),
            pl.BlockSpec((1, 1, 8, block_q),
                         lambda b_, h_, j, i: (b_, h_, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, j, i: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, j, i: (b_, h_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), dkdv_out_dtype),
            jax.ShapeDtypeStruct((b, h, sk, d), dkdv_out_dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse8, dlt8)
    if group > 1:
        dk = dk.reshape(b, kvh, group, sk, d).sum(axis=2).astype(k.dtype)
        dv = dv.reshape(b, kvh, group, sk, d).sum(axis=2).astype(v.dtype)

    # -------- dq: grid (b, h, q-block, k-block), k innermost -----------
    dqt = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, seq_k=sk),
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, i, j: (b_, h_ // group, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, i, j: (b_, h_ // group, j, 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, 8, block_q),
                         lambda b_, h_, i, j: (b_, h_, 0, i)),
            pl.BlockSpec((1, 1, 8, block_q),
                         lambda b_, h_, i, j: (b_, h_, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, 1, d, block_q),
                               lambda b_, h_, i, j: (b_, h_, 0, i)),
        out_shape=jax.ShapeDtypeStruct((b, h, d, sq), q.dtype),
        scratch_shapes=[pltpu.VMEM((d, block_q), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse8, dlt8)
    dq = dqt.swapaxes(2, 3)                    # one XLA transpose
    return dq, dk, dv


# ------------------------------------------------ backward (xla check)
def _flash_bwd_xla(q, k, v, o, lse, do, causal, sm_scale, block_k):
    """Blockwise flash backward: scan over K blocks; O(seq·block) memory."""
    b, h, sq, d = q.shape
    kvh, sk = k.shape[1], k.shape[2]
    group = h // kvh
    if group != 1:
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    # Keep matmul operands in the input dtype (bf16 on TPU) with f32
    # accumulation — upcasting operands would force f32 MXU passes.
    kf, vf = k, v
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)  # (b,h,sq)

    block_k = min(block_k, sk)
    sk_pad = ((sk + block_k - 1) // block_k) * block_k
    if sk_pad != sk:
        pad = [(0, 0), (0, 0), (0, sk_pad - sk), (0, 0)]
        kf = jnp.pad(kf, pad)
        vf = jnp.pad(vf, pad)
    nk = sk_pad // block_k
    kb = kf.reshape(b, h, nk, block_k, d).transpose(2, 0, 1, 3, 4)
    vb = vf.reshape(b, h, nk, block_k, d).transpose(2, 0, 1, 3, 4)
    qi = lax.broadcasted_iota(jnp.int32, (sq, block_k), 0)

    def step(dq, blk):
        j, k_j, v_j = blk                                  # (b,h,bk,d)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_j,
                       preferred_element_type=jnp.float32) * sm_scale
        ki = j * block_k + lax.broadcasted_iota(
            jnp.int32, (sq, block_k), 1)
        valid = ki < sk
        if causal:
            valid = valid & (qi >= ki)
        if causal or sk_pad != sk:
            s = jnp.where(valid, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse[..., None])                    # (b,h,sq,bk) f32
        pc = p.astype(q.dtype)
        dv_j = jnp.einsum("bhqk,bhqd->bhkd", pc, do,
                          preferred_element_type=jnp.float32)
        dp = jnp.einsum("bhqd,bhkd->bhqk", do, v_j,
                        preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[..., None]) * sm_scale).astype(q.dtype)
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, k_j,
                             preferred_element_type=jnp.float32)
        dk_j = jnp.einsum("bhqk,bhqd->bhkd", ds, q,
                          preferred_element_type=jnp.float32)
        return dq, (dk_j, dv_j)

    dq0 = jnp.zeros(q.shape, jnp.float32)  # f32 accumulator across blocks
    dq, (dkb, dvb) = lax.scan(
        step, dq0, (jnp.arange(nk), kb, vb))
    dk = dkb.transpose(1, 2, 0, 3, 4).reshape(b, h, sk_pad, d)[:, :, :sk]
    dv = dvb.transpose(1, 2, 0, 3, 4).reshape(b, h, sk_pad, d)[:, :, :sk]
    if group != 1:
        dk = dk.reshape(b, kvh, group, sk, d).sum(axis=2)
        dv = dv.reshape(b, kvh, group, sk, d).sum(axis=2)
    return dq.astype(q.dtype), dk.astype(q.dtype), dv.astype(q.dtype)


# ----------------------------------------------------------- public API
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    """Returns (out, lse); lse has stop-gradient semantics (its cotangent
    is ignored by the VJP — it is an auxiliary statistic, not a loss
    term)."""
    return _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                      interpret)


def _flash_fwd_rule(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret)
    return (out, lse), (q, k, v, out, lse)


def _flash_bwd_rule(causal, sm_scale, block_q, block_k, interpret, res, g):
    do, _g_lse = g  # lse cotangent dropped by design (see _flash docstring)
    q, k, v, out, lse = res
    return _flash_bwd_pallas(q, k, v, out, lse, do, causal, sm_scale,
                             block_q, block_k, interpret)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    return_lse: bool = False):
    """Dispatching entry point: Pallas on TPU, reference elsewhere.

    Shapes: q (b, h, s, d); k/v (b, kvh, s, d), kvh | h.
    """
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    from ray_tpu.ops.dispatch import on_tpu as _on_tpu
    on_tpu = _on_tpu()
    if return_lse:
        return _flash(q, k, v, causal, sm_scale, block_q, block_k,
                      not on_tpu)
    if not on_tpu:
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    return _flash(q, k, v, causal, sm_scale, block_q, block_k, False)[0]


def flash_attention_kernel(q, k, v, causal=True, sm_scale=None,
                           block_q=128, block_k=128):
    """Force the Pallas kernel path (interpreter off-TPU) — test hook."""
    from ray_tpu.ops.dispatch import on_tpu as _on_tpu
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash(q, k, v, causal, sm_scale, block_q, block_k,
                  not _on_tpu())[0]


# --------------------------------------- remat-saveable attention path
#
# Under per-layer `jax.checkpoint`, a custom_vjp flash kernel reruns its
# forward during the backward pass to rebuild residuals — the kernel
# executes twice per step. This path splits the op so the residuals
# (out, lse) are *named public values* a checkpoint policy can save:
#
#   out, lse = fwd kernel        (no AD; pruned from recompute when saved)
#   out, lse = checkpoint_name(...)
#   return _attn_from_saved(q, k, v, stop_grad(out), stop_grad(lse))
#
# `_attn_from_saved` is the only differentiable op: its VJP runs the
# Pallas backward straight from the saved residuals. Cotangents for
# out/lse die at stop_gradient, so the forward kernel is never
# differentiated or (with `save_only_these_names("attn_out","attn_lse")`)
# re-executed. q/k/v are still rematerialised by the layer recompute —
# that is three cheap matmuls + rope, not the attention kernel.

ATTN_RESIDUAL_NAMES = ("attn_out", "attn_lse")


def attn_remat_policy():
    """Checkpoint policy saving exactly the flash-attention residuals."""
    return jax.checkpoint_policies.save_only_these_names(
        *ATTN_RESIDUAL_NAMES)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _attn_from_saved(q, k, v, out, lse, causal, sm_scale, block_q,
                     block_k, interpret):
    return out


def _afs_fwd(q, k, v, out, lse, causal, sm_scale, block_q, block_k,
             interpret):
    return out, (q, k, v, out, lse)


def _afs_bwd(causal, sm_scale, block_q, block_k, interpret, res, do):
    q, k, v, out, lse = res
    dq, dk, dv = _flash_bwd_pallas(q, k, v, out, lse, do, causal,
                                   sm_scale, block_q, block_k, interpret)
    # out/lse arrive through stop_gradient: their cotangents are dropped
    # symbolically, these zeros never materialise.
    return dq, dk, dv, jnp.zeros_like(out), jnp.zeros_like(lse)


_attn_from_saved.defvjp(_afs_fwd, _afs_bwd)


def flash_attention_saveable(q: jax.Array, k: jax.Array, v: jax.Array,
                             causal: bool = True,
                             sm_scale: Optional[float] = None,
                             block_q: int = 128, block_k: int = 128,
                             interpret: Optional[bool] = None) -> jax.Array:
    """Flash attention whose residuals survive `jax.checkpoint` when the
    wrapping policy is `attn_remat_policy()` (see block comment above).
    Semantically identical to `flash_attention`; use inside rematted
    layer bodies."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        from ray_tpu.ops.dispatch import on_tpu as _on_tpu
        interpret = not _on_tpu()
    from jax.ad_checkpoint import checkpoint_name
    # Run the forward kernel on gradient-stopped inputs: pallas_call has
    # no JVP rule, and the only differentiable route is _attn_from_saved.
    out, lse = _flash_fwd(lax.stop_gradient(q), lax.stop_gradient(k),
                          lax.stop_gradient(v), causal, sm_scale,
                          block_q, block_k, interpret)
    out = checkpoint_name(out, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return _attn_from_saved(q, k, v, lax.stop_gradient(out),
                            lax.stop_gradient(lse), causal, sm_scale,
                            block_q, block_k, interpret)
