"""ray_tpu.tune: hyperparameter sweeps over trial actors.

Parity: reference python/ray/tune (Tuner, TuneConfig, grid_search,
ASHA). Inside a trainable use `ray_tpu.tune.report` (alias of
ray_tpu.train.report) and `ray_tpu.tune.get_checkpoint`.
"""
from ray_tpu.train.session import get_checkpoint, report  # noqa: F401
from ray_tpu.tune.schedulers import (ASHAScheduler,  # noqa: F401
                                     FIFOScheduler,
                                     PopulationBasedTraining)
from ray_tpu.tune.search import (BasicVariantGenerator, choice,  # noqa: F401
                                 grid_search, loguniform, randint,
                                 Searcher, TPESearcher, uniform)
from ray_tpu.tune.tuner import (ResultGrid, Trial, TuneConfig,  # noqa: F401
                                Tuner)

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "Trial", "ASHAScheduler",
    "FIFOScheduler", "PopulationBasedTraining", "grid_search", "choice",
    "uniform", "loguniform", "randint", "BasicVariantGenerator",
    "Searcher", "TPESearcher", "report", "get_checkpoint",
]
