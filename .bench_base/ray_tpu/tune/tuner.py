"""Tuner + trial controller: concurrent trials, schedulers, searchers,
PBT, distributed (worker-group) trials, resume.

Parity: reference tune/execution/tune_controller.py (trial lifecycle
state machine + event loop), tune/tuner.py (Tuner.fit/restore),
tune/result_grid.py, tune/execution/placement_groups.py (trial PGs) —
re-shaped for this stack:

- a trial is either ONE RayTrainWorker actor (function trainables) or a
  whole PG-placed WorkerGroup (when the trainable is a JaxTrainer), so a
  multi-host SPMD trainer can be tuned with per-report scheduling
  decisions — the reference reaches this through Trainable-wrapping at
  base_trainer.py:567-623, here the controller drives the group
  directly;
- `ray_tpu.train.report(metrics, checkpoint)` works unchanged inside any
  trainable; checkpoints ride the object store as tar bytes (no shared
  fs), which is also the PBT exploit/inherit transport;
- the controller multiplexes trials with `ray_tpu.wait` instead of a
  callback event loop.
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import Result
from ray_tpu.tune.schedulers import (CONTINUE, EXPLOIT, STOP,
                                     FIFOScheduler)
from ray_tpu.tune.search import BasicVariantGenerator, Searcher

PENDING = "PENDING"
RUNNING = "RUNNING"
TERMINATED = "TERMINATED"   # ran to completion (or scheduler max_t)
STOPPED = "STOPPED"         # killed early by the scheduler
ERROR = "ERROR"


@dataclasses.dataclass
class TuneConfig:
    metric: str = "loss"
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: int = 2
    scheduler: Any = None               # default FIFO
    search_alg: Optional[Searcher] = None
    seed: int = 0
    resources_per_trial: Optional[Dict[str, float]] = None
    trial_poll_timeout: float = 120.0


@dataclasses.dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    status: str = PENDING
    last_result: Dict[str, Any] = dataclasses.field(default_factory=dict)
    num_results: int = 0
    best_checkpoint_path: Optional[str] = None
    error: Optional[str] = None
    num_perturbations: int = 0

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "Trial":
        return cls(**d)


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: str, mode: str,
                 path: str):
        self.trials = trials
        self._metric, self._mode = metric, mode
        self.path = path

    def __len__(self) -> int:
        return len(self.trials)

    def _trial_result(self, t: Trial) -> Result:
        ckpt = (Checkpoint(t.best_checkpoint_path)
                if t.best_checkpoint_path else None)
        return Result(metrics=dict(t.last_result), checkpoint=ckpt,
                      path=self.path, metrics_history=[],
                      error=t.error, config=dict(t.config))

    def __iter__(self):
        """Per-trial Results, reference ResultGrid iteration."""
        return (self._trial_result(t) for t in self.trials)

    def __getitem__(self, i: int) -> Result:
        return self._trial_result(self.trials[i])

    @property
    def num_errors(self) -> int:
        return sum(1 for t in self.trials if t.status == ERROR)

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        sign = 1.0 if mode == "max" else -1.0
        best: Optional[Trial] = None
        best_v = -float("inf")
        for t in self.trials:
            if metric not in t.last_result:
                continue
            v = sign * float(t.last_result[metric])
            if v > best_v:
                best, best_v = t, v
        if best is None:
            raise ValueError(f"no trial reported metric {metric!r}")
        r = self._trial_result(best)
        # kept in metrics for backwards compatibility with earlier
        # callers; Result.config is the structured home
        r.metrics.setdefault("config", dict(best.config))
        r.metrics.setdefault("trial_id", best.trial_id)
        return r


# ------------------------------------------------------------ runners
class _FnTrialRunner:
    """One RayTrainWorker actor running a function trainable."""

    def __init__(self, actor_cls, fn_bytes: bytes):
        self._actor_cls = actor_cls
        self._fn_bytes = fn_bytes
        self._actor = None

    def launch(self, config: Dict[str, Any],
               restore_bytes: Optional[bytes]) -> None:
        self._actor = self._actor_cls.remote(0, 1)
        self._actor.init_session.remote(
            self._fn_bytes, config, restore_bytes, None)

    def poll(self):
        """Submit one next_result round; returns the ref to wait on."""
        return self._actor.next_result.remote()

    def collect(self, ref, timeout: float):
        """-> (metrics, ckpt_bytes) or None (trainable finished)."""
        return ray_tpu.get(ref, timeout=timeout)

    def stop(self) -> None:
        if self._actor is not None:
            try:
                ray_tpu.kill(self._actor)
            except BaseException:
                pass
            self._actor = None


class _GroupTrialRunner:
    """A PG-placed WorkerGroup running a JaxTrainer's loop — the
    distributed-trial path (reference tune/execution/placement_groups.py:
    every trial owns a placement group sized to its worker group)."""

    def __init__(self, trainer):
        self._trainer = trainer
        self._group = None
        self._backend = None
        self._round_refs: List[Any] = []

    def launch(self, config: Dict[str, Any],
               restore_bytes: Optional[bytes]) -> None:
        from ray_tpu.train.backend import Backend
        from ray_tpu.train.worker_group import WorkerGroup
        tr = self._trainer
        scaling = tr._scaling
        group = WorkerGroup(scaling.num_workers,
                            scaling.worker_resources(),
                            scaling.placement_strategy,
                            bundles=scaling.worker_bundles())
        group.start()
        try:
            backend: Backend = tr._backend_config.backend_cls()()
            backend.on_start(group, tr._backend_config)
            fn_bytes = cloudpickle.dumps(tr._fn)
            restore_arg = (ray_tpu.put(restore_bytes)
                           if restore_bytes is not None else None)
            shard_bytes = tr._dataset_shards(group.num_workers)
            ray_tpu.get([
                w.init_session.remote(fn_bytes, config, restore_arg,
                                      shard_bytes[i])
                for i, w in enumerate(group.workers)])
            backend.on_training_start(group, tr._backend_config)
        except BaseException:
            # never strand a started PG + actors on a failed launch
            group.shutdown()
            raise
        self._group, self._backend = group, backend

    def poll(self):
        """One synchronous round: report() is collective in SPMD loops,
        so every rank reaches it together; the controller waits on rank
        0's ref and gathers the rest at collect()."""
        self._round_refs = [w.next_result.remote()
                            for w in self._group.workers]
        return self._round_refs[0]

    def collect(self, ref, timeout: float):
        results = ray_tpu.get(self._round_refs, timeout=timeout)
        return results[0]          # rank 0 carries metrics + checkpoint

    def stop(self) -> None:
        if self._group is not None:
            try:
                self._backend.on_shutdown(self._group)
            except BaseException:
                pass
            self._group.shutdown()
            self._group = None


class Tuner:
    """Sweep a trainable over a param space.

    Two trainable forms:
    - a function ``trainable(config)`` — runs inside one trial actor and
      reports via ``ray_tpu.train.report(metrics, checkpoint=...)``;
    - a ``JaxTrainer`` instance — each trial becomes a PG-placed worker
      group running the trainer's loop with the trial's
      ``train_loop_config``; param_space may be flat (merged into
      train_loop_config) or ``{"train_loop_config": {...}}``.
    """

    def __init__(self, trainable: Any,
                 *, param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config=None,
                 _restored_trials: Optional[List[Trial]] = None):
        from ray_tpu.train.config import RunConfig
        self._trainable = trainable
        self._param_space = dict(param_space or {})
        self._tune = tune_config or TuneConfig()
        self._run = run_config or RunConfig()
        self._restored = _restored_trials

    # --------------------------------------------------------- persist
    def _state_path(self, exp_dir: str) -> str:
        return os.path.join(exp_dir, "experiment_state.json")

    def _save_state(self, exp_dir: str, trials: List[Trial]) -> None:
        tmp = self._state_path(exp_dir) + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"trials": [t.to_json() for t in trials],
                       "metric": self._tune.metric,
                       "mode": self._tune.mode}, f, indent=1)
        os.replace(tmp, self._state_path(exp_dir))

    @classmethod
    def restore(cls, exp_dir: str, trainable: Callable,
                tune_config: Optional[TuneConfig] = None,
                run_config=None) -> "Tuner":
        """Resume an interrupted experiment: finished trials keep their
        results; RUNNING/PENDING/ERROR trials are re-run (reference
        Tuner.restore + experiment_state semantics)."""
        from ray_tpu.train.config import RunConfig
        with open(os.path.join(exp_dir, "experiment_state.json")) as f:
            state = json.load(f)
        trials = [Trial.from_json(d) for d in state["trials"]]
        run = run_config or RunConfig(
            name=os.path.basename(exp_dir.rstrip("/")),
            storage_path=os.path.dirname(exp_dir.rstrip("/")))
        space: Dict[str, Any] = {}
        sp = os.path.join(exp_dir, "param_space.pkl")
        if os.path.exists(sp):
            with open(sp, "rb") as f:
                space = cloudpickle.load(f)
        return cls(trainable, param_space=space,
                   tune_config=tune_config or TuneConfig(
                       metric=state["metric"], mode=state["mode"]),
                   run_config=run, _restored_trials=trials)

    # -------------------------------------------------- trial creation
    def _generate_trials(self) -> List[Trial]:
        cfg = self._tune
        space = self._param_space
        if "train_loop_config" in space and len(space) == 1:
            space = space["train_loop_config"]
        if self._restored is not None:
            if cfg.search_alg is not None:
                # re-arm the searcher so unlaunched ({} config) restored
                # trials can still get lazy suggestions
                cfg.search_alg.set_space(space, cfg.metric, cfg.mode)
            return [
                t if t.status in (TERMINATED, STOPPED)
                else Trial(t.trial_id, t.config)
                for t in self._restored]
        if cfg.search_alg is not None:
            cfg.search_alg.set_space(space, cfg.metric, cfg.mode)
            # configs stay empty until launch: suggest() runs lazily so
            # the searcher sees completed-trial feedback mid-experiment
            return [Trial(f"trial_{i:05d}", {})
                    for i in range(cfg.num_samples)]
        gen = BasicVariantGenerator(cfg.seed)
        return [Trial(f"trial_{i:05d}", c) for i, c in enumerate(
            gen.variants(space, cfg.num_samples))]

    def _runner_factory(self):
        """Built ONCE per fit(): returns (make_runner, resources_needed).
        The trainable pickle and remote actor class are shared across
        every trial launch (and PBT relaunch)."""
        from ray_tpu.train.trainer import JaxTrainer
        if isinstance(self._trainable, JaxTrainer):
            tr = self._trainable
            per = dict(tr._scaling.worker_resources() or {"CPU": 1.0})
            need = {k: v * tr._scaling.num_workers for k, v in per.items()}
            return (lambda: _GroupTrialRunner(tr)), need
        res = dict(self._tune.resources_per_trial or {"CPU": 1.0})
        need = dict(res)
        actor_cls = ray_tpu.remote(**{
            "num_cpus": res.pop("CPU", 1.0),
            "num_tpus": res.pop("TPU", 0) or None,
            "resources": res or None})(
                _lazy_train_worker())
        fn_bytes = cloudpickle.dumps(self._trainable)
        return (lambda: _FnTrialRunner(actor_cls, fn_bytes)), need

    def _trial_config(self, trial: Trial) -> Dict[str, Any]:
        from ray_tpu.train.trainer import JaxTrainer
        if isinstance(self._trainable, JaxTrainer):
            return {**self._trainable._config, **trial.config}
        return trial.config

    # ------------------------------------------------------------- fit
    def fit(self) -> ResultGrid:
        cfg = self._tune
        run_name = self._run.name or f"tune_{int(time.time())}"
        storage = (self._run.storage_path
                   or os.path.expanduser("~/ray_tpu_results"))
        exp_dir = os.path.join(storage, run_name)
        os.makedirs(exp_dir, exist_ok=True)
        scheduler = cfg.scheduler or FIFOScheduler()
        searcher = cfg.search_alg

        trials = self._generate_trials()
        if not trials:
            raise ValueError("param space produced no trials")
        if self._param_space and self._restored is None:
            # persist the space (Domains and all) so restore() can
            # re-arm a searcher for still-unlaunched trials
            with open(os.path.join(exp_dir, "param_space.pkl"),
                      "wb") as f:
                cloudpickle.dump(self._param_space, f)
        make_runner, trial_resources = self._runner_factory()

        pending = [t for t in trials if t.status == PENDING]
        runners: Dict[str, Any] = {}      # trial_id -> runner
        inflight: Dict[str, Trial] = {}   # ref.object_id -> trial
        ref_of: Dict[str, Any] = {}       # trial_id -> wait ref
        managers: Dict[str, CheckpointManager] = {}
        ckpt_cfg = self._run.checkpoint_config
        # restore bytes for requeued relaunches (PBT exploit that lost a
        # placement race keeps its inherited checkpoint)
        pending_restore: Dict[str, bytes] = {}

        def launch(trial: Trial,
                   restore_bytes: Optional[bytes] = None) -> None:
            if searcher is not None and not trial.config:
                trial.config = searcher.suggest(trial.trial_id)
            if restore_bytes is None:
                restore_bytes = pending_restore.pop(trial.trial_id, None)
            runner = make_runner()
            runner.launch(self._trial_config(trial), restore_bytes)
            trial.status = RUNNING
            runners[trial.trial_id] = runner
            if trial.trial_id not in managers:
                managers[trial.trial_id] = CheckpointManager(
                    os.path.join(exp_dir, trial.trial_id, "checkpoints"),
                    num_to_keep=ckpt_cfg.num_to_keep,
                    score_attribute=ckpt_cfg.checkpoint_score_attribute,
                    score_order=ckpt_cfg.checkpoint_score_order)
            if hasattr(scheduler, "on_trial_add"):
                scheduler.on_trial_add(trial.trial_id, trial.config)
            poll(trial)

        def poll(trial: Trial) -> None:
            ref = runners[trial.trial_id].poll()
            inflight[ref.object_id] = trial
            ref_of[trial.trial_id] = ref

        def finish(trial: Trial, status: str,
                   error: Optional[str] = None) -> None:
            trial.status = status
            trial.error = error
            runner = runners.pop(trial.trial_id, None)
            ref_of.pop(trial.trial_id, None)
            if runner is not None:
                runner.stop()
            mgr = managers.get(trial.trial_id)
            if mgr is not None and mgr.best is not None:
                trial.best_checkpoint_path = mgr.best.path
            if searcher is not None:
                searcher.on_trial_complete(trial.trial_id,
                                           trial.last_result)
            self._save_state(exp_dir, trials)

        def latest_ckpt_bytes(trial_id: str) -> Optional[bytes]:
            mgr = managers.get(trial_id)
            if mgr is None or mgr.latest is None:
                return None
            from ray_tpu.train.checkpoint import pack_dir
            return pack_dir(mgr.latest.path)

        def capacity_for_trial() -> bool:
            """Advisory pre-check so a full cluster defers a launch
            instead of blocking the controller in a 60s PG wait while
            healthy trials starve."""
            try:
                avail = ray_tpu.available_resources()
            except Exception:
                return True
            return all(avail.get(k, 0.0) >= v
                       for k, v in trial_resources.items())

        try:
            while pending or runners:
                while pending and len(runners) < cfg.max_concurrent_trials:
                    if runners and not capacity_for_trial():
                        break                    # defer until a trial frees up
                    trial = pending.pop(0)
                    try:
                        launch(trial)
                    except Exception as e:
                        if not runners:
                            # nothing running to free capacity — surface it
                            finish(trial, ERROR, error=repr(e))
                            continue
                        # transient (e.g. PG race lost): retry after progress
                        trial.status = PENDING
                        runners.pop(trial.trial_id, None)
                        ref_of.pop(trial.trial_id, None)
                        pending.append(trial)
                        break
                if not runners:
                    if pending:
                        continue
                    break
                ready, _ = ray_tpu.wait(
                    [ref_of[t] for t in runners], num_returns=1,
                    timeout=cfg.trial_poll_timeout)
                if not ready:
                    raise TimeoutError(
                        f"no trial progressed within "
                        f"{cfg.trial_poll_timeout}s: {sorted(runners)}")
                ref = ready[0]
                trial = inflight.pop(ref.object_id)
                try:
                    # gather timeout matches the wait phase: an SPMD
                    # trial's other ranks may lag rank 0 by a full jit
                    # compile, which routinely exceeds 30s
                    item = runners[trial.trial_id].collect(
                        ref, timeout=cfg.trial_poll_timeout)
                except BaseException as e:
                    finish(trial, ERROR, error=repr(e))
                    continue
                if item is None:
                    finish(trial, TERMINATED)
                    continue
                metrics, ckpt_bytes = item
                trial.num_results += 1
                trial.last_result = metrics
                if ckpt_bytes is not None:
                    managers[trial.trial_id].register_bytes(ckpt_bytes,
                                                            metrics)
                if searcher is not None:
                    searcher.on_trial_result(trial.trial_id,
                                             trial.num_results, metrics)
                decision = scheduler.on_result(
                    trial.trial_id, trial.num_results, metrics)
                if decision == STOP:
                    finish(trial, STOPPED)
                elif isinstance(decision, tuple) and decision[0] == EXPLOIT:
                    # PBT: inherit the source trial's checkpoint + mutated
                    # config, restart this trial's runner in place
                    _, src_id, new_config = decision
                    restore = latest_ckpt_bytes(src_id)
                    runners.pop(trial.trial_id).stop()
                    ref_of.pop(trial.trial_id, None)
                    trial.config = dict(new_config)
                    trial.num_perturbations += 1
                    try:
                        launch(trial, restore)
                    except Exception:
                        # transient (e.g. lost the PG race to the
                        # stopping group's teardown): requeue like the
                        # launch loop does instead of erroring a healthy
                        # trial; the inherited checkpoint rides along
                        trial.status = PENDING
                        runners.pop(trial.trial_id, None)
                        ref_of.pop(trial.trial_id, None)
                        if restore is not None:
                            pending_restore[trial.trial_id] = restore
                        pending.append(trial)
                else:
                    assert decision == CONTINUE
                    poll(trial)
                self._save_state(exp_dir, trials)
        except BaseException:
            for _r in list(runners.values()):
                try:
                    _r.stop()
                except BaseException:
                    pass
            raise

        self._save_state(exp_dir, trials)
        return ResultGrid(trials, cfg.metric, cfg.mode, exp_dir)


def _lazy_train_worker():
    from ray_tpu.train.worker_group import RayTrainWorker
    return RayTrainWorker
