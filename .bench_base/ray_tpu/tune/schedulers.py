"""Trial schedulers: FIFO, ASHA early stopping, PBT.

Parity: reference tune/schedulers/async_hyperband.py
(AsyncHyperBandScheduler/ASHAScheduler) — the asynchronous successive
halving rule: rungs at grace_period * reduction_factor^k; when a trial
reports at a rung, it continues only if it is in the top 1/rf of
everything that has reached that rung so far. And
tune/schedulers/pbt.py (PopulationBasedTraining) — exploit/explore:
bottom-quantile trials inherit a top-quantile trial's checkpoint and a
perturbed copy of its config.
"""
from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
EXPLOIT = "EXPLOIT"


class FIFOScheduler:
    """Run every trial to completion (reference FIFOScheduler)."""

    def on_result(self, trial_id: str, step: int, metrics: Dict) -> str:
        return CONTINUE


class ASHAScheduler:
    def __init__(self, metric: str, mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4):
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.rf = reduction_factor
        # rung milestones: grace, grace*rf, grace*rf^2, ... < max_t
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        # rung milestone -> recorded metric values (sign-normalised: max)
        self._recorded: Dict[int, List[float]] = {r: [] for r in self.rungs}
        self._trial_rung: Dict[str, int] = {}   # highest rung passed

    def _val(self, metrics: Dict) -> float:
        v = float(metrics[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial_id: str, step: int, metrics: Dict) -> str:
        if step >= self.max_t:
            return STOP                      # budget exhausted (normal)
        if self.metric not in metrics:
            return CONTINUE
        v = self._val(metrics)
        decision = CONTINUE
        for rung in self.rungs:
            if step < rung or self._trial_rung.get(trial_id, -1) >= rung:
                continue
            self._trial_rung[trial_id] = rung
            rec = self._recorded[rung]
            rec.append(v)
            if len(rec) >= self.rf:
                # keep only the top 1/rf of what reached this rung
                cutoff = sorted(rec, reverse=True)[
                    max(0, len(rec) // self.rf - 1)]
                if v < cutoff:
                    decision = STOP
        return decision


class PopulationBasedTraining:
    """PBT (reference tune/schedulers/pbt.py): every
    `perturbation_interval` reports, a trial in the bottom
    `quantile_fraction` of the population exploits a random trial from
    the top quantile — inherits its checkpoint (the controller handles
    the transfer) and a mutated copy of its config.

    `hyperparam_mutations` values may be: a list (resample = random
    choice), a tune Domain (resample = domain.sample), or a callable
    () -> value. Non-resampled continuous params multiply by 0.8 / 1.2
    (the reference's explore defaults, pbt.py _explore).
    """

    def __init__(self, metric: str, mode: str = "max",
                 perturbation_interval: int = 2,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 seed: int = 0):
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        if not 0 < quantile_fraction <= 0.5:
            raise ValueError("quantile_fraction must be in (0, 0.5]")
        if not hyperparam_mutations:
            raise ValueError("hyperparam_mutations must be non-empty")
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.mutations = dict(hyperparam_mutations)
        self._rng = random.Random(seed)
        self._configs: Dict[str, Dict[str, Any]] = {}
        self._scores: Dict[str, float] = {}     # sign-normalized (max)
        self._last_perturb: Dict[str, int] = {}
        self.num_exploits = 0

    # controller hook: record each trial's live config
    def on_trial_add(self, trial_id: str, config: Dict[str, Any]) -> None:
        self._configs[trial_id] = dict(config)
        self._last_perturb.setdefault(trial_id, 0)

    def _quantiles(self):
        ranked = sorted(self._scores, key=self._scores.get)
        k = max(1, int(len(ranked) * self.quantile))
        if len(ranked) < 2 * k:
            return [], []
        return ranked[:k], ranked[-k:]          # (bottom, top)

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.tune.search import Domain
        new = dict(config)
        for key, mut in self.mutations.items():
            resample = self._rng.random() < self.resample_p
            cur = new.get(key)
            if isinstance(mut, list):
                if resample or cur not in mut:
                    new[key] = self._rng.choice(mut)
                else:
                    # shift to a neighboring value (reference pbt.py:
                    # continuous lists perturb by index +-1)
                    i = mut.index(cur)
                    j = min(max(i + self._rng.choice((-1, 1)), 0),
                            len(mut) - 1)
                    new[key] = mut[j]
            elif isinstance(mut, Domain):
                if resample or not isinstance(cur, (int, float)):
                    new[key] = mut.sample(self._rng)
                else:
                    new[key] = cur * self._rng.choice((0.8, 1.2))
            elif callable(mut):
                new[key] = mut()
            else:
                raise TypeError(f"unsupported mutation spec for {key!r}")
        return new

    def on_result(self, trial_id: str, step: int, metrics: Dict):
        if self.metric not in metrics:
            return CONTINUE
        v = float(metrics[self.metric])
        self._scores[trial_id] = v if self.mode == "max" else -v
        if step - self._last_perturb.get(trial_id, 0) < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = step
        bottom, top = self._quantiles()
        if trial_id not in bottom:
            return CONTINUE
        src = self._rng.choice(top)
        new_config = self._explore(self._configs.get(src, {}))
        self._configs[trial_id] = dict(new_config)
        self.num_exploits += 1
        return (EXPLOIT, src, new_config)
