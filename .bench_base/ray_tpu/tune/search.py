"""Search spaces + variant generation.

Parity: reference tune/search/ (sample.py Domain/Categorical/Float,
basic_variant.py BasicVariantGenerator) — trimmed to the deterministic
core: grid_search cross-products, stochastic domains sampled
`num_samples` times, every variant a plain config dict.
"""
from __future__ import annotations

import itertools
import math
import random
from typing import Any, Dict, Iterator, List, Sequence


class Domain:
    """A stochastic hyperparameter domain; `sample(rng)` draws one."""

    def sample(self, rng: random.Random) -> Any:  # pragma: no cover
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Uniform(Domain):
    def __init__(self, lower: float, upper: float):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.uniform(self.lower, self.upper)


class LogUniform(Domain):
    def __init__(self, lower: float, upper: float):
        if lower <= 0:
            raise ValueError("loguniform needs lower > 0")
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.lower),
                                    math.log(self.upper)))


class RandInt(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def uniform(lower: float, upper: float) -> Uniform:
    return Uniform(lower, upper)


def loguniform(lower: float, upper: float) -> LogUniform:
    return LogUniform(lower, upper)


def randint(lower: int, upper: int) -> RandInt:
    return RandInt(lower, upper)


def grid_search(values: Sequence[Any]) -> Dict[str, List[Any]]:
    """Marker dict, reference tune.grid_search: every value becomes its
    own variant (cross-product with other grids)."""
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v) == {"grid_search"}


class Searcher:
    """Pluggable search algorithm (reference tune/search/searcher.py).

    The controller calls `set_space` once, then `suggest` per trial and
    feeds observations back through `on_trial_result`/`on_trial_complete`.
    """

    def set_space(self, param_space: Dict[str, Any], metric: str,
                  mode: str) -> None:
        self._space = param_space
        self._metric = metric
        self._sign = 1.0 if mode == "max" else -1.0

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, step: int,
                        metrics: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Dict[str, Any]) -> None:
        pass


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (the model behind the
    reference's OptunaSearch default sampler — optuna.samplers.TPESampler;
    implemented natively since this stack vendors no external searcher).

    After `n_initial` random trials: observations are split into the
    top-`gamma` ("good") and the rest ("bad"); each numeric dimension is
    modeled by a Parzen window (Gaussian KDE over observed values) per
    split, categorical dimensions by smoothed counts; `n_candidates`
    draws from the good model are scored by the density ratio
    l_good/l_bad and the argmax is suggested (Bergstra et al. 2011,
    "Algorithms for Hyper-Parameter Optimization").
    """

    def __init__(self, n_initial: int = 5, gamma: float = 0.25,
                 n_candidates: int = 24, seed: int = 0):
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._suggested: Dict[str, Dict[str, Any]] = {}
        self._obs: List[tuple] = []          # (config, score: higher=better)

    # ------------------------------------------------------- feedback
    def on_trial_result(self, trial_id, step, metrics):
        self._record(trial_id, metrics)

    def on_trial_complete(self, trial_id, result):
        self._record(trial_id, result)

    def _record(self, trial_id, metrics):
        if not metrics or self._metric not in metrics:
            return
        cfg = self._suggested.get(trial_id)
        if cfg is None:
            return
        score = self._sign * float(metrics[self._metric])
        # keep the best observation per trial
        for i, (c, s) in enumerate(self._obs):
            if c is cfg:
                if score > s:
                    self._obs[i] = (c, score)
                return
        self._obs.append((cfg, score))

    # -------------------------------------------------------- suggest
    def suggest(self, trial_id: str) -> Dict[str, Any]:
        if len(self._obs) < self.n_initial:
            cfg = self._random_config()
        else:
            cfg = self._tpe_config()
        self._suggested[trial_id] = cfg
        return cfg

    def _random_config(self) -> Dict[str, Any]:
        cfg = {}
        for k, v in self._space.items():
            if _is_grid(v):
                cfg[k] = self._rng.choice(v["grid_search"])
            elif isinstance(v, Domain):
                cfg[k] = v.sample(self._rng)
            else:
                cfg[k] = v
        return cfg

    def _tpe_config(self) -> Dict[str, Any]:
        ranked = sorted(self._obs, key=lambda cs: -cs[1])
        n_good = max(1, int(len(ranked) * self.gamma))
        good = [c for c, _ in ranked[:n_good]]
        bad = [c for c, _ in ranked[n_good:]] or good
        best, best_score = None, -float("inf")
        for _ in range(self.n_candidates):
            cand = {}
            logratio = 0.0
            for k, v in self._space.items():
                if isinstance(v, Domain):
                    cand[k], lr = self._sample_dim(k, v, good, bad)
                    logratio += lr
                elif _is_grid(v):
                    cand[k] = self._rng.choice(v["grid_search"])
                else:
                    cand[k] = v
            if logratio > best_score:
                best, best_score = cand, logratio
        return best if best is not None else self._random_config()

    def _sample_dim(self, key, domain, good, bad):
        gvals = [c[key] for c in good if key in c]
        bvals = [c[key] for c in bad if key in c]
        if isinstance(domain, Categorical) or not all(
                isinstance(x, (int, float)) for x in gvals + bvals):
            cats = (domain.categories if isinstance(domain, Categorical)
                    else sorted({*gvals, *bvals}, key=repr))
            # smoothed counts; sample from the good distribution
            gw = [gvals.count(c) + 1.0 for c in cats]
            val = self._rng.choices(cats, weights=gw)[0]
            bw = bvals.count(val) + 1.0
            return val, math.log(
                (gvals.count(val) + 1.0) / sum(gw)
                / (bw / (len(bvals) + len(cats))))
        logspace = isinstance(domain, LogUniform)
        xform = math.log if logspace else (lambda x: x)
        inv = math.exp if logspace else (lambda x: x)
        g = [xform(x) for x in gvals] or [xform(domain.sample(self._rng))]
        b = [xform(x) for x in bvals] or g
        lo = xform(domain.lower)
        hi = xform(domain.upper)
        sigma = max((hi - lo) / max(len(g), 1), 1e-12)
        mu = self._rng.choice(g)
        x = min(max(self._rng.gauss(mu, sigma), lo), hi)
        val = inv(x)
        if isinstance(domain, RandInt):
            val = int(min(max(round(val), domain.lower),
                          domain.upper - 1))
            x = xform(val)
        return val, (_parzen_logpdf(x, g, sigma)
                     - _parzen_logpdf(x, b, sigma))


def _parzen_logpdf(x: float, centers: List[float], sigma: float) -> float:
    m = max(-0.5 * ((x - c) / sigma) ** 2 for c in centers)
    s = sum(math.exp(-0.5 * ((x - c) / sigma) ** 2 - m) for c in centers)
    return m + math.log(s / (len(centers) * sigma * math.sqrt(2 * math.pi)))


class BasicVariantGenerator:
    """Expand a param_space into concrete trial configs.

    Grid dimensions cross-product; Domain dimensions re-sample per
    variant; `num_samples` multiplies the whole set (reference
    basic_variant semantics: num_samples repeats of each grid point)."""

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)

    def variants(self, param_space: Dict[str, Any],
                 num_samples: int = 1) -> Iterator[Dict[str, Any]]:
        grid_keys = [k for k, v in param_space.items() if _is_grid(v)]
        grid_vals = [param_space[k]["grid_search"] for k in grid_keys]
        for _ in range(num_samples):
            for combo in (itertools.product(*grid_vals)
                          if grid_keys else [()]):
                cfg = {}
                for k, v in param_space.items():
                    if k in grid_keys:
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self._rng)
                    else:
                        cfg[k] = v
                yield cfg
