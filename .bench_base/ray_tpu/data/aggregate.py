"""Grouped and global aggregation for ray_tpu.data.

Parity: reference python/ray/data/aggregate.py (AggregateFn, Count, Sum,
Min, Max, Mean, Std) and grouped_data.py — re-designed columnar: after a
hash exchange co-locates each key's rows in one partition (shuffle.py),
aggregation is vectorized with sort + ``np.*.reduceat`` over group
boundaries instead of the reference's per-row accumulate loop.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu.data.block import Block, block_concat, block_num_rows


class AggregateFn:
    """One aggregation over an (optional) input column.

    Subclasses define the vectorized segment reduction
    (``reduce_segments``) used on sorted-by-key partitions, plus
    ``merge``/``finalize`` so global (ungrouped) aggregation can combine
    per-partition partials.
    """

    def __init__(self, on: Optional[str] = None,
                 alias_name: Optional[str] = None):
        self.on = on
        self._alias = alias_name

    @property
    def name(self) -> str:
        if self._alias:
            return self._alias
        tag = self.__class__.__name__.lower()
        return f"{tag}({self.on or ''})"

    def _col(self, block: Block) -> np.ndarray:
        if self.on is None:
            raise ValueError(f"{self.__class__.__name__} needs on=<column>")
        if self.on not in block:
            raise KeyError(f"aggregate column {self.on!r} not in block "
                           f"(have {list(block)})")
        return np.asarray(block[self.on], dtype=np.float64)

    # --- vectorized path: values sorted by key, starts = group offsets
    def reduce_segments(self, block: Block,
                        starts: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    # --- partial path (global aggregates across partitions)
    def partial(self, block: Block) -> Any:
        raise NotImplementedError

    def merge(self, a: Any, b: Any) -> Any:
        raise NotImplementedError

    def finalize(self, acc: Any) -> Any:
        return acc


class Count(AggregateFn):
    """Row count (reference aggregate.py Count)."""

    def __init__(self, alias_name: Optional[str] = None):
        super().__init__(on=None, alias_name=alias_name)

    @property
    def name(self) -> str:
        return self._alias or "count()"

    def reduce_segments(self, block, starts):
        n = block_num_rows(block)
        ends = np.append(starts[1:], n)
        return (ends - starts).astype(np.int64)

    def partial(self, block):
        return block_num_rows(block)

    def merge(self, a, b):
        return a + b


class Sum(AggregateFn):
    def reduce_segments(self, block, starts):
        return np.add.reduceat(self._col(block), starts)

    def partial(self, block):
        return float(self._col(block).sum())

    def merge(self, a, b):
        return a + b


class Min(AggregateFn):
    def reduce_segments(self, block, starts):
        return np.minimum.reduceat(self._col(block), starts)

    def partial(self, block):
        return float(self._col(block).min())

    def merge(self, a, b):
        return min(a, b)


class Max(AggregateFn):
    def reduce_segments(self, block, starts):
        return np.maximum.reduceat(self._col(block), starts)

    def partial(self, block):
        return float(self._col(block).max())

    def merge(self, a, b):
        return max(a, b)


class Mean(AggregateFn):
    def reduce_segments(self, block, starts):
        vals = self._col(block)
        ends = np.append(starts[1:], len(vals))
        return np.add.reduceat(vals, starts) / (ends - starts)

    def partial(self, block):
        v = self._col(block)
        return (float(v.sum()), len(v))

    def merge(self, a, b):
        return (a[0] + b[0], a[1] + b[1])

    def finalize(self, acc):
        return acc[0] / acc[1] if acc[1] else float("nan")


class Std(AggregateFn):
    """Sample standard deviation (ddof=1 default, like the reference)."""

    def __init__(self, on: Optional[str] = None, ddof: int = 1,
                 alias_name: Optional[str] = None):
        super().__init__(on=on, alias_name=alias_name)
        self.ddof = ddof

    def reduce_segments(self, block, starts):
        # shifted two-pass: subtract each segment's mean before squaring
        # (naive sum-of-squares loses all precision when |mean| >> std)
        vals = self._col(block)
        ends = np.append(starts[1:], len(vals))
        n = (ends - starts).astype(np.float64)
        mean = np.add.reduceat(vals, starts) / n
        dev = vals - np.repeat(mean, (ends - starts))
        m2 = np.add.reduceat(dev * dev, starts)
        var = m2 / np.maximum(n - self.ddof, 1e-12)
        var = np.where(n > self.ddof, np.maximum(var, 0.0), np.nan)
        return np.sqrt(var)

    def partial(self, block):
        v = self._col(block)
        m = float(v.mean())
        return (len(v), m, float(((v - m) ** 2).sum()))

    def merge(self, a, b):
        # Chan et al. parallel variance merge of (n, mean, M2) partials
        na, ma, m2a = a
        nb, mb, m2b = b
        n = na + nb
        d = mb - ma
        return (n, ma + d * nb / n, m2a + m2b + d * d * na * nb / n)

    def finalize(self, acc):
        n, _, m2 = acc
        if n <= self.ddof:
            return float("nan")
        return float(np.sqrt(max(m2 / (n - self.ddof), 0.0)))


class AbsMax(AggregateFn):
    def reduce_segments(self, block, starts):
        return np.maximum.reduceat(np.abs(self._col(block)), starts)

    def partial(self, block):
        return float(np.abs(self._col(block)).max())

    def merge(self, a, b):
        return max(a, b)


# --------------------------------------------------------------- engine
def sort_block_by_keys(block: Block,
                       keys: Sequence[str]) -> Tuple[Block, np.ndarray]:
    """Stable-sort a block by key column(s); return (sorted_block,
    group_start_offsets)."""
    n = block_num_rows(block)
    if n == 0:
        return block, np.empty(0, dtype=np.int64)
    cols = [np.asarray(block[k]) for k in keys]
    order = np.lexsort(tuple(reversed(cols)))
    sorted_block = {k: v[order] for k, v in block.items()}
    skeys = [c[order] for c in cols]
    change = np.zeros(n, dtype=bool)
    change[0] = True
    for c in skeys:
        change[1:] |= c[1:] != c[:-1]
    return sorted_block, np.nonzero(change)[0]


def aggregate_partition(block: Block, keys: Sequence[str],
                        aggs: Sequence[AggregateFn]) -> Block:
    """All rows for any given key must already be in `block` (post
    hash-exchange). Returns one row per distinct key."""
    if block_num_rows(block) == 0:
        return {}
    sblock, starts = sort_block_by_keys(block, keys)
    out: Block = {k: sblock[k][starts] for k in keys}
    for agg in aggs:
        out[agg.name] = np.asarray(agg.reduce_segments(sblock, starts))
    return out


def aggregate_global(blocks: Any,
                     aggs: Sequence[AggregateFn]) -> Dict[str, Any]:
    """Ungrouped aggregation over a full dataset (Dataset.aggregate)."""
    accs: List[Any] = [None] * len(aggs)
    for b in blocks:
        if not block_num_rows(b):
            continue
        for i, agg in enumerate(aggs):
            p = agg.partial(b)
            accs[i] = p if accs[i] is None else agg.merge(accs[i], p)
    return {agg.name: (None if accs[i] is None else agg.finalize(accs[i]))
            for i, agg in enumerate(aggs)}


def map_groups_partition(block: Block, keys: Sequence[str],
                         fn: Callable[[Block], Any]) -> List[Block]:
    """Run `fn` once per key-group (rows of that group as a Block)."""
    from ray_tpu.data.block import block_slice, normalize_batch_output
    if block_num_rows(block) == 0:
        return []
    sblock, starts = sort_block_by_keys(block, keys)
    n = block_num_rows(sblock)
    ends = np.append(starts[1:], n)
    out = []
    for lo, hi in zip(starts, ends):
        res = fn(block_slice(sblock, int(lo), int(hi)))
        if res is not None:
            blk = normalize_batch_output(res)
            if block_num_rows(blk):
                out.append(blk)
    return out
