"""Dataset: lazy, streaming, shardable data pipelines.

Parity: reference python/ray/data/dataset.py:141 (Dataset, map_batches
:391, iter_batches, split, take, count) and read_api.py constructors —
re-designed for the TPU training loop: columnar numpy blocks, remote
per-partition execution with a bounded streaming window
(executor.stream_blocks), and `iter_batches` that can hand back
dp/fsdp-sharded `jax.Array`s with double-buffered host→device prefetch
(jax_iter.JaxBatchIterator).
"""
from __future__ import annotations

import itertools
import time
from typing import (Any, Callable, Dict, Iterator, List, Optional,
                    Sequence, Union)

import numpy as np

from ray_tpu.data import datasource as ds
from ray_tpu.data.block import (Block, block_concat, block_num_rows,
                                block_slice, block_take, block_to_rows)
from ray_tpu.data.executor import Op, apply_ops, stream_blocks


def _irange(n: int):
    import builtins
    return builtins.range(n)


class _FusedTask:
    """Picklable read-task body with an op chain baked in (union/zip
    pipeline breakers)."""

    def __init__(self, task: ds.ReadTask, ops: List[Op]):
        self._task = task
        self._ops = ops

    def __call__(self):
        from ray_tpu.data.executor import apply_ops
        return apply_ops(self._task(), self._ops)


class DataIterator:
    """One epoch-iterable view of a Dataset (reference
    data/iterator.py DataIterator). Created by `Dataset.iterator()` or
    handed to train workers by `get_dataset_shard`."""

    def __init__(self, dataset: "Dataset"):
        self._ds = dataset
        self.last_wait_s = 0.0   # input-pipeline stall accounting

    def iter_batches(self, **kw) -> Iterator[Dict[str, np.ndarray]]:
        return self._ds.iter_batches(**kw)

    def iter_jax_batches(self, **kw):
        from ray_tpu.data.jax_iter import iter_jax_batches
        return iter_jax_batches(self._ds, **kw)

    def materialize(self) -> "Dataset":
        return self._ds.materialize()


class ActorPoolStrategy:
    """compute= strategy for map_batches: run the partition pipeline on a
    pool of long-lived actors so callable-class transforms keep state
    (model weights, tokenizers) across partitions. Reference
    data/_internal/compute.py ActorPoolStrategy /
    actor_pool_map_operator.py."""

    def __init__(self, size: Optional[int] = None, *,
                 min_size: Optional[int] = None,
                 max_size: Optional[int] = None):
        if size is None:
            size = max_size if max_size is not None else (
                min_size if min_size is not None else 2)
        if size < 1:
            raise ValueError("pool size must be >= 1")
        self.size = int(size)


class Dataset:
    """Lazy pipeline: read tasks + op chain, executed streaming."""

    def __init__(self, read_tasks: List[ds.ReadTask],
                 ops: Optional[List[Op]] = None,
                 max_in_flight: int = 4,
                 compute: Optional[ActorPoolStrategy] = None,
                 op_specs: Optional[list] = None):
        self._tasks = read_tasks
        self._ops: List[Op] = list(ops or [])
        self._max_in_flight = max_in_flight
        self._compute = compute
        # per-op StageSpec (or None = fuse) — parallel to _ops
        self._op_specs: list = (list(op_specs) if op_specs is not None
                                else [None] * len(self._ops))
        self._stats_sink: list = []

    # ------------------------------------------------------ transforms
    def map_batches(self, fn: Union[Callable[[Block], Dict[str, Any]], type],
                    *, batch_size: Optional[int] = None,
                    compute: Optional[ActorPoolStrategy] = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None,
                    num_cpus: Optional[float] = None,
                    concurrency: Optional[int] = None,
                    ) -> "Dataset":
        """Transform batches. `fn` may be a callable class (stateful —
        constructed once per worker); pass compute=ActorPoolStrategy(n)
        to run the pipeline on a pool of n long-lived actors.

        Passing `num_cpus` and/or `concurrency` gives this op its OWN
        physical stage (per-operator streaming execution: separate
        resources, in-flight window, and backpressure — reference
        streaming_executor); `compute` then scopes the actor pool to
        just this stage instead of the whole pipeline."""
        if isinstance(fn, type):
            from ray_tpu.data.executor import ClassSpec
            if compute is None:
                compute = ActorPoolStrategy(2)
            fn = ClassSpec(fn)
        op = ("map_batches", fn, batch_size, fn_constructor_args,
              fn_constructor_kwargs or {})
        if num_cpus is not None or concurrency is not None:
            from ray_tpu.data.streaming import StageSpec
            spec = StageSpec(
                num_cpus=num_cpus if num_cpus is not None else 1.0,
                concurrency=concurrency if concurrency is not None else 4,
                compute=compute)
            return self._with_op(op, spec)
        out = self._with_op(op)
        if compute is not None:
            out._compute = compute
        return out

    def map(self, fn: Callable[[Dict], Dict]) -> "Dataset":
        return self._with_op(("map", fn))

    def filter(self, fn: Callable[[Dict], bool]) -> "Dataset":
        return self._with_op(("filter", fn))

    def flat_map(self, fn: Callable[[Dict], Sequence[Dict]]) -> "Dataset":
        return self._with_op(("flat_map", fn))

    def _with_op(self, op: Op, spec=None) -> "Dataset":
        return Dataset(self._tasks, self._ops + [op], self._max_in_flight,
                       self._compute, op_specs=self._op_specs + [spec])

    # ------------------------------------------- shuffle-backed relations
    def groupby(self, key: Union[str, List[str]],
                *, num_partitions: Optional[int] = None):
        """Group rows by key column(s) via a hash exchange; aggregate or
        map_groups on the result (reference dataset.py groupby)."""
        from ray_tpu.data.grouped_data import GroupedData
        return GroupedData(self, key, num_partitions)

    def aggregate(self, *aggs) -> Dict[str, Any]:
        """Whole-dataset aggregation -> one dict (reference
        Dataset.aggregate)."""
        from ray_tpu.data.aggregate import aggregate_global
        return aggregate_global(self.iter_blocks(), aggs)

    def sum(self, on: str):
        from ray_tpu.data import aggregate as A
        return self.aggregate(A.Sum(on))[f"sum({on})"]

    def min(self, on: str):
        from ray_tpu.data import aggregate as A
        return self.aggregate(A.Min(on))[f"min({on})"]

    def max(self, on: str):
        from ray_tpu.data import aggregate as A
        return self.aggregate(A.Max(on))[f"max({on})"]

    def mean(self, on: str):
        from ray_tpu.data import aggregate as A
        return self.aggregate(A.Mean(on))[f"mean({on})"]

    def std(self, on: str, ddof: int = 1):
        from ray_tpu.data import aggregate as A
        return self.aggregate(A.Std(on, ddof=ddof))[f"std({on})"]

    def unique(self, on: str) -> List[Any]:
        """Distinct values of a column (reference Dataset.unique)."""
        rows = self.groupby(on).count().take_all()
        return [r[on] for r in rows]

    def sort(self, key: str, *, descending: bool = False,
             num_partitions: Optional[int] = None) -> "Dataset":
        """Global sort by one column: sample range boundaries, range-
        exchange, sort each output partition (reference Dataset.sort /
        _internal/planner/exchange/sort_task_spec.py)."""
        from ray_tpu.data import shuffle as sh
        num_out = num_partitions or max(1, min(self.num_partitions(), 8))
        bounds = sh.sort_boundaries(self._tasks, self._ops, key, num_out)
        if not len(bounds):
            num_out = 1
        tasks = sh.exchange(
            self._tasks, self._ops,
            sh._map_range, (key, bounds, descending, num_out),
            sh.make_reduce_sort(key, descending), num_out)
        return Dataset(tasks)

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_partitions: Optional[int] = None) -> "Dataset":
        """Global random shuffle: rows are hash-scattered to random
        partitions, then permuted within each (reference
        Dataset.random_shuffle)."""
        from ray_tpu.data import shuffle as sh
        num_out = num_partitions or max(1, self.num_partitions())
        tasks = sh.exchange(
            self._tasks, self._ops,
            sh._map_random, (seed, num_out),
            sh.make_reduce_permute(seed), num_out)
        return Dataset(tasks)

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise join of two row-aligned datasets (reference
        Dataset.zip); duplicate column names from `other` get a _1
        suffix."""
        left, right = self, other

        def _zipped():
            from ray_tpu.data.block import rebatch_blocks
            CHUNK = 4096
            lit = rebatch_blocks(left.iter_blocks(), CHUNK)
            rit = rebatch_blocks(right.iter_blocks(), CHUNK)
            lbuf: Block = {}
            rbuf: Block = {}
            while True:
                if not block_num_rows(lbuf):
                    lbuf = next(lit, {})
                if not block_num_rows(rbuf):
                    rbuf = next(rit, {})
                ln, rn = block_num_rows(lbuf), block_num_rows(rbuf)
                if not ln or not rn:
                    if ln != rn:
                        raise ValueError(
                            "zip(): datasets have different row counts")
                    return
                n = min(ln, rn)
                out = dict(block_slice(lbuf, 0, n))
                for k, v in block_slice(rbuf, 0, n).items():
                    out[k if k not in out else f"{k}_1"] = v
                yield out
                lbuf = block_slice(lbuf, n, ln)
                rbuf = block_slice(rbuf, n, rn)

        return Dataset([ds.ReadTask(_zipped, "zip")])

    def union(self, *others: "Dataset") -> "Dataset":
        """Row-concatenate datasets (reference Dataset.union). Each
        input's op chain is fused into its read tasks so the combined
        dataset has a single empty chain."""
        tasks: List[ds.ReadTask] = []
        for d in (self, *others):
            tasks.extend(d._fused_tasks())
        return Dataset(tasks)

    def _fused_tasks(self) -> List[ds.ReadTask]:
        """Read tasks with this dataset's op chain baked in."""
        if not self._ops:
            return list(self._tasks)
        ops = list(self._ops)
        return [ds.ReadTask(_FusedTask(t, ops), f"fused[{t.name}]")
                for t in self._tasks]

    # --------------------------------------------------------- sharding
    def split(self, n: int, *, locality_hints=None) -> List["Dataset"]:
        """Round-robin the read partitions into n sub-datasets (the
        per-train-worker shard primitive; reference streaming_split).
        Partitions, not rows, are the split unit — use enough input
        files/blocks (override_num_blocks) for even shards."""
        if n <= 0:
            raise ValueError("n must be positive")
        if len(self._tasks) < n:
            raise ValueError(
                f"cannot split {len(self._tasks)} partitions into {n} "
                f"shards; re-read with override_num_blocks>={n}")
        return [Dataset(self._tasks[i::n], list(self._ops),
                        self._max_in_flight, self._compute,
                        op_specs=self._op_specs)
                for i in _irange(n)]

    def repartition(self, n: int) -> "Dataset":
        """Materialize and re-block into exactly n row-range partitions
        (driver-resident; use for small datasets or to enable split(n)
        when the input had fewer files than workers)."""
        blocks = list(self.iter_blocks())
        merged = block_concat(blocks)
        total = block_num_rows(merged)
        if total == 0:
            raise ValueError("cannot repartition an empty dataset")
        bounds = np.linspace(0, total, n + 1, dtype=int)
        tasks = []
        for i in _irange(n):
            chunk = block_slice(merged, int(bounds[i]), int(bounds[i + 1]))
            tasks.append(ds.ReadTask(lambda c=chunk: iter([c]),
                                     f"repartition[{i}]"))
        return Dataset(tasks)

    def iterator(self) -> DataIterator:
        return DataIterator(self)

    # ------------------------------------------------------ consumption
    def iter_blocks(self) -> Iterator[Block]:
        if any(s is not None for s in self._op_specs):
            from ray_tpu.data.streaming import execute_streaming
            return execute_streaming(self._tasks, self._ops,
                                     self._op_specs,
                                     stage0_compute=self._compute,
                                     stats_sink=self._stats_sink)
        if self._compute is not None:
            from ray_tpu.data.executor import stream_blocks_actor_pool
            return stream_blocks_actor_pool(
                self._tasks, self._ops, pool_size=self._compute.size)
        return stream_blocks(self._tasks, self._ops,
                             max_in_flight=self._max_in_flight)

    def stats(self):
        """Per-stage execution stats of the last streaming (per-op
        staged) iteration, or None (reference Dataset.stats())."""
        return self._stats_sink[-1] if self._stats_sink else None

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for b in self.iter_blocks():
            yield from block_to_rows(b)

    def iter_batches(self, *, batch_size: int = 256,
                     drop_last: bool = False,
                     local_shuffle_buffer_size: int = 0,
                     seed: Optional[int] = None,
                     ) -> Iterator[Dict[str, np.ndarray]]:
        """Stream fixed-size row batches; optional streaming shuffle via
        a reservoir buffer (reference iter_batches
        local_shuffle_buffer_size semantics)."""
        from ray_tpu.data.block import rebatch_blocks
        blocks = self.iter_blocks()
        if local_shuffle_buffer_size:
            blocks = _shuffle_blocks(blocks, local_shuffle_buffer_size,
                                     seed)
        yield from rebatch_blocks(blocks, batch_size, drop_last=drop_last)

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        return list(itertools.islice(self.iter_rows(), n))

    def take_all(self) -> List[Dict[str, Any]]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(block_num_rows(b) for b in self.iter_blocks())

    def schema(self) -> Dict[str, str]:
        for b in self.iter_blocks():
            return {k: str(v.dtype) for k, v in b.items()}
        return {}

    def materialize(self) -> "Dataset":
        """Execute now; the result is a Dataset over in-memory blocks."""
        blocks = list(self.iter_blocks())
        # one task per materialized block keeps split() usable
        tasks = []
        for i, blk in enumerate(blocks):
            tasks.append(ds.ReadTask(
                lambda b=blk: iter([b]), f"materialized[{i}]"))
        return Dataset(tasks)

    # ----------------------------------------------------------- output
    def write_jsonl(self, path: str) -> List[str]:
        return ds.write_jsonl(self.iter_blocks(), path)

    def write_parquet(self, path: str) -> List[str]:
        return ds.write_parquet(self.iter_blocks(), path)

    def write_csv(self, path: str) -> List[str]:
        return ds.write_csv(self.iter_blocks(), path)

    def write_tfrecords(self, path: str) -> List[str]:
        return ds.write_tfrecords(self.iter_blocks(), path)

    # ------------------------------------------------------------ misc
    def num_partitions(self) -> int:
        return len(self._tasks)

    def __repr__(self) -> str:
        ops = " -> ".join(o[0] for o in self._ops) or "read"
        return (f"Dataset(partitions={len(self._tasks)}, plan={ops})")


def _shuffle_blocks(blocks: Iterator[Block], buffer_rows: int,
                    seed: Optional[int]) -> Iterator[Block]:
    """Streaming shuffle: fill a row buffer, emit random halves."""
    rng = np.random.default_rng(seed)
    buf: List[Block] = []
    have = 0
    for b in blocks:
        buf.append(b)
        have += block_num_rows(b)
        if have >= buffer_rows:
            merged = block_concat(buf)
            perm = rng.permutation(have)
            emit = have // 2          # keep half buffered for mixing
            yield block_take(merged, perm[:emit])
            buf = [block_take(merged, perm[emit:])]
            have -= emit
    if have:
        merged = block_concat(buf)
        yield block_take(merged, rng.permutation(have))


# ------------------------------------------------------------ read API
def range(n: int, *, override_num_blocks: int = 8) -> Dataset:  # noqa: A001
    return Dataset(ds.range_tasks(n, override_num_blocks))


def from_items(items: List[Any], *, override_num_blocks: int = 8) -> Dataset:
    return Dataset(ds.items_tasks(items, override_num_blocks))


def read_json(paths, *, rows_per_block: int = 4096) -> Dataset:
    return Dataset(ds.jsonl_tasks(paths, rows_per_block))


def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 rows_per_block: int = 65536) -> Dataset:
    return Dataset(ds.parquet_tasks(paths, columns, rows_per_block))


def read_csv(paths, *, rows_per_block: int = 65536) -> Dataset:
    return Dataset(ds.csv_tasks(paths, rows_per_block))


def read_text(paths, *, rows_per_block: int = 65536) -> Dataset:
    return Dataset(ds.text_tasks(paths, rows_per_block))


def read_binary_files(paths, *, include_paths: bool = True) -> Dataset:
    return Dataset(ds.binary_tasks(paths, include_paths))


def read_images(paths, *, size=None, mode: str = "RGB",
                include_paths: bool = False) -> Dataset:
    return Dataset(ds.image_tasks(paths, size, mode, include_paths))


def read_tfrecords(paths, *, rows_per_block: int = 4096) -> Dataset:
    return Dataset(ds.tfrecord_tasks(paths, rows_per_block))


def from_numpy(arrays: Dict[str, np.ndarray], *,
               override_num_blocks: int = 8) -> Dataset:
    import builtins
    n = len(next(iter(arrays.values())))
    num = max(1, min(override_num_blocks, n))
    bounds = np.linspace(0, n, num + 1, dtype=int)
    tasks = []
    for i in builtins.range(num):
        lo, hi = int(bounds[i]), int(bounds[i + 1])
        chunk = {k: v[lo:hi] for k, v in arrays.items()}
        tasks.append(ds.ReadTask(lambda c=chunk: iter([c]),
                                 f"numpy[{lo}:{hi}]"))
    return Dataset(tasks)
