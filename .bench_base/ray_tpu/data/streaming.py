"""Per-operator streaming execution: stages with their own resources,
concurrency, and backpressure.

Parity: reference data/_internal/execution/streaming_executor.py +
resource_manager.py + backpressure_policy/ — re-shaped for ray_tpu.
The default executor (executor.py) fuses the whole op chain into one
task per read partition: optimal when every op is cheap and uniform.
When an op declares its own resources (`map_batches(..., num_cpus=4)`,
`concurrency=2`, or a per-op `ActorPoolStrategy`), the plan splits
into physical *stages* at each declared boundary; blocks flow between
stages as object refs (workers fetch them directly — the driver never
materializes intermediate blocks), each stage keeps its own bounded
in-flight window, and a stage may only run ahead of its consumer by a
bounded backlog — so a fast reader cannot flood the object store while
a slow TPU-heavy stage drains (the reference's
OutputBudgetBackpressurePolicy, expressed as queue bounds).

Scheduling order is downstream-first (reference streaming_executor
picks the operator closest to the output), and output order is
deterministic: every stage consumes and emits in submission order.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Iterator, List, Optional

from ray_tpu.data.block import Block, block_num_rows
from ray_tpu.data.executor import apply_ops

Op = Any


@dataclass
class StageSpec:
    """Physical requirements of one pipeline stage."""
    num_cpus: float = 1.0
    concurrency: int = 4          # max in-flight tasks for this stage
    compute: Any = None           # ActorPoolStrategy -> stateful pool


def plan_stages(ops: List[Op], specs: List[Optional[StageSpec]],
                stage0_compute=None):
    """Split the op chain into (ops, spec) stages. An op with an
    explicit spec starts a new stage; spec-less ops fuse into the
    current stage (reference fusion rule: same-resource ops fuse).
    `stage0_compute` carries the dataset-level ActorPoolStrategy so
    stateful callable-class transforms fused into stage 0 still run on
    a persistent pool (one instance per pool worker, not per task)."""
    stages: List[tuple] = [([], StageSpec(compute=stage0_compute))]
    for op, spec in zip(ops, specs):
        if spec is not None:
            stages.append(([op], spec))
        else:
            stages[-1][0].append(op)
    return stages


def _run_stage(inp, ops: List[Op]) -> List[Block]:
    """One stage task: input is a ReadTask (stage 0) or the resolved
    block list from an upstream stage's object ref."""
    it = inp() if callable(inp) else iter(inp)
    return [b for b in apply_ops(it, ops) if block_num_rows(b)]


class _StageWorker:
    """Pool actor for stages with compute=ActorPoolStrategy: keeps
    callable-class transform instances alive across inputs."""

    def __init__(self):
        self._instances: dict = {}

    def run_stage(self, inp, ops: List[Op]) -> List[Block]:
        it = inp() if callable(inp) else iter(inp)
        return [b for b in apply_ops(it, ops, self._instances)
                if block_num_rows(b)]


class _StageState:
    def __init__(self, idx: int, ops: List[Op], spec: StageSpec):
        self.idx = idx
        self.ops = ops
        self.spec = spec
        self.pending: deque = deque()    # undispatched inputs
        self.inflight: deque = deque()   # (out_ref, in_ref, actor, t0)
        self.done: deque = deque()       # completed out_refs, in order
        self.input_done = False
        self.actors: list = []
        self.free_actors: deque = deque()
        self.stats = {"tasks": 0, "task_s": 0.0, "blocks_out": 0}

    def drained(self) -> bool:
        return (self.input_done and not self.pending
                and not self.inflight and not self.done)


class ExecutionStats:
    """Per-stage task counts + cumulative task seconds of the last
    streaming execution (reference Dataset.stats()).

    `task_s` is wall time IN FLIGHT (dispatch -> completion), so it
    includes queue and worker-spawn time, not just execution;
    `blocks_out` is counted only for the terminal stage (intermediate
    blocks flow worker-to-worker as refs and are never materialized on
    the driver)."""

    def __init__(self, stages: List[_StageState], wall_s: float):
        self.wall_s = wall_s
        self.stages = [
            {"stage": i,
             "ops": [op[0] for op in st.ops] or ["read"],
             "num_cpus": st.spec.num_cpus,
             "concurrency": st.spec.concurrency,
             "actor_pool": bool(st.spec.compute),
             **st.stats}
            for i, st in enumerate(stages)]

    def __repr__(self) -> str:
        lines = [f"ExecutionStats(wall={self.wall_s:.2f}s)"]
        for s in self.stages:
            kind = "pool" if s["actor_pool"] else "tasks"
            lines.append(
                f"  stage {s['stage']} {'+'.join(s['ops'])} [{kind} "
                f"x{s['concurrency']}, cpus={s['num_cpus']}]: "
                f"{s['tasks']} tasks, {s['task_s']:.2f} task-s, "
                f"{s['blocks_out']} blocks")
        return "\n".join(lines)


def execute_streaming(read_tasks: List[Any], ops: List[Op],
                      specs: List[Optional[StageSpec]],
                      max_backlog: int = 8,
                      stage0_compute=None,
                      stats_sink: Optional[list] = None,
                      ) -> Iterator[Block]:
    """Yield output blocks of the staged pipeline, in partition order."""
    import ray_tpu
    plan = plan_stages(ops, specs, stage0_compute)
    if not read_tasks:
        return
    if not ray_tpu.is_initialized():
        # local fallback: run stages sequentially in-process; one shared
        # instances dict so callable-class state persists across
        # partitions (like a 1-worker pool)
        instances: dict = {}
        blocks: Any = None
        for i, (stage_ops, _spec) in enumerate(plan):
            if i == 0:
                out: List[Block] = []
                for t in read_tasks:
                    it = t()
                    out.extend(b for b in apply_ops(it, stage_ops,
                                                    instances)
                               if block_num_rows(b))
            else:
                out = [b for b in apply_ops(iter(blocks), stage_ops,
                                            instances)
                       if block_num_rows(b)]
            blocks = out
        yield from blocks
        return

    t_start = time.time()
    stages = [_StageState(i, stage_ops, spec)
              for i, (stage_ops, spec) in enumerate(plan)]
    stages[0].pending.extend(read_tasks)
    stages[0].input_done = True

    task_fns = {}
    for st in stages:
        if st.spec.compute is not None:
            Actor = ray_tpu.remote(num_cpus=st.spec.num_cpus)(_StageWorker)
            st.actors = [Actor.remote()
                         for _ in range(st.spec.compute.size)]
            st.free_actors.extend(st.actors)
        else:
            task_fns[st.idx] = ray_tpu.remote(
                num_cpus=st.spec.num_cpus)(_run_stage)

    try:
        while True:
            progressed = False
            # harvest head-of-line completions (order-preserving)
            for st in stages:
                while st.inflight:
                    ready, _ = ray_tpu.wait([st.inflight[0][0]],
                                            num_returns=1, timeout=0)
                    if not ready:
                        break
                    out_ref, _in_ref, actor, t0 = st.inflight.popleft()
                    st.stats["task_s"] += time.time() - t0
                    if actor is not None:
                        st.free_actors.append(actor)
                    st.done.append(out_ref)
                    progressed = True
            # propagate downstream, bounded so backpressure chains up
            for i in range(len(stages) - 1):
                st, nxt = stages[i], stages[i + 1]
                cap = nxt.spec.concurrency * 2
                while st.done and (len(nxt.pending)
                                   + len(nxt.inflight)) < cap:
                    nxt.pending.append(st.done.popleft())
                if (st.input_done and not st.pending
                        and not st.inflight and not st.done):
                    nxt.input_done = True
            # dispatch, downstream-first
            for st in reversed(stages):
                while (st.pending
                       and len(st.inflight) < st.spec.concurrency
                       and (len(st.done) + len(st.inflight))
                       < max_backlog
                       and (st.spec.compute is None
                            or st.free_actors)):
                    inp = st.pending.popleft()
                    if st.spec.compute is not None:
                        actor = st.free_actors.popleft()
                        ref = actor.run_stage.remote(inp, st.ops)
                    else:
                        actor = None
                        ref = task_fns[st.idx].remote(inp, st.ops)
                    st.inflight.append((ref, inp, actor, time.time()))
                    st.stats["tasks"] += 1
                    progressed = True
            # emit finished output
            last = stages[-1]
            while last.done:
                for b in ray_tpu.get(last.done.popleft()):
                    last.stats["blocks_out"] += 1
                    yield b
                progressed = True
            if last.drained():
                break
            if not progressed:
                heads = [st.inflight[0][0] for st in stages
                         if st.inflight]
                if heads:
                    ray_tpu.wait(heads, num_returns=1)
    finally:
        for st in stages:
            for a in st.actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass
        if stats_sink is not None:
            stats_sink.append(
                ExecutionStats(stages, time.time() - t_start))
