"""Streaming executor: pull blocks through the op chain with bounded
in-flight work.

Parity: reference data/_internal/execution/streaming_executor.py:48 —
re-shaped for ray_tpu: instead of an operator-graph thread juggling
actor pools, each ReadTask (+ its whole op chain) becomes ONE remote
task; the driver keeps a bounded window of them in flight and yields
blocks in task order. Backpressure falls out of the window bound: no
more than `max_in_flight` read partitions are ever materialized beyond
what the consumer has taken. Falls back to a local thread when the
runtime is not initialized (pure-local datasets in tests/tools).
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Iterator, List, Optional, Tuple

from ray_tpu.data.block import (Block, block_concat, block_num_rows,
                                block_slice, normalize_batch_output)
from ray_tpu.data.datasource import ReadTask

# op tuples: ("map_batches", fn, batch_size) | ("map", fn) |
#            ("filter", fn) | ("flat_map", fn)
Op = Tuple[Any, ...]


def apply_ops(blocks: Iterator[Block], ops: List[Op],
              instances: Optional[dict] = None) -> Iterator[Block]:
    """`instances` caches constructed callable-class transforms keyed by
    op position — pass a persistent dict (actor-pool workers do) so
    stateful transforms survive across partitions."""
    for i, op in enumerate(ops):
        kind = op[0]
        if kind == "map_batches":
            fn = _resolve_fn(op, i, instances)
            blocks = _apply_map_batches(blocks, fn, op[2])
        elif kind == "map":
            blocks = _apply_map(blocks, op[1])
        elif kind == "filter":
            blocks = _apply_filter(blocks, op[1])
        elif kind == "flat_map":
            blocks = _apply_flat_map(blocks, op[1])
        else:  # pragma: no cover - guarded at Dataset level
            raise ValueError(f"unknown op {kind}")
    return blocks


class ClassSpec:
    """Callable-class transform captured BY VALUE (cloudpickle) at
    map_batches() time, so classes defined in driver-only modules (test
    files, notebooks) construct fine inside workers that cannot import
    those modules."""

    def __init__(self, cls: type):
        from ray_tpu._private.pickle_utils import dumps_by_value
        self.data = dumps_by_value(cls)
        self.qualname = cls.__qualname__

    def load(self) -> type:
        import cloudpickle
        return cloudpickle.loads(self.data)


def _resolve_fn(op: Op, idx: int, instances: Optional[dict]):
    """map_batches fn may be a (by-value captured) callable class:
    construct once per worker when an instance cache is provided."""
    fn = op[1]
    if not isinstance(fn, ClassSpec):
        return fn
    ctor_args = op[3] if len(op) > 3 else ()
    ctor_kwargs = op[4] if len(op) > 4 else {}

    def construct():
        return fn.load()(*ctor_args, **ctor_kwargs)

    if instances is None:
        return construct()
    key = (idx, fn.qualname)
    if key not in instances:
        instances[key] = construct()
    return instances[key]


def _apply_map_batches(blocks, fn, batch_size) -> Iterator[Block]:
    if batch_size is None:
        for b in blocks:
            if block_num_rows(b):
                yield normalize_batch_output(fn(b))
        return
    from ray_tpu.data.block import rebatch_blocks
    for batch in rebatch_blocks(blocks, batch_size):
        yield normalize_batch_output(fn(batch))


def _apply_map(blocks, fn) -> Iterator[Block]:
    from ray_tpu.data.block import block_from_rows, block_to_rows
    for b in blocks:
        rows = [fn(r) for r in block_to_rows(b)]
        if rows:
            yield block_from_rows(rows)


def _apply_filter(blocks, fn) -> Iterator[Block]:
    import numpy as np

    from ray_tpu.data.block import block_take, block_to_rows
    for b in blocks:
        keep = np.asarray([bool(fn(r)) for r in block_to_rows(b)])
        if keep.any():
            yield block_take(b, np.nonzero(keep)[0])


def _apply_flat_map(blocks, fn) -> Iterator[Block]:
    from ray_tpu.data.block import block_from_rows, block_to_rows
    for b in blocks:
        rows = []
        for r in block_to_rows(b):
            rows.extend(fn(r))
        if rows:
            yield block_from_rows(rows)


def _run_partition(task: ReadTask, ops: List[Op]) -> List[Block]:
    """Executed inside a ray_tpu worker: read + transform one partition."""
    return [b for b in apply_ops(task(), ops) if block_num_rows(b)]


def stream_blocks(tasks: List[ReadTask], ops: List[Op],
                  max_in_flight: int = 4,
                  locality: Optional[str] = None) -> Iterator[Block]:
    """Yield blocks across all partitions, in partition order."""
    if not tasks:
        return
    import ray_tpu
    if not ray_tpu.is_initialized():
        yield from _stream_local(tasks, ops)
        return

    remote_fn = ray_tpu.remote(num_cpus=1)(_run_partition)
    opts = {}
    if locality:
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        opts["scheduling_strategy"] = NodeAffinitySchedulingStrategy(
            node_id=locality, soft=True)
        remote_fn = remote_fn.options(**opts)

    window: List[Any] = []
    next_submit = 0
    while next_submit < len(tasks) or window:
        while next_submit < len(tasks) and len(window) < max_in_flight:
            window.append(remote_fn.remote(tasks[next_submit], ops))
            next_submit += 1
        blocks = ray_tpu.get(window.pop(0))
        for b in blocks:
            yield b


class _PoolWorker:
    """Long-lived actor that runs partition pipelines, keeping callable-
    class transform instances alive across partitions (reference
    data/_internal/execution/operators/actor_pool_map_operator.py)."""

    def __init__(self):
        self._instances: dict = {}

    def run_partition(self, task: ReadTask, ops: List[Op]) -> List[Block]:
        return [b for b in apply_ops(task(), ops, self._instances)
                if block_num_rows(b)]


def stream_blocks_actor_pool(tasks: List[ReadTask], ops: List[Op],
                             pool_size: int) -> Iterator[Block]:
    """Yield blocks in partition order, dispatching partitions to a pool
    of stateful actors (util.actor_pool handles ordered results +
    pool-width parallelism). Falls back to one local instance cache when
    the runtime is not initialized."""
    if not tasks:
        return
    import ray_tpu
    if not ray_tpu.is_initialized():
        instances: dict = {}
        for t in tasks:
            for b in apply_ops(t(), ops, instances):
                if block_num_rows(b):
                    yield b
        return

    from ray_tpu.util.actor_pool import ActorPool
    Actor = ray_tpu.remote(num_cpus=1)(_PoolWorker)
    actors = [Actor.remote() for _ in range(pool_size)]
    try:
        pool = ActorPool(actors)
        for blocks in pool.map(
                lambda a, t: a.run_partition.remote(t, ops), tasks):
            for b in blocks:
                yield b
    finally:
        for a in actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


def _stream_local(tasks: List[ReadTask], ops: List[Op]) -> Iterator[Block]:
    """Single background thread reads ahead one partition. The producer
    polls a closed flag on every put so an abandoned consumer (generator
    GC'd mid-stream) retires the thread instead of stranding it."""
    q: "queue.Queue" = queue.Queue(maxsize=2)
    SENTINEL = object()
    closed = threading.Event()

    from ray_tpu.data._util import put_unless_closed

    def _put(item) -> bool:
        return put_unless_closed(q, item, closed)

    def producer():
        try:
            for t in tasks:
                for b in apply_ops(t(), ops):
                    if block_num_rows(b):
                        if not _put(b):
                            return
            _put(SENTINEL)
        except BaseException as e:  # surface in consumer
            _put(e)

    th = threading.Thread(target=producer, daemon=True,
                          name="data-producer")
    th.start()
    try:
        while True:
            item = q.get()
            if item is SENTINEL:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        closed.set()
