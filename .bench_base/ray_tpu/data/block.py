"""Columnar block primitives for ray_tpu.data.

A Block is a dict[str, np.ndarray] whose arrays share their first
dimension (the row count). This is the TPU-era replacement for the
reference's pyarrow Block (reference python/ray/data/block.py): token
pipelines want contiguous numpy that `jax.device_put` can ship without
a format hop, and pyarrow remains available at the datasource edge for
parquet IO.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

import numpy as np

Block = Dict[str, np.ndarray]


def block_num_rows(block: Block) -> int:
    if not block:
        return 0
    return len(next(iter(block.values())))


def block_from_rows(rows: List[Dict[str, Any]]) -> Block:
    """Rows (list of dicts) -> columnar block.

    Rows may have heterogeneous key sets (optional JSONL fields are the
    norm): columns are the UNION of keys, absent values become None (the
    column is then object-dtyped), mirroring the reference's null-filling
    pyarrow conversion."""
    if not rows:
        return {}
    keys: List[str] = []
    seen = set()
    for r in rows:
        for k in r:
            if k not in seen:
                seen.add(k)
                keys.append(k)
    cols: Dict[str, list] = {
        k: [r.get(k) for r in rows] for k in keys}
    return {k: _to_array(v) for k, v in cols.items()}


def _to_array(values: list) -> np.ndarray:
    def _object_array():
        out = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            out[i] = v
        return out

    if any(v is None for v in values):   # nullable column
        return _object_array()
    first = values[0]
    if isinstance(first, np.ndarray):
        try:
            return np.stack(values)
        except ValueError:          # ragged: keep as object array
            return _object_array()
    arr = np.asarray(values)
    if arr.dtype.kind in ("U", "S"):
        arr = arr.astype(object)
    return arr


def block_to_rows(block: Block) -> Iterable[Dict[str, Any]]:
    n = block_num_rows(block)
    keys = list(block)
    for i in range(n):
        yield {k: block[k][i] for k in keys}


def block_slice(block: Block, start: int, stop: int) -> Block:
    return {k: v[start:stop] for k, v in block.items()}


def block_take(block: Block, indices: np.ndarray) -> Block:
    return {k: v[indices] for k, v in block.items()}


def block_concat(blocks: List[Block]) -> Block:
    """Concatenate blocks row-wise. Key sets may differ between blocks
    (a nullable column can be absent from a whole chunk): columns are
    the union, absent stretches are None-filled object columns —
    consistent with block_from_rows' row-level semantics."""
    blocks = [b for b in blocks if block_num_rows(b)]
    if not blocks:
        return {}
    if len(blocks) == 1:
        return blocks[0]
    keys: List[str] = []
    seen = set()
    for b in blocks:
        for k in b:
            if k not in seen:
                seen.add(k)
                keys.append(k)

    def col(b: Block, k: str) -> np.ndarray:
        if k in b:
            return b[k]
        filler = np.empty(block_num_rows(b), dtype=object)
        filler[:] = None
        return filler

    def obj_rows(c: np.ndarray) -> np.ndarray:
        """(n, ...) array -> (n,) object array of row sub-arrays, so a
        multi-dim column can concat with a None-filled stretch."""
        if c.dtype == object and c.ndim == 1:
            return c
        out = np.empty(len(c), dtype=object)
        for i in range(len(c)):
            out[i] = c[i]
        return out

    out: Block = {}
    for k in keys:
        cols = [col(b, k) for b in blocks]
        if any(c.dtype == object or c.ndim != cols[0].ndim
               for c in cols):
            cols = [obj_rows(c) for c in cols]
        out[k] = np.concatenate(cols)
    return out


def rebatch_blocks(blocks: Iterable[Block], batch_size: int,
                   drop_last: bool = False) -> Iterable[Block]:
    """Re-chunk a block stream into fixed-size row batches (the shared
    engine behind Dataset.iter_batches and map_batches(batch_size=...))."""
    buf: List[Block] = []
    have = 0
    for b in blocks:
        n = block_num_rows(b)
        if not n:
            continue
        buf.append(b)
        have += n
        while have >= batch_size:
            merged = block_concat(buf)
            yield block_slice(merged, 0, batch_size)
            rest = block_slice(merged, batch_size, have)
            have = block_num_rows(rest)
            buf = [rest] if have else []
    if have and not drop_last:
        yield block_concat(buf)


def validate_block(block: Block) -> None:
    lengths = {k: len(v) for k, v in block.items()}
    if len(set(lengths.values())) > 1:
        raise ValueError(f"ragged block: column lengths {lengths}")


def normalize_batch_output(out: Any) -> Block:
    """map_batches user fns may return dict of arrays/lists."""
    if not isinstance(out, dict):
        raise TypeError(
            f"map_batches fn must return a dict of columns, got "
            f"{type(out).__name__}")
    block = {k: (v if isinstance(v, np.ndarray) else _to_array(list(v)))
             for k, v in out.items()}
    validate_block(block)
    return block


class BlockMetadata:
    """Size/row accounting carried with each block (reference
    data/block.py BlockMetadata, trimmed to what the executor uses)."""

    __slots__ = ("num_rows", "size_bytes", "input_files")

    def __init__(self, num_rows: int, size_bytes: int,
                 input_files: Optional[List[str]] = None):
        self.num_rows = num_rows
        self.size_bytes = size_bytes
        self.input_files = input_files or []

    @staticmethod
    def of(block: Block,
           input_files: Optional[List[str]] = None) -> "BlockMetadata":
        size = sum(v.nbytes if isinstance(v, np.ndarray) else 0
                   for v in block.values())
        return BlockMetadata(block_num_rows(block), size, input_files)
