"""GroupedData: the result of ``Dataset.groupby``.

Parity: reference python/ray/data/grouped_data.py (GroupedData.aggregate,
count/sum/min/max/mean/std, map_groups) — implemented as a hash exchange
(shuffle.py) that co-locates each key's rows in one reduce partition,
then vectorized per-partition aggregation (aggregate.py).
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Union

from ray_tpu.data import aggregate as agg_mod
from ray_tpu.data import shuffle as sh


def _as_keys(key: Union[str, Sequence[str]]) -> List[str]:
    return [key] if isinstance(key, str) else list(key)


class GroupedData:
    def __init__(self, dataset, key: Union[str, Sequence[str]],
                 num_partitions: Optional[int] = None):
        self._ds = dataset
        self._keys = _as_keys(key)
        self._num_parts = num_partitions

    def _exchange(self, reduce_fn) -> "Any":
        from ray_tpu.data.dataset import Dataset
        ds = self._ds
        num_out = self._num_parts or max(1, min(ds.num_partitions(), 8))
        tasks = sh.exchange(
            ds._tasks, ds._ops,
            sh._map_hash, (self._keys, num_out),
            reduce_fn, num_out)
        return Dataset(tasks)

    def aggregate(self, *aggs: agg_mod.AggregateFn):
        """One output row per distinct key with a column per aggregate."""
        if not aggs:
            raise ValueError("aggregate() needs at least one AggregateFn")
        return self._exchange(
            sh.make_reduce_aggregate(self._keys, list(aggs)))

    def map_groups(self, fn: Callable) -> "Any":
        """Run `fn(group_block) -> dict-of-columns` once per key group."""
        return self._exchange(sh.make_reduce_map_groups(self._keys, fn))

    # convenience aggregates (reference grouped_data.py:244-400)
    def count(self):
        return self.aggregate(agg_mod.Count())

    def sum(self, on: str):
        return self.aggregate(agg_mod.Sum(on))

    def min(self, on: str):
        return self.aggregate(agg_mod.Min(on))

    def max(self, on: str):
        return self.aggregate(agg_mod.Max(on))

    def mean(self, on: str):
        return self.aggregate(agg_mod.Mean(on))

    def std(self, on: str, ddof: int = 1):
        return self.aggregate(agg_mod.Std(on, ddof=ddof))
