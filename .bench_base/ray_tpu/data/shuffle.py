"""Distributed shuffle exchange for ray_tpu.data.

Parity: reference python/ray/data/_internal/planner/exchange/
(ShuffleTaskSpec, sort_task_spec.py, push-based map/reduce shuffle) —
re-designed for the task API here: a map task runs one input partition
through its fused op chain and splits the rows into ``num_out`` shards
(``num_returns=num_out`` so each shard is an independently transferable
object); a reduce task takes the j-th shard of every map task as
top-level ref args and combines them. Runs in-process when the runtime
is not initialized (pure-local datasets).

Hashing is deterministic across worker processes (CRC32 / integer
mixing, never Python's per-process-salted ``hash``) so every map task
routes equal keys to the same reduce partition.
"""
from __future__ import annotations

import zlib
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu.data.block import (Block, block_concat, block_num_rows,
                                block_take)
from ray_tpu.data.datasource import ReadTask


def _hash_column(arr: np.ndarray, num_out: int) -> np.ndarray:
    """Deterministic per-row bucket assignment for one key column."""
    arr = np.asarray(arr)
    if arr.dtype.kind in "iub":
        v = arr.astype(np.uint64, copy=False)
        # Fibonacci/Knuth multiplicative mix so consecutive ints spread
        v = (v * np.uint64(0x9E3779B97F4A7C15))
        v ^= v >> np.uint64(29)
        return (v % np.uint64(num_out)).astype(np.int64)
    if arr.dtype.kind == "f":
        # +0.0 normalizes -0.0 so the equal keys share a bit pattern
        v = (arr.astype(np.float64) + 0.0).view(np.uint64)
        v = (v * np.uint64(0x9E3779B97F4A7C15))
        v ^= v >> np.uint64(29)
        return (v % np.uint64(num_out)).astype(np.int64)
    out = np.empty(len(arr), dtype=np.int64)
    for i, v in enumerate(arr):
        b = v.encode() if isinstance(v, str) else repr(v).encode()
        out[i] = zlib.crc32(b) % num_out
    return out


def hash_buckets(block: Block, keys: Sequence[str],
                 num_out: int) -> np.ndarray:
    """Bucket index per row from the combined key columns."""
    n = block_num_rows(block)
    combined = np.zeros(n, dtype=np.int64)
    for k in keys:
        h = _hash_column(block[k], 1 << 30)
        combined = (combined * 1315423911 + h) % (1 << 62)
    return (combined % num_out).astype(np.int64)


def split_by_bucket(blocks: List[Block], bucket_of: np.ndarray,
                    num_out: int) -> List[Block]:
    merged = block_concat(blocks)
    return [block_take(merged, np.nonzero(bucket_of == j)[0])
            for j in range(num_out)]


# ------------------------------------------------------------- exchange
def _map_hash(task: ReadTask, ops: list, idx: int,
              keys: Sequence[str], num_out: int) -> List[Block]:
    from ray_tpu.data.executor import apply_ops
    blocks = [b for b in apply_ops(task(), ops) if block_num_rows(b)]
    if not blocks:
        return [{} for _ in range(num_out)]
    merged = block_concat(blocks)
    return split_by_bucket([merged], hash_buckets(merged, keys, num_out),
                           num_out)


def _map_range(task: ReadTask, ops: list, idx: int, key: str,
               boundaries: np.ndarray, descending: bool,
               num_out: int) -> List[Block]:
    from ray_tpu.data.executor import apply_ops
    blocks = [b for b in apply_ops(task(), ops) if block_num_rows(b)]
    if not blocks:
        return [{} for _ in range(num_out)]
    merged = block_concat(blocks)
    vals = merged[key]
    idx = np.searchsorted(boundaries, vals, side="right")
    if descending:
        idx = (num_out - 1) - idx
    return split_by_bucket([merged], idx.astype(np.int64), num_out)


def _map_random(task: ReadTask, ops: list, idx: int,
                seed: Optional[int], num_out: int) -> List[Block]:
    from ray_tpu.data.executor import apply_ops
    blocks = [b for b in apply_ops(task(), ops) if block_num_rows(b)]
    if not blocks:
        return [{} for _ in range(num_out)]
    merged = block_concat(blocks)
    n = block_num_rows(merged)
    # decorrelate partitions by INDEX (task names are not unique);
    # seeded runs stay deterministic
    rng = np.random.default_rng(None if seed is None else [seed, idx])
    return split_by_bucket([merged], rng.integers(0, num_out, size=n),
                           num_out)


def make_reduce_permute(seed: Optional[int]):
    def _reduce(j: int, *shards: Block) -> List[Block]:
        merged = block_concat([s for s in shards if block_num_rows(s)])
        n = block_num_rows(merged)
        if not n:
            return []
        rng = np.random.default_rng(None if seed is None else [seed, j])
        return [block_take(merged, rng.permutation(n))]
    return _reduce


def _sample_keys(task: ReadTask, ops: list, key: str,
                 max_samples: int) -> np.ndarray:
    from ray_tpu.data.executor import apply_ops
    blocks = [b for b in apply_ops(task(), ops) if block_num_rows(b)]
    if not blocks:
        return np.empty(0)
    vals = np.concatenate([np.asarray(b[key]) for b in blocks])
    if len(vals) > max_samples:
        step = len(vals) // max_samples
        vals = vals[::step][:max_samples]
    return vals


def exchange(tasks: List[ReadTask], ops: list,
             map_fn: Callable[..., List[Block]], map_args: tuple,
             reduce_fn: Callable[..., List[Block]], num_out: int,
             ) -> List[ReadTask]:
    """Generic 2-stage exchange. Returns ReadTasks for the reduced
    partitions (driver holds refs; each reduce output streams on
    demand)."""
    import ray_tpu
    if not ray_tpu.is_initialized():
        shard_lists = [map_fn(t, ops, i, *map_args)
                       for i, t in enumerate(tasks)]
        out = []
        for j in range(num_out):
            shards = [s[j] for s in shard_lists]
            blocks = reduce_fn(j, *shards)
            out.append(ReadTask(lambda bs=blocks: iter(bs),
                                f"exchange[{j}]"))
        return out

    if num_out == 1:
        # num_returns=1 would store the whole List[Block] as the one
        # return object; unwrap to the single shard instead
        rmap = ray_tpu.remote(num_cpus=1)(_MapSingle(map_fn))
    else:
        rmap = ray_tpu.remote(num_cpus=1, num_returns=num_out)(map_fn)
    rreduce = ray_tpu.remote(num_cpus=1)(reduce_fn)
    shard_refs = [rmap.remote(t, ops, i, *map_args)
                  for i, t in enumerate(tasks)]
    if num_out == 1:
        shard_refs = [[r] for r in shard_refs]
    out = []
    for j in range(num_out):
        ref = rreduce.remote(j, *[s[j] for s in shard_refs])
        out.append(ReadTask(_RefRead(ref), f"exchange[{j}]"))
    return out


class _MapSingle:
    """Unwraps a map_fn's 1-element shard list for num_out == 1."""

    def __init__(self, map_fn):
        self._fn = map_fn

    def __call__(self, task, ops, idx, *args):
        return self._fn(task, ops, idx, *args)[0]


class _RefRead:
    """ReadTask body that resolves a reduce-output ref at stream time
    (inside whichever process consumes the partition)."""

    def __init__(self, ref):
        self._ref = ref

    def __call__(self):
        import ray_tpu
        blocks = ray_tpu.get(self._ref)
        return iter(blocks)


# ------------------------------------------------------------- reducers
def reduce_concat(j: int, *shards: Block) -> List[Block]:
    merged = block_concat([s for s in shards if block_num_rows(s)])
    return [merged] if block_num_rows(merged) else []


def make_reduce_aggregate(keys, aggs):
    from ray_tpu.data.aggregate import aggregate_partition

    def _reduce(j: int, *shards: Block) -> List[Block]:
        merged = block_concat([s for s in shards if block_num_rows(s)])
        out = aggregate_partition(merged, keys, aggs)
        return [out] if block_num_rows(out) else []
    return _reduce


def make_reduce_map_groups(keys, fn):
    from ray_tpu.data.aggregate import map_groups_partition

    def _reduce(j: int, *shards: Block) -> List[Block]:
        merged = block_concat([s for s in shards if block_num_rows(s)])
        return map_groups_partition(merged, keys, fn)
    return _reduce


def make_reduce_sort(key: str, descending: bool):
    def _reduce(j: int, *shards: Block) -> List[Block]:
        merged = block_concat([s for s in shards if block_num_rows(s)])
        if not block_num_rows(merged):
            return []
        order = np.argsort(merged[key], kind="stable")
        if descending:
            order = order[::-1]
        return [block_take(merged, order)]
    return _reduce


def sort_boundaries(tasks: List[ReadTask], ops: list, key: str,
                    num_out: int, max_samples_per_part: int = 256,
                    ) -> np.ndarray:
    """Stage 0 of sort: sample keys, pick num_out-1 range cut points
    (reference sort_task_spec.py SortKey sample_boundaries)."""
    import ray_tpu
    if ray_tpu.is_initialized():
        rs = ray_tpu.remote(num_cpus=1)(_sample_keys)
        samples = ray_tpu.get([rs.remote(t, ops, key, max_samples_per_part)
                               for t in tasks])
    else:
        samples = [_sample_keys(t, ops, key, max_samples_per_part)
                   for t in tasks]
    allv = np.concatenate([s for s in samples if len(s)]) if any(
        len(s) for s in samples) else np.empty(0)
    if not len(allv):
        return np.empty(0)
    qs = np.linspace(0, 1, num_out + 1)[1:-1]
    return np.quantile(np.sort(allv), qs, method="nearest") if \
        allv.dtype.kind in "iuf" else _object_quantiles(allv, num_out)


def _object_quantiles(vals: np.ndarray, num_out: int) -> np.ndarray:
    svals = np.sort(vals)
    idx = [int(len(svals) * (j + 1) / num_out) for j in range(num_out - 1)]
    return svals[np.clip(idx, 0, len(svals) - 1)]
