"""Shared helpers for background producer threads."""
from __future__ import annotations

import queue as _queue
import threading


def put_unless_closed(q: "_queue.Queue", item, closed: threading.Event,
                      poll_s: float = 0.1) -> bool:
    """Bounded-queue put that aborts when `closed` is set — so an
    abandoned consumer retires its producer thread instead of stranding
    it in a full-queue put forever. Returns False when closed."""
    while not closed.is_set():
        try:
            q.put(item, timeout=poll_s)
            return True
        except _queue.Full:
            continue
    return False
