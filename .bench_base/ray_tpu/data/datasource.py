"""Datasources: file/range/items readers producing ReadTasks.

Parity: reference python/ray/data/_internal/datasource/ (parquet, json,
csv readers) + read_api.py — re-shaped for the columnar numpy Block.
Each ReadTask is a picklable zero-arg callable returning an iterator of
Blocks, so the streaming executor can run it inside a ray_tpu task on
any worker.
"""
from __future__ import annotations

import glob
import json
import os
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

from ray_tpu.data.block import Block, block_from_rows, block_slice

ReadFn = Callable[[], Iterator[Block]]


class ReadTask:
    """One unit of parallel read work."""

    def __init__(self, fn: ReadFn, name: str,
                 input_files: Optional[List[str]] = None):
        self._fn = fn
        self.name = name
        self.input_files = input_files or []

    def __call__(self) -> Iterator[Block]:
        return self._fn()

    def __repr__(self) -> str:
        return f"ReadTask({self.name})"


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        p = os.fspath(p)
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if not f.startswith(".")))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths!r}")
    return out


# --------------------------------------------------------------- range
def range_tasks(n: int, num_blocks: int) -> List[ReadTask]:
    num_blocks = max(1, min(num_blocks, n) if n else 1)
    sizes = [n // num_blocks + (1 if i < n % num_blocks else 0)
             for i in range(num_blocks)]
    tasks, start = [], 0
    for i, sz in enumerate(sizes):
        lo, hi = start, start + sz
        start = hi

        def fn(lo=lo, hi=hi) -> Iterator[Block]:
            yield {"id": np.arange(lo, hi, dtype=np.int64)}

        tasks.append(ReadTask(fn, f"range[{lo}:{hi}]"))
    return tasks


# --------------------------------------------------------------- items
def items_tasks(items: List[Any], num_blocks: int) -> List[ReadTask]:
    n = len(items)
    num_blocks = max(1, min(num_blocks, n) if n else 1)
    sizes = [n // num_blocks + (1 if i < n % num_blocks else 0)
             for i in range(num_blocks)]
    tasks, start = [], 0
    for sz in sizes:
        chunk = items[start:start + sz]
        start += sz

        def fn(chunk=chunk) -> Iterator[Block]:
            rows = [r if isinstance(r, dict) else {"item": r}
                    for r in chunk]
            yield block_from_rows(rows)

        tasks.append(ReadTask(fn, f"items[{sz}]"))
    return tasks


# --------------------------------------------------------------- jsonl
def jsonl_tasks(paths, rows_per_block: int = 4096) -> List[ReadTask]:
    files = _expand_paths(paths)

    def read_one(path: str) -> Iterator[Block]:
        rows: List[Dict[str, Any]] = []
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rows.append(json.loads(line))
                if len(rows) >= rows_per_block:
                    yield block_from_rows(rows)
                    rows = []
        if rows:
            yield block_from_rows(rows)

    return [ReadTask(lambda p=p: read_one(p), f"jsonl[{os.path.basename(p)}]",
                     [p]) for p in files]


# ------------------------------------------------------------- parquet
def parquet_tasks(paths, columns: Optional[List[str]] = None,
                  rows_per_block: int = 65536) -> List[ReadTask]:
    files = _expand_paths(paths)

    def read_one(path: str) -> Iterator[Block]:
        import pyarrow.parquet as pq
        pf = pq.ParquetFile(path)
        for batch in pf.iter_batches(batch_size=rows_per_block,
                                     columns=columns):
            block: Block = {}
            for name, col in zip(batch.schema.names, batch.columns):
                arr = col.to_numpy(zero_copy_only=False)
                if arr.dtype.kind in ("U", "S"):
                    arr = arr.astype(object)
                block[name] = arr
            yield block

    return [ReadTask(lambda p=p: read_one(p),
                     f"parquet[{os.path.basename(p)}]", [p])
            for p in files]


# ----------------------------------------------------------------- csv
def csv_tasks(paths, rows_per_block: int = 65536) -> List[ReadTask]:
    files = _expand_paths(paths)

    def read_one(path: str) -> Iterator[Block]:
        import pyarrow.csv as pacsv
        table = pacsv.read_csv(path)
        n = table.num_rows
        cols = {name: table.column(name).to_numpy(zero_copy_only=False)
                for name in table.schema.names}
        block = {k: (v.astype(object) if v.dtype.kind in ("U", "S") else v)
                 for k, v in cols.items()}
        for lo in range(0, n, rows_per_block):
            yield block_slice(block, lo, min(lo + rows_per_block, n))

    return [ReadTask(lambda p=p: read_one(p),
                     f"csv[{os.path.basename(p)}]", [p]) for p in files]


# ----------------------------------------------------------- write side
def write_jsonl(blocks: Iterator[Block], path: str) -> List[str]:
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, "part-00000.jsonl")
    from ray_tpu.data.block import block_to_rows
    with open(out, "w", encoding="utf-8") as f:
        for block in blocks:
            for row in block_to_rows(block):
                f.write(json.dumps({k: _json_safe(v)
                                    for k, v in row.items()}) + "\n")
    return [out]


def write_parquet(blocks: Iterator[Block], path: str) -> List[str]:
    import pyarrow as pa
    import pyarrow.parquet as pq
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, "part-00000.parquet")
    tables = []
    for block in blocks:
        tables.append(pa.table(
            {k: pa.array(list(v)) for k, v in block.items()}))
    if tables:
        pq.write_table(pa.concat_tables(tables), out)
    return [out]


def _json_safe(v):
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, np.bool_):
        return bool(v)
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v


# ------------------------------------------------------------- text/bin
def text_tasks(paths, rows_per_block: int = 65536) -> List[ReadTask]:
    """One row per line, column 'text' (reference read_text)."""
    files = _expand_paths(paths)

    def read_one(path: str) -> Iterator[Block]:
        rows: List[Dict[str, Any]] = []
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                rows.append({"text": line.rstrip("\n")})
                if len(rows) >= rows_per_block:
                    yield block_from_rows(rows)
                    rows = []
        if rows:
            yield block_from_rows(rows)

    return [ReadTask(lambda p=p: read_one(p),
                     f"text[{os.path.basename(p)}]", [p]) for p in files]


def binary_tasks(paths, include_paths: bool = True) -> List[ReadTask]:
    """One row per file: {'bytes': ..., 'path': ...} (reference
    read_binary_files)."""
    files = _expand_paths(paths)

    def read_one(path: str) -> Iterator[Block]:
        with open(path, "rb") as f:
            data = f.read()
        row: Dict[str, Any] = {"bytes": data}
        if include_paths:
            row["path"] = path
        yield block_from_rows([row])

    return [ReadTask(lambda p=p: read_one(p),
                     f"binary[{os.path.basename(p)}]", [p])
            for p in files]


def image_tasks(paths, size=None, mode: str = "RGB",
                include_paths: bool = False) -> List[ReadTask]:
    """Decode images via PIL into uint8 arrays, column 'image'
    ((H,W,C) per row; with `size=(h,w)` all rows share one shape so the
    column is a dense (N,H,W,C) batch). Reference read_images."""
    files = _expand_paths(paths)

    def read_one(path: str) -> Iterator[Block]:
        from PIL import Image
        img = Image.open(path)
        if mode:
            img = img.convert(mode)
        if size is not None:
            img = img.resize((size[1], size[0]))
        row: Dict[str, Any] = {"image": np.asarray(img)}
        if include_paths:
            row["path"] = path
        yield block_from_rows([row])

    return [ReadTask(lambda p=p: read_one(p),
                     f"image[{os.path.basename(p)}]", [p])
            for p in files]


# ------------------------------------------------------------ tfrecords
# Pure-python tf.train.Example wire codec: the TFRecord container is
# [uint64 len][crc32c(len)][payload][crc32c(payload)] and the payload is
# an Example proto — Example{1: Features{1: map<string, Feature>}},
# Feature = oneof BytesList(1){bytes 1} / FloatList(2){packed float 1} /
# Int64List(3){packed varint 1}. No tensorflow/protobuf dependency.
# Parity: reference data/_internal/datasource/tfrecords_datasource.py.

_CRC_TABLE = None


def _crc32c(data: bytes) -> int:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78          # Castagnoli, reflected
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    try:
        from ray_tpu import native
        if native.available():          # ~1.5 GB/s vs ~7 MB/s in Python
            return native.masked_crc32c(data)
    except Exception:
        pass
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def _read_varint(buf, pos):
    shift = val = 0
    while True:
        b = buf[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7


def _iter_proto_fields(buf):
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        fnum, wtype = tag >> 3, tag & 7
        if wtype == 0:
            val, pos = _read_varint(buf, pos)
        elif wtype == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wtype == 5:
            val = buf[pos:pos + 4]
            pos += 4
        elif wtype == 1:
            val = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wtype}")
        yield fnum, wtype, val


def _parse_feature(buf) -> list:
    import struct
    for fnum, _wt, val in _iter_proto_fields(buf):
        if fnum == 1:      # BytesList
            return [v for f, _, v in _iter_proto_fields(val) if f == 1]
        if fnum == 2:      # FloatList (packed or repeated)
            out: list = []
            for f, wt2, v in _iter_proto_fields(val):
                if f != 1:
                    continue
                if wt2 == 2:
                    out.extend(struct.unpack(f"<{len(v) // 4}f", v))
                else:
                    out.append(struct.unpack("<f", v)[0])
            return [float(x) for x in out]
        if fnum == 3:      # Int64List (packed or repeated varint)
            out = []
            for f, wt2, v in _iter_proto_fields(val):
                if f != 1:
                    continue
                vals = []
                if wt2 == 2:
                    p = 0
                    while p < len(v):
                        x, p = _read_varint(v, p)
                        vals.append(x)
                else:
                    vals.append(v)
                for x in vals:
                    out.append(x - (1 << 64) if x >= (1 << 63) else x)
            return out
    return []


def _parse_example(buf) -> Dict[str, Any]:
    feats: Dict[str, Any] = {}
    for fnum, _wt, val in _iter_proto_fields(buf):
        if fnum != 1:
            continue                       # Features
        for f2, _w2, entry in _iter_proto_fields(val):
            if f2 != 1:
                continue                   # map entry
            key, feature = None, b""
            for f3, _w3, v3 in _iter_proto_fields(entry):
                if f3 == 1:
                    key = v3.decode("utf-8")
                elif f3 == 2:
                    feature = v3
            if key is not None:
                vals = _parse_feature(feature)
                feats[key] = vals[0] if len(vals) == 1 else np.asarray(
                    vals) if vals and not isinstance(vals[0], bytes) \
                    else vals
    return feats


def tfrecord_tasks(paths, rows_per_block: int = 4096) -> List[ReadTask]:
    files = _expand_paths(paths)

    def read_one(path: str) -> Iterator[Block]:
        import struct
        rows: List[Dict[str, Any]] = []
        with open(path, "rb") as f:
            while True:
                header = f.read(8)
                if len(header) < 8:
                    break
                (length,) = struct.unpack("<Q", header)
                f.read(4)                  # length crc (not verified)
                payload = f.read(length)
                if len(payload) < length:
                    raise ValueError(
                        f"corrupted TFRecord {path!r}: record claims "
                        f"{length} bytes, file has {len(payload)}")
                f.read(4)                  # payload crc
                rows.append(_parse_example(payload))
                if len(rows) >= rows_per_block:
                    yield block_from_rows(rows)
                    rows = []
        if rows:
            yield block_from_rows(rows)

    return [ReadTask(lambda p=p: read_one(p),
                     f"tfrecord[{os.path.basename(p)}]", [p])
            for p in files]


def _enc_varint(val: int) -> bytes:
    if val < 0:
        val += 1 << 64
    out = bytearray()
    while True:
        b = val & 0x7F
        val >>= 7
        if val:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _enc_field(fnum: int, payload: bytes) -> bytes:
    return _enc_varint((fnum << 3) | 2) + _enc_varint(len(payload)) \
        + payload


def _enc_feature(value) -> bytes:
    import struct
    if isinstance(value, np.ndarray):
        value = value.tolist()
    if not isinstance(value, (list, tuple)):
        value = [value]
    if all(isinstance(v, (bytes, str)) for v in value):
        payload = b"".join(
            _enc_field(1, v.encode("utf-8") if isinstance(v, str) else v)
            for v in value)
        return _enc_field(1, payload)      # BytesList
    if all(isinstance(v, (int, np.integer)) for v in value):
        packed = b"".join(_enc_varint(int(v)) for v in value)
        return _enc_field(3, _enc_field(1, packed))   # Int64List
    packed = struct.pack(f"<{len(value)}f", *[float(v) for v in value])
    return _enc_field(2, _enc_field(1, packed))       # FloatList


def write_tfrecords(blocks: Iterator[Block], path: str) -> List[str]:
    """Write rows as tf.train.Example TFRecords (valid masked-crc32c
    framing: readable by TF's TFRecordDataset and by tfrecord_tasks)."""
    import struct

    from ray_tpu.data.block import block_to_rows
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, "part-00000.tfrecords")
    with open(out, "wb") as f:
        for block in blocks:
            for row in block_to_rows(block):
                entries = b""
                for k, v in row.items():
                    entry = _enc_field(1, k.encode("utf-8")) \
                        + _enc_field(2, _enc_feature(v))
                    entries += _enc_field(1, entry)
                example = _enc_field(1, entries)
                header = struct.pack("<Q", len(example))
                f.write(header)
                f.write(struct.pack("<I", _masked_crc(header)))
                f.write(example)
                f.write(struct.pack("<I", _masked_crc(example)))
    return [out]


def write_csv(blocks: Iterator[Block], path: str) -> List[str]:
    import csv

    from ray_tpu.data.block import block_to_rows
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, "part-00000.csv")
    writer = None
    with open(out, "w", newline="", encoding="utf-8") as f:
        for block in blocks:
            for row in block_to_rows(block):
                if writer is None:
                    writer = csv.DictWriter(f, fieldnames=list(row))
                    writer.writeheader()
                writer.writerow({k: _json_safe(v) for k, v in row.items()})
    return [out]
