"""ray_tpu.data: streaming datasets feeding TPU training.

Parity target: reference python/ray/data/ (Dataset dataset.py:141,
streaming executor _internal/execution/streaming_executor.py:48) — the
subset SURVEY.md §7 step 7 calls for: read → map_batches → shuffle →
iter_batches yielding sharded jax.Arrays, executed as bounded-window
remote tasks over the ray_tpu runtime.
"""
from ray_tpu.data import aggregate  # noqa: F401
from ray_tpu.data.aggregate import (AbsMax, AggregateFn, Count, Max, Mean,
                                    Min, Std, Sum)
from ray_tpu.data.block import Block, BlockMetadata
from ray_tpu.data.dataset import (ActorPoolStrategy, DataIterator, Dataset,
                                  from_items, from_numpy, range, read_csv,
                                  read_binary_files, read_images,
                                  read_json, read_parquet, read_text,
                                  read_tfrecords)
from ray_tpu.data.grouped_data import GroupedData
from ray_tpu.data.jax_iter import iter_jax_batches
from ray_tpu.data.streaming import StageSpec

__all__ = [
    "Block", "BlockMetadata", "DataIterator", "Dataset", "from_items",
    "from_numpy", "range", "read_csv", "read_json", "read_parquet",
    "read_text", "read_binary_files", "read_images", "read_tfrecords",
    "iter_jax_batches", "ActorPoolStrategy", "GroupedData", "StageSpec",
    "AggregateFn", "Count", "Sum", "Min", "Max", "Mean", "Std", "AbsMax",
]
