"""Host→device batch feeding: sharded jax.Arrays with prefetch.

The Train ingestion edge (reference data/iterator.py iter_torch_batches
analogue, TPU-shaped): numpy batches stream off the Dataset while the
PREVIOUS batch's `jax.device_put` transfer overlaps the current step —
a two-deep pipeline so input never serializes with compute unless the
pipeline genuinely underruns (tracked in `stats()`).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Iterator, Optional

import numpy as np


class _Prefetcher:
    """Bounded background producer of host batches.

    `close()` unblocks and retires the producer thread when the consumer
    abandons the iterator early (the common `zip(range(steps), it)` loop)
    — without it the thread would sit in q.put forever, pinning batches."""

    def __init__(self, it: Iterator[Dict[str, np.ndarray]], depth: int):
        import queue
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._sentinel = object()
        self._closed = threading.Event()
        self.wait_s = 0.0

        def run():
            try:
                for item in it:
                    if not self._put(item):
                        return
                self._put(self._sentinel)
            except BaseException as e:
                self._put(e)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="data-prefetch")
        self._thread.start()

    def _put(self, item) -> bool:
        from ray_tpu.data._util import put_unless_closed
        return put_unless_closed(self._q, item, self._closed)

    def close(self) -> None:
        self._closed.set()

    def __iter__(self):
        while True:
            t0 = time.perf_counter()
            item = self._q.get()
            self.wait_s += time.perf_counter() - t0
            if item is self._sentinel:
                return
            if isinstance(item, BaseException):
                raise item
            yield item


def iter_jax_batches(dataset, *, batch_size: int,
                     sharding=None,
                     dtypes: Optional[Dict[str, str]] = None,
                     drop_last: bool = True,
                     local_shuffle_buffer_size: int = 0,
                     seed: Optional[int] = None,
                     prefetch_depth: int = 2,
                     stats: Optional[dict] = None):
    """Yield dict[str, jax.Array] batches.

    `sharding`: a jax.sharding.Sharding (e.g. NamedSharding(mesh,
    P("dp"))) applied on device_put — the per-host batch lands already
    laid out for the train step, no resharding inside jit.
    """
    import jax

    host_iter = dataset.iter_batches(
        batch_size=batch_size, drop_last=drop_last,
        local_shuffle_buffer_size=local_shuffle_buffer_size, seed=seed)
    pf = _Prefetcher(host_iter, prefetch_depth)

    def put(batch: Dict[str, np.ndarray]):
        out = {}
        for k, v in batch.items():
            if dtypes and k in dtypes:
                v = v.astype(dtypes[k])
            out[k] = (jax.device_put(v, sharding) if sharding is not None
                      else jax.device_put(v))
        return out

    pending = None
    n = 0
    try:
        for batch in pf:
            nxt = put(batch)        # start async transfer
            if pending is not None:
                yield pending
                n += 1
            pending = nxt
        if pending is not None:
            yield pending
            n += 1
    finally:
        # runs on normal exhaustion AND GeneratorExit when the consumer
        # abandons the loop early — either way the producer must die.
        pf.close()
        if stats is not None:
            stats["num_batches"] = n
            stats["input_wait_s"] = pf.wait_s
