"""Durable workflows: step-checkpointed task graphs.

Parity: reference python/ray/workflow (workflow_executor.py — each step
persists its result; a resumed workflow replays completed steps from
storage instead of re-executing them; workflow_state.py — per-step
metadata + retry/catch options; api.py list_all/get_metadata).
Re-shaped for this stack:

- `@workflow.step` wraps a function; inside a running workflow each
  invocation is one durable unit. Step identity = call order + function
  name + a content hash of the arguments: a replayed step must match
  the stored content key, otherwise it is re-executed (and everything
  downstream re-keys off the fresh result), so editing/reordering a
  branch between run and resume cannot silently replay wrong results.
- Per-step options (reference workflow.options): `max_retries` rides
  the task layer (worker death / system failures); `retry_exceptions`
  additionally retries application exceptions; `timeout` bounds one
  attempt (the timed-out task is cancelled, the attempt counts against
  retries); `catch_exceptions` returns `(result, None)` /
  `(None, exc)` instead of raising.
- `workflow.run(entry_fn, *args, workflow_id=..., storage=...)`
  executes the entry function; every step result is pickled under
  `<storage>/<workflow_id>/steps/` with metadata (attempts, duration)
  and an append-only `events.jsonl` (started/completed/replayed/
  invalidated/failed per step).
- `workflow.resume(workflow_id, storage=...)` re-runs the entry
  function (persisted at first run); completed steps return their
  stored results without executing, so the workflow continues from the
  first incomplete step. `list_workflows()` + `get_metadata()` expose
  status (RUNNING/SUCCEEDED/FAILED) and per-step records.

Steps execute as ray_tpu tasks (isolation + retries ride the task
layer). Non-step code in the entry function re-runs on resume — keep
side effects inside steps, exactly as the reference demands.
"""
from __future__ import annotations

import contextvars
import functools
import hashlib
import json
import os
import pickle
import time
from typing import Any, Callable, Optional, Union

import cloudpickle

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError, TaskError

_DEFAULT_STORAGE = os.path.expanduser("~/ray_tpu_workflows")

_ctx: contextvars.ContextVar[Optional["_WorkflowContext"]] = (
    contextvars.ContextVar("rtpu_workflow_ctx", default=None))


class WorkflowNotFoundError(Exception):
    pass


class StepTimeoutError(Exception):
    """A step attempt exceeded its `timeout` option."""


def _digest(obj) -> bytes:
    """Canonical digest: containers are hashed structurally (sets
    element-order-independently — raw pickle bytes of a set vary with
    PYTHONHASHSEED across processes), leaves via cloudpickle."""
    if isinstance(obj, dict):
        # insertion order is deterministic for the same code path
        return b"d" + b"".join(_digest(k) + _digest(v)
                               for k, v in obj.items())
    if isinstance(obj, (set, frozenset)):
        return b"s" + b"".join(sorted(_digest(x) for x in obj))
    if isinstance(obj, (list, tuple)):
        return b"l" + b"".join(_digest(x) for x in obj)
    return hashlib.sha256(cloudpickle.dumps(obj)).digest()


def _content_key(name: str, args, kwargs) -> Optional[str]:
    """Stable digest of a step invocation. None when the args don't
    pickle deterministically enough to hash (then identity falls back
    to call order + name, the pre-round-5 contract)."""
    try:
        payload = _digest((name, list(args), kwargs))
    except Exception:
        return None
    return hashlib.sha256(payload).hexdigest()[:16]


class _WorkflowContext:
    def __init__(self, workflow_id: str, storage: str):
        self.workflow_id = workflow_id
        self.dir = os.path.join(storage, workflow_id)
        self.steps_dir = os.path.join(self.dir, "steps")
        os.makedirs(self.steps_dir, exist_ok=True)
        self.call_index = 0
        self.num_replayed = 0
        self.num_executed = 0
        self.num_invalidated = 0

    def step_path(self, name: str) -> str:
        idx = self.call_index
        self.call_index += 1
        return os.path.join(self.steps_dir, f"{idx:05d}_{name}.pkl")

    def event(self, step: str, kind: str, **extra) -> None:
        row = {"ts": time.time(), "step": step, "event": kind, **extra}
        with open(os.path.join(self.dir, "events.jsonl"), "a") as f:
            f.write(json.dumps(row) + "\n")

    def set_status(self, status: str) -> None:
        tmp = os.path.join(self.dir, "status.json.tmp")
        with open(tmp, "w") as f:
            json.dump({"status": status, "ts": time.time()}, f)
        os.replace(tmp, os.path.join(self.dir, "status.json"))


class WorkflowStep:
    """A durable unit. Called inside workflow.run: executes as a task
    and persists; outside a workflow: plain call."""

    def __init__(self, fn: Callable, name: Optional[str] = None,
                 max_retries: int = 3,
                 retry_exceptions: Union[bool, tuple] = False,
                 timeout: Optional[float] = None,
                 catch_exceptions: bool = False):
        self._fn = fn
        self.name = name or fn.__name__
        self.max_retries = max_retries
        if isinstance(retry_exceptions, type):  # bare exception class
            retry_exceptions = (retry_exceptions,)
        self.retry_exceptions = retry_exceptions
        self.timeout = timeout
        self.catch_exceptions = catch_exceptions
        self._remote = ray_tpu.remote(max_retries=max_retries)(fn)
        functools.update_wrapper(self, fn)

    def options(self, **overrides) -> "WorkflowStep":
        """Reference step.options(): a copy with per-call overrides."""
        merged = dict(name=self.name, max_retries=self.max_retries,
                      retry_exceptions=self.retry_exceptions,
                      timeout=self.timeout,
                      catch_exceptions=self.catch_exceptions)
        merged.update(overrides)
        return WorkflowStep(self._fn, **merged)

    def _retryable(self, exc: Exception) -> bool:
        if isinstance(exc, StepTimeoutError):
            return True          # timeouts always count against retries
        # app exceptions surface wrapped in TaskError; match the cause
        if isinstance(exc, TaskError) and exc.cause is not None:
            exc = exc.cause
        if self.retry_exceptions is True:
            return True
        if self.retry_exceptions:
            return isinstance(exc, tuple(self.retry_exceptions))
        return False

    def _execute_once(self, args, kwargs):
        ref = self._remote.remote(*args, **kwargs)
        try:
            return ray_tpu.get(ref, timeout=self.timeout)
        except GetTimeoutError:
            try:
                ray_tpu.cancel(ref, force=True)
            except Exception:
                pass
            raise StepTimeoutError(
                f"step {self.name!r} exceeded {self.timeout}s") from None

    def __call__(self, *args, **kwargs):
        ctx = _ctx.get()
        if ctx is None:
            return self._fn(*args, **kwargs)
        path = ctx.step_path(self.name)
        key = _content_key(self.name, args, kwargs)
        base = os.path.basename(path)
        # a checkpoint at this position under a *different* step name
        # (branch renamed/removed between runs) is stale: drop it so it
        # neither replays wrongly nor lingers in status/metadata
        import glob as _glob
        for other in _glob.glob(os.path.join(
                ctx.steps_dir, base.split("_", 1)[0] + "_*.pkl")):
            if os.path.basename(other) != base:
                os.remove(other)
                ctx.num_invalidated += 1
                ctx.event(self.name, "invalidated",
                          stale=os.path.basename(other))
        if os.path.exists(path):
            with open(path, "rb") as f:
                rec = pickle.load(f)
            stored_key = rec.get("key")
            if key is None or stored_key is None or stored_key == key:
                ctx.num_replayed += 1
                ctx.event(self.name, "replayed", path=base)
                if "error" in rec:   # durable caught failure
                    if self.catch_exceptions:
                        return (None, rec["error"])
                    raise rec["error"]
                result = rec["result"]
                if self.catch_exceptions:
                    return (result, None)
                return result
            # The call at this position no longer matches what was
            # checkpointed (branch edited/reordered): re-execute.
            ctx.num_invalidated += 1
            ctx.event(self.name, "invalidated",
                      stored_key=stored_key, new_key=key)

        attempts = 0
        start = time.time()
        ctx.event(self.name, "started")
        while True:
            attempts += 1
            try:
                result = self._execute_once(args, kwargs)
                break
            except Exception as e:
                if self._retryable(e) and attempts <= self.max_retries:
                    ctx.event(self.name, "retrying", attempt=attempts,
                              error=repr(e))
                    continue
                ctx.event(self.name, "failed", attempt=attempts,
                          error=repr(e))
                if self.catch_exceptions:
                    if isinstance(e, TaskError) and e.cause is not None:
                        e = e.cause
                    # the caught failure is itself durable: resume must
                    # not silently re-run the step's side effects
                    try:
                        tmp = path + ".tmp"
                        with open(tmp, "wb") as f:
                            pickle.dump({"error": e, "key": key,
                                         "meta": {"attempts": attempts}},
                                        f)
                        os.replace(tmp, path)
                    except Exception:
                        pass  # unpicklable exception: re-run on resume
                    return (None, e)
                raise
        meta = {"attempts": attempts, "start_ts": start,
                "duration_s": time.time() - start}
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump({"result": result, "key": key, "meta": meta}, f)
        os.replace(tmp, path)            # atomic: crash-safe commit
        ctx.num_executed += 1
        ctx.event(self.name, "completed", **meta)
        if self.catch_exceptions:
            return (result, None)
        return result


def step(fn: Optional[Callable] = None, *, name: Optional[str] = None,
         max_retries: int = 3,
         retry_exceptions: Union[bool, tuple] = False,
         timeout: Optional[float] = None,
         catch_exceptions: bool = False):
    """`@workflow.step` / `@workflow.step(name=..., max_retries=...,
    retry_exceptions=..., timeout=..., catch_exceptions=...)`."""
    if fn is not None:
        return WorkflowStep(fn)
    return lambda f: WorkflowStep(
        f, name=name, max_retries=max_retries,
        retry_exceptions=retry_exceptions, timeout=timeout,
        catch_exceptions=catch_exceptions)


def run(entry_fn: Callable, *args, workflow_id: str,
        storage: Optional[str] = None, **kwargs) -> Any:
    """Execute a workflow to completion; durable against re-runs."""
    storage = storage or _DEFAULT_STORAGE
    ctx = _WorkflowContext(workflow_id, storage)
    # persist the entry point + args so resume() can replay it
    entry_path = os.path.join(ctx.dir, "entry.pkl")
    if not os.path.exists(entry_path):
        with open(entry_path, "wb") as f:
            cloudpickle.dump({"fn": entry_fn, "args": args,
                              "kwargs": kwargs}, f)
    global _LAST_STATS
    ctx.set_status("RUNNING")
    token = _ctx.set(ctx)
    try:
        result = entry_fn(*args, **kwargs)
    except BaseException:
        ctx.set_status("FAILED")
        raise
    finally:
        _ctx.reset(token)
        _LAST_STATS = {"replayed": ctx.num_replayed,
                       "executed": ctx.num_executed,
                       "invalidated": ctx.num_invalidated}
    rpath = os.path.join(ctx.dir, "result.pkl")
    try:
        with open(rpath + ".tmp", "wb") as f:
            pickle.dump({"result": result}, f)
        os.replace(rpath + ".tmp", rpath)
    except Exception:
        ctx.set_status("FAILED")
        raise
    ctx.set_status("SUCCEEDED")
    return result


def resume(workflow_id: str, storage: Optional[str] = None) -> Any:
    """Re-run a workflow: finished steps replay from storage; a stored
    final result short-circuits entirely."""
    storage = storage or _DEFAULT_STORAGE
    wdir = os.path.join(storage, workflow_id)
    result_path = os.path.join(wdir, "result.pkl")
    if os.path.exists(result_path):
        try:
            with open(result_path, "rb") as f:
                return pickle.load(f)["result"]
        except Exception:
            os.remove(result_path)   # truncated by a crash: replay
    entry_path = os.path.join(wdir, "entry.pkl")
    if not os.path.exists(entry_path):
        raise WorkflowNotFoundError(
            f"no workflow {workflow_id!r} under {storage}")
    with open(entry_path, "rb") as f:
        entry = cloudpickle.load(f)
    return run(entry["fn"], *entry["args"], workflow_id=workflow_id,
               storage=storage, **entry["kwargs"])


def get_status(workflow_id: str,
               storage: Optional[str] = None) -> dict:
    storage = storage or _DEFAULT_STORAGE
    wdir = os.path.join(storage, workflow_id)
    if not os.path.isdir(wdir):
        raise WorkflowNotFoundError(workflow_id)
    steps = sorted(os.listdir(os.path.join(wdir, "steps")))
    steps = [s for s in steps if s.endswith(".pkl")]
    status = "RUNNING"
    spath = os.path.join(wdir, "status.json")
    if os.path.exists(spath):
        with open(spath) as f:
            status = json.load(f)["status"]
    return {
        "workflow_id": workflow_id,
        "status": status,
        "finished": os.path.exists(os.path.join(wdir, "result.pkl")),
        "steps_completed": len(steps),
        "steps": steps,
    }


def get_metadata(workflow_id: str,
                 storage: Optional[str] = None) -> dict:
    """Workflow-level status + per-step records (attempts, duration)
    + the event log. Parity: reference workflow.get_metadata."""
    storage = storage or _DEFAULT_STORAGE
    info = get_status(workflow_id, storage)
    wdir = os.path.join(storage, workflow_id)
    step_meta = {}
    for fname in info["steps"]:
        with open(os.path.join(wdir, "steps", fname), "rb") as f:
            rec = pickle.load(f)
        step_meta[fname] = {"key": rec.get("key"), **rec.get("meta", {})}
    events = []
    epath = os.path.join(wdir, "events.jsonl")
    if os.path.exists(epath):
        with open(epath) as f:
            events = [json.loads(line) for line in f if line.strip()]
    info["step_metadata"] = step_meta
    info["events"] = events
    return info


def list_workflows(storage: Optional[str] = None) -> list:
    """All workflow ids under storage with their status.
    Parity: reference workflow.list_all()."""
    storage = storage or _DEFAULT_STORAGE
    if not os.path.isdir(storage):
        return []
    out = []
    for wid in sorted(os.listdir(storage)):
        if os.path.isdir(os.path.join(storage, wid, "steps")):
            out.append((wid, get_status(wid, storage)["status"]))
    return out


_LAST_STATS: dict = {}


def last_run_stats() -> dict:
    """Replay/execute counters of the most recent run/resume in this
    process (observability + tests)."""
    return dict(_LAST_STATS)
