"""Native core loader: build-on-first-use C library + ctypes bindings.

`core.c` holds the GIL-free channel wait primitive and the CRC32C used
by TFRecord IO (see its header comment for the reference parity map).
The library is compiled once per host with the system C compiler into
``~/.ray_tpu/native/<source-hash>.so`` (override the cache root with
``RAY_TPU_RUNTIME_ENV_DIR``'s sibling ``RAY_TPU_NATIVE_DIR``) and
loaded via ctypes — no pybind11/setuptools dependency, and every
caller keeps a pure-Python fallback, so a host without a compiler
still works (``RAY_TPU_DISABLE_NATIVE=1`` forces the fallbacks).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
import threading
from typing import Optional

_SRC = os.path.join(os.path.dirname(__file__), "core.c")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _cache_dir() -> str:
    return os.path.expanduser(
        os.environ.get("RAY_TPU_NATIVE_DIR", "~/.ray_tpu/native"))


def _build() -> Optional[str]:
    with open(_SRC, "rb") as f:
        src = f.read()
    tag = hashlib.sha1(src).hexdigest()[:16]
    out = os.path.join(_cache_dir(), f"core_{tag}.so")
    if os.path.exists(out):
        return out
    cc = os.environ.get("CC") or "cc"
    os.makedirs(_cache_dir(), exist_ok=True)
    tmp = out + f".tmp{os.getpid()}"
    cmd = [cc, "-O2", "-shared", "-fPIC", "-o", tmp, _SRC]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=60)
        if proc.returncode != 0:
            sys.stderr.write(
                f"ray_tpu: native core build failed "
                f"({' '.join(cmd)}):\n{proc.stderr}\n"
                f"falling back to pure-Python paths\n")
            return None
        os.replace(tmp, out)            # atomic vs concurrent builders
        return out
    except (OSError, subprocess.TimeoutExpired):
        return None
    finally:
        import contextlib
        with contextlib.suppress(OSError):
            os.unlink(tmp)              # failure paths leave no litter


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("RAY_TPU_DISABLE_NATIVE"):
            return None
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.rtpu_wait_u64s_ge.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_int64]
        lib.rtpu_wait_u64s_ge.restype = ctypes.c_int
        lib.rtpu_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.rtpu_crc32c.restype = ctypes.c_uint32
        lib.rtpu_masked_crc32c.argtypes = [ctypes.c_char_p,
                                           ctypes.c_size_t]
        lib.rtpu_masked_crc32c.restype = ctypes.c_uint32
        _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def wait_u64s_ge(mv: memoryview, offset: int, count: int, value: int,
                 timeout_s: Optional[float]) -> bool:
    """Block (GIL released) until the `count` u64 words at `offset` in
    the writable buffer `mv` are all >= value. True on success, False
    on timeout. Caller guarantees the buffer outlives the call."""
    lib = _load()
    assert lib is not None, "call native.available() first"
    base = ctypes.addressof(ctypes.c_char.from_buffer(mv, offset))
    t_ns = -1 if timeout_s is None else max(0, int(timeout_s * 1e9))
    return lib.rtpu_wait_u64s_ge(base, count, value, t_ns) == 0


def crc32c(data: bytes) -> int:
    lib = _load()
    assert lib is not None
    return int(lib.rtpu_crc32c(data, len(data)))


def masked_crc32c(data: bytes) -> int:
    lib = _load()
    assert lib is not None
    return int(lib.rtpu_masked_crc32c(data, len(data)))
