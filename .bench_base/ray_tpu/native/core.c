/* ray_tpu native core: the latency/throughput-critical leaves the
 * Python runtime can't do well while holding the GIL.
 *
 * Parity intent: the reference implements its mutable-object wait
 * loops and checksum paths in C++ (src/ray/core_worker/
 * experimental_mutable_object_manager.cc waits on futex-backed
 * semaphores; src/ray/util crc32c). Here:
 *
 *  - rtpu_wait_u64s_ge: spin/backoff until `count` contiguous
 *    little-endian u64 words are all >= value. Called through ctypes,
 *    so the GIL is RELEASED for the whole wait — the Python spin loop
 *    it replaces held the GIL between checks, actively starving the
 *    peer thread/process it was waiting on (measurably so on 1-core
 *    hosts). Used for the DAG shm-channel writer ack-gate and reader
 *    seq-gate.
 *  - rtpu_crc32c / rtpu_masked_crc32c: slice-by-8 software CRC32C
 *    (Castagnoli) with the TFRecord masking, ~GB/s vs ~MB/s for the
 *    pure-Python table loop.
 *
 * Built on demand by ray_tpu/native/__init__.py with the host cc; the
 * Python fallbacks remain when no compiler is available.
 */
#include <stdint.h>
#include <stddef.h>
#include <time.h>
#include <sched.h>

static inline uint64_t now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

/* Wait until words[0..count) are all >= value.
 * timeout_ns < 0 means no deadline. Returns 0 on success, 1 on
 * timeout. Words are written by other processes with aligned stores;
 * volatile reads are sufficient on x86-64/aarch64 for this
 * single-writer-per-word protocol. */
int rtpu_wait_u64s_ge(const volatile uint64_t *words, int count,
                      uint64_t value, int64_t timeout_ns) {
    uint64_t deadline = 0;
    int have_deadline = timeout_ns >= 0;
    if (have_deadline)
        deadline = now_ns() + (uint64_t)timeout_ns;
    long sleep_ns = 20000;              /* 20 us */
    int spins = 0;
    for (;;) {
        int ok = 1;
        for (int i = 0; i < count; i++) {
            if (words[i] < value) { ok = 0; break; }
        }
        if (ok)
            return 0;
        if (++spins < 2000) {
            /* hot phase: burn ~tens of µs re-checking; yield so a
             * same-core peer can make progress */
            if ((spins & 63) == 0)
                sched_yield();
            continue;
        }
        if (have_deadline && now_ns() > deadline)
            return 1;
        struct timespec ts = {0, sleep_ns};
        nanosleep(&ts, NULL);
        if (sleep_ns < 1000000)         /* cap at 1 ms */
            sleep_ns += sleep_ns / 2;
    }
}

/* ---------------- CRC32C (Castagnoli), slice-by-8 ---------------- */
static uint32_t crc_table[8][256];
static int crc_ready = 0;

/* Table init runs at library load (dlopen happens under the loader's
 * Python-side lock) — a lazy flag without barriers would race two
 * GIL-released callers on weakly-ordered CPUs. */
static void crc_init(void) __attribute__((constructor));

static void crc_init(void) {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c >> 1) ^ (0x82F63B78u & (~(c & 1) + 1));
        crc_table[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = crc_table[0][i];
        for (int t = 1; t < 8; t++) {
            c = crc_table[0][c & 0xFF] ^ (c >> 8);
            crc_table[t][i] = c;
        }
    }
    crc_ready = 1;
}

uint32_t rtpu_crc32c(const uint8_t *buf, size_t len) {
    if (!crc_ready)
        crc_init();
    uint32_t crc = 0xFFFFFFFFu;
    while (len >= 8) {
        crc ^= (uint32_t)buf[0] | ((uint32_t)buf[1] << 8)
             | ((uint32_t)buf[2] << 16) | ((uint32_t)buf[3] << 24);
        uint32_t hi = (uint32_t)buf[4] | ((uint32_t)buf[5] << 8)
                    | ((uint32_t)buf[6] << 16) | ((uint32_t)buf[7] << 24);
        crc = crc_table[7][crc & 0xFF]
            ^ crc_table[6][(crc >> 8) & 0xFF]
            ^ crc_table[5][(crc >> 16) & 0xFF]
            ^ crc_table[4][crc >> 24]
            ^ crc_table[3][hi & 0xFF]
            ^ crc_table[2][(hi >> 8) & 0xFF]
            ^ crc_table[1][(hi >> 16) & 0xFF]
            ^ crc_table[0][hi >> 24];
        buf += 8;
        len -= 8;
    }
    while (len--)
        crc = crc_table[0][(crc ^ *buf++) & 0xFF] ^ (crc >> 8);
    return crc ^ 0xFFFFFFFFu;
}

/* TFRecord framing mask. */
uint32_t rtpu_masked_crc32c(const uint8_t *buf, size_t len) {
    uint32_t crc = rtpu_crc32c(buf, len);
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}
