"""Backend plugins: per-framework worker-group wiring.

Parity: reference train/backend.py:32-56 (Backend ABC with
on_start/on_training_start/on_shutdown) and the torch-XLA backend's
master-address broadcast + env fanout (train/torch/xla/config.py:120-169),
re-done for JAX: worker 0 donates a coordinator address and every worker
joins via jax.distributed.initialize — after which each worker's
jax.devices() is the global pod view and pjit/shard_map span all hosts.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from ray_tpu.train.worker_group import WorkerGroup


@dataclasses.dataclass
class BackendConfig:
    def backend_cls(self):
        return Backend


class Backend:
    """No-op base backend."""

    def on_start(self, worker_group: WorkerGroup,
                 backend_config: "BackendConfig") -> None:
        pass

    def on_training_start(self, worker_group: WorkerGroup,
                          backend_config: "BackendConfig") -> None:
        pass

    def on_shutdown(self, worker_group: WorkerGroup) -> None:
        pass


@dataclasses.dataclass
class JaxConfig(BackendConfig):
    """distributed=True joins all workers into one jax.distributed
    runtime (required for multi-host SPMD; off for independent workers
    and single-worker groups). `env` is fanned out to every worker
    BEFORE its first jax import — the only reliable point to pin
    JAX_PLATFORMS / XLA_FLAGS (set platform='cpu' for CPU worker groups;
    on TPU pods leave unset so each worker claims its host's chips)."""
    distributed: Optional[bool] = None  # None = auto (W > 1)
    coordinator_port: Optional[int] = None
    env: Optional[dict] = None
    platform: Optional[str] = None      # convenience: "cpu" | "tpu"

    def backend_cls(self):
        return JaxBackend


def _pin_platform(platform: str):
    """Pin JAX to `platform` WITHOUT initializing the XLA backend.

    This must stay side-effect-free with respect to backend state:
    `jax.distributed.initialize` (run later for distributed groups)
    requires that no prior JAX call initialized a backend, so nothing
    here may touch `jax.default_backend()` / `jax.devices()`.
    """
    import os
    os.environ["JAX_PLATFORMS"] = platform
    import jax
    jax.config.update("jax_platforms", platform)


def _join_distributed(coordinator: str, num_processes: int, rank: int,
                      platform: Optional[str]):
    if platform:
        _pin_platform(platform)
    import jax
    from ray_tpu.parallel.dist import initialize_distributed
    initialize_distributed(coordinator, num_processes, rank)
    return jax.process_index()


class JaxBackend(Backend):
    def on_start(self, worker_group: WorkerGroup,
                 backend_config: JaxConfig) -> None:
        import cloudpickle

        import ray_tpu
        w = worker_group.num_workers
        distributed = backend_config.distributed
        if distributed is None:
            distributed = w > 1
        if backend_config.env:
            worker_group.set_env_on_all(backend_config.env)
        if backend_config.platform:
            # pin on every worker — a site hook can rewrite
            # jax_platforms, so env alone is not enough; in distributed
            # mode the pin instead happens inside _join_distributed,
            # immediately before jax.distributed.initialize, so no
            # worker touches JAX state before joining.
            platform = backend_config.platform
            worker_group.set_env_on_all({"JAX_PLATFORMS": platform})
            if not distributed:
                worker_group.run_on_all(_pin_platform, platform)
        if not distributed:
            return
        addr = ray_tpu.get(worker_group.workers[0].get_address.remote())
        port = (backend_config.coordinator_port
                or ray_tpu.get(
                    worker_group.workers[0].find_free_port.remote()))
        coordinator = f"{addr}:{port}"
        # every worker joins; worker 0 hosts the coordinator service
        join = cloudpickle.dumps(_join_distributed)
        refs = [worker_group.workers[rank].run.remote(
            join, (coordinator, w, rank, backend_config.platform), {})
            for rank in range(w)]
        ray_tpu.get(refs, timeout=120)
