"""Collective helpers for shard_map bodies — ICI-native ray.util.collective.

The reference exposes allreduce/broadcast/allgather/reducescatter/send/recv
over NCCL actor groups (reference python/ray/util/collective/collective.py:
258,373,423,472,531,594). On TPU the same verbs are XLA collectives emitted
inside `shard_map`; these wrappers exist so library code and user code share
one vocabulary, and so the host-side (CPU, cross-process) backend in
ray_tpu.util.collective can mirror the same API.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def allreduce(x, axis: str, op: str = "sum"):
    if op == "sum":
        return lax.psum(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"unsupported reduce op {op!r}")


def allgather(x, axis: str, *, tiled: bool = True, gather_dim: int = 0):
    return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reducescatter(x, axis: str, *, scatter_dim: int = 0, tiled: bool = True):
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim,
                            tiled=tiled)


def broadcast(x, axis: str, root: int = 0):
    """Everyone receives root's shard. Non-root shards are never read
    (NCCL broadcast tolerates garbage/NaN in non-root buffers), so the
    non-root contribution is a hard zero via `where`, not a mask multiply."""
    idx = lax.axis_index(axis)
    contrib = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(contrib, axis)


def all_to_all(x, axis: str, split_dim: int, concat_dim: int, *,
               tiled: bool = True):
    """Ulysses-style head/sequence re-sharding primitive."""
    return lax.all_to_all(x, axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=tiled)


def ppermute_ring(x, axis: str, *, shift: int = 1):
    """Rotate shards around the ring (K/V rotation for ring attention)."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def send_recv(x, axis: str, pairs):
    """Explicit point-to-point: `pairs` is a list of (src, dst) device
    indices along `axis`. Only named destinations receive; every other
    device keeps its own buffer — matching NCCL send/recv semantics
    (reference nccl_collective_group.py send/recv)."""
    shifted = lax.ppermute(x, axis, pairs)
    idx = lax.axis_index(axis)
    is_dst = jnp.zeros((), bool)
    for _, dst in pairs:
        is_dst = is_dst | (idx == dst)
    return jnp.where(is_dst, shifted, x)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str):
    return lax.axis_size(axis)


def barrier(axis: str, x=None):
    """Collective fence. With `x`, threads the fence through the value's
    data dependency (a zero-valued psum token is added to every leaf) so
    the collective cannot be dead-code-eliminated; a bare `barrier(axis)`
    returns the token, which MUST be consumed to have any effect."""
    token = lax.psum(jnp.zeros((), jnp.int32), axis)
    if x is None:
        return token
    return jax.tree.map(lambda v: v + token.astype(v.dtype), x)
