"""Multi-host JAX bootstrap — the torch `init_process_group` replacement.

The reference's torch-XLA backend broadcasts a master address and calls
`dist.init_process_group("xla")` on every worker (reference
python/ray/train/torch/xla/config.py:67-75,120-169). The JAX analogue is
`jax.distributed.initialize(coordinator, num_processes, process_id)`: all
hosts join one multi-controller SPMD program and `jax.devices()` becomes
the global pod view. ray_tpu.train's JaxBackend calls this on every
worker actor with a rendezvous address fanned out from worker 0.
"""
from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)

_initialized = False


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None,
                           local_device_ids=None) -> None:
    """Join the global JAX distributed runtime (idempotent).

    With no args, relies on TPU metadata / env autodetection (GKE, GCE),
    mirroring the reference's TPU pod probing
    (reference python/ray/_private/accelerators/tpu.py:48-68,198-228).
    """
    global _initialized
    if _initialized:
        return
    import jax

    if num_processes is not None and num_processes <= 1 and (
            coordinator_address is None):
        # Single-process: nothing to rendezvous.
        _initialized = True
        return
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    logger.info("jax.distributed.initialize(%s)", kwargs)
    jax.distributed.initialize(**kwargs)
    _initialized = True


def is_distributed_initialized() -> bool:
    return _initialized


def process_index() -> int:
    import jax
    return jax.process_index()


def process_count() -> int:
    import jax
    return jax.process_count()


def coordinator_env() -> dict:
    """Env vars a worker-group launcher should fan out (parity with the
    reference's MASTER_ADDR/MASTER_PORT fanout,
    reference python/ray/train/torch/config.py:156-200)."""
    return {
        k: v for k, v in os.environ.items()
        if k.startswith(("JAX_", "TPU_", "MEGASCALE_"))
    }
