"""TPU-first parallelism layer.

This is the ray_tpu replacement for the reference's process-group wiring
(reference python/ray/train/torch/config.py:66-121, NCCL DDP) and the
externally-delegated TP/PP/SP strategies catalogued in SURVEY.md §2.4.
Instead of NCCL process groups we expose:

- :class:`MeshSpec` / :func:`prepare_mesh` — named `jax.sharding.Mesh`
  construction over (dp, fsdp, tp, sp, ep, pp) axes, single- or multi-slice.
- logical-axis sharding rules (:mod:`ray_tpu.parallel.sharding`) that map
  model-logical axes ("batch", "embed", "mlp", "heads", ...) onto mesh axes,
  GSPMD-style, replacing DDP/FSDP/ZeRO wrappers
  (reference python/ray/train/torch/train_loop_utils.py:162-202).
- collective helpers (:mod:`ray_tpu.parallel.collectives`) for use inside
  ``shard_map`` — the ICI-native analogue of ray.util.collective
  (reference python/ray/util/collective/collective.py).
- multi-host bootstrap (:mod:`ray_tpu.parallel.dist`) replacing
  ``dist.init_process_group`` (reference python/ray/train/torch/xla/config.py:67-75).
"""
from ray_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    prepare_mesh,
    local_mesh,
    mesh_shape_for,
)
from ray_tpu.parallel.sharding import (  # noqa: F401
    LOGICAL_AXIS_RULES,
    logical_sharding,
    shard_pytree,
    with_logical_constraint,
    param_shardings,
)
from ray_tpu.parallel import collectives  # noqa: F401
from ray_tpu.parallel.dist import initialize_distributed  # noqa: F401
