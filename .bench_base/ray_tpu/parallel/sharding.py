"""Logical-axis sharding rules: the GSPMD replacement for DDP/FSDP wrappers.

Where the reference wraps modules (`prepare_model` →
DistributedDataParallel/FSDP, reference
python/ray/train/torch/train_loop_utils.py:162-202), ray_tpu annotates
arrays with *logical* axis names and maps them to mesh axes via a rule
table. XLA then inserts all-gathers/reduce-scatters/psums over ICI —
there is no wrapper object and no NCCL.

Logical axes used by the model zoo:
  batch, seq, embed, mlp, heads, kv_heads, head_dim, vocab, experts, layers
"""
from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Axes = Union[str, Tuple[str, ...], None]

# rule: logical axis -> mesh axis (or tuple of mesh axes, or None=replicate).
# fsdp shards along embed (ZeRO-3 analogue: params gathered per-layer on use);
# tp shards mlp/heads/vocab (megatron); sp shards seq; ep shards experts;
# batch shards over (dp, fsdp) — fsdp contributes to the data axis for
# activations, matching the "fsdp is dp for activations" recipe.
LOGICAL_AXIS_RULES: dict[str, Axes] = {
    "batch": ("dp", "fsdp"),
    "seq": "sp",
    "embed": "fsdp",
    "mlp": "tp",
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "vocab": "tp",
    "experts": "ep",
    "layers": None,
    "stages": "pp",
}


def _mesh_axes_for(logical: Axes, rules: dict[str, Axes],
                   mesh: Optional[Mesh]) -> Axes:
    if logical is None:
        return None
    if isinstance(logical, str):
        if logical not in rules:
            raise ValueError(
                f"unknown logical axis {logical!r}; known: {sorted(rules)}. "
                "Pass an extended rules dict to add custom axes.")
        mapped = rules[logical]
    else:
        mapped = logical
    if mapped is None:
        return None
    if mesh is not None:
        # Drop trivial mesh axes so specs stay minimal (pure cosmetics: a
        # size-1 axis means replicated anyway).
        axes = mapped if isinstance(mapped, tuple) else (mapped,)
        axes = tuple(a for a in axes if mesh.shape.get(a, 1) > 1)
        if not axes:
            return None
        return axes if len(axes) > 1 else axes[0]
    return mapped


def logical_spec(logical_axes: Sequence[Axes],
                 rules: Optional[dict[str, Axes]] = None,
                 mesh: Optional[Mesh] = None) -> P:
    """Map a tuple of logical axis names to a PartitionSpec."""
    rules = rules if rules is not None else LOGICAL_AXIS_RULES
    return P(*(_mesh_axes_for(ax, rules, mesh) for ax in logical_axes))


def logical_sharding(mesh: Mesh, logical_axes: Sequence[Axes],
                     rules: Optional[dict[str, Axes]] = None) -> NamedSharding:
    return NamedSharding(mesh, logical_spec(logical_axes, rules, mesh))


def with_logical_constraint(x: Any, logical_axes: Sequence[Axes],
                            mesh: Optional[Mesh] = None,
                            rules: Optional[dict[str, Axes]] = None) -> Any:
    """`lax.with_sharding_constraint` in logical-axis vocabulary.

    Inside jit, mesh may be omitted if running under `jax.set_mesh` /
    mesh context; we fall back to the ambient abstract mesh.
    """
    spec = logical_spec(logical_axes, rules, mesh)
    if mesh is not None:
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    return jax.lax.with_sharding_constraint(x, spec)


def param_shardings(mesh: Mesh, logical_tree: Any,
                    rules: Optional[dict[str, Axes]] = None) -> Any:
    """Pytree of logical-axis tuples -> pytree of NamedShardings.

    `logical_tree` mirrors the param pytree, with each leaf a tuple of
    logical axis names (e.g. ("embed", "mlp")). Models in
    ray_tpu.models expose this via `Model.param_logical_axes()`.
    """
    return jax.tree.map(
        lambda axes: logical_sharding(mesh, axes, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, (str, tuple)) for a in x),
    )


def shard_pytree(tree: Any, shardings: Any) -> Any:
    """Place a host pytree onto devices per a matching sharding pytree."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, s), tree, shardings)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for host-fed data batches: leading axis over (dp, fsdp)."""
    return logical_sharding(mesh, ("batch",))
