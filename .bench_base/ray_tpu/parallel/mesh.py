"""Named device-mesh construction for TPU pods and slices.

The reference wires data-parallel groups with NCCL ranks
(reference python/ray/train/torch/config.py:153-213); on TPU the analogue
is a `jax.sharding.Mesh` whose axes name the parallelism strategies.
Collectives ride ICI within a slice and DCN across slices — we use
`mesh_utils.create_hybrid_device_mesh` when >1 slice is present so the
outermost (data/pipeline) axes land on DCN and inner (model) axes on ICI.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

# Canonical axis order: outermost (slowest-varying, DCN-friendly) first.
# pp/dp/fsdp cross slices fine; tp/sp/ep want ICI locality so they're innermost.
AXIS_ORDER = ("pp", "dp", "fsdp", "sp", "ep", "tp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape over the canonical parallelism axes.

    Any axis set to -1 absorbs the remaining device count (at most one).
    Axes of size 1 are still materialised in the mesh so sharding rules can
    reference them unconditionally (a size-1 axis shards to replication).
    """

    dp: int = -1     # data parallel (gradient psum)
    fsdp: int = 1    # fully-sharded params/optimizer (ZeRO-3 analogue)
    tp: int = 1      # tensor parallel (megatron-style matmul sharding)
    sp: int = 1      # sequence/context parallel (ring attention axis)
    ep: int = 1      # expert parallel (MoE all_to_all axis)
    pp: int = 1      # pipeline parallel (inter-slice / DCN axis)

    def resolve(self, n_devices: int) -> Tuple[int, ...]:
        """Concrete per-axis sizes in AXIS_ORDER, -1 axis inferred."""
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one axis may be -1, got {wild}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {sizes}")
            sizes[wild[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {math.prod(sizes.values())} devices, "
                f"have {n_devices}")
        return tuple(sizes[a] for a in AXIS_ORDER)

    def build(self, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
        return prepare_mesh(self, devices)


def _num_slices(devices: Sequence[jax.Device]) -> int:
    """Count distinct TPU slices (DCN-connected groups) among devices."""
    ids = {getattr(d, "slice_index", 0) or 0 for d in devices}
    return max(len(ids), 1)


def prepare_mesh(spec: MeshSpec | None = None,
                 devices: Optional[Sequence[jax.Device]] = None,
                 **axes: int) -> Mesh:
    """Build a named Mesh from a MeshSpec (or axis kwargs).

    ``prepare_mesh(dp=4, tp=2)`` is the TPU-era `prepare_model` entry point:
    the returned mesh is what all sharding rules and pjit'ed steps close over.
    """
    if spec is None:
        spec = MeshSpec(**axes) if axes else MeshSpec()
    elif axes:
        raise ValueError("pass either a MeshSpec or axis kwargs, not both")
    devices = list(devices if devices is not None else jax.devices())
    shape = spec.resolve(len(devices))
    n_slices = _num_slices(devices)
    if n_slices > 1 and len(devices) % n_slices == 0:
        # Hybrid mesh: outer axes over DCN (slices), inner over ICI.
        per_slice = len(devices) // n_slices
        dcn_shape, ici_shape = _split_hybrid(shape, n_slices, per_slice)
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=devices)
    else:
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    return Mesh(dev_array, AXIS_ORDER)


# Axes allowed to span DCN (slice boundaries). tp/sp/ep are ICI-only:
# their collectives are latency/bandwidth-critical per-layer, and landing
# them on DCN silently would be a performance cliff, so we refuse.
_DCN_AXES = frozenset({"pp", "dp", "fsdp"})


def _split_hybrid(shape: Tuple[int, ...], n_slices: int,
                  per_slice: int) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """Factor each axis into (dcn, ici) parts, consuming slices outermost-first.

    Only pp/dp/fsdp may absorb the slice factor — the inner model axes
    (sp/ep/tp) always stay within a slice (ICI)."""
    dcn, ici = [], []
    remaining = n_slices
    for axis, size in zip(AXIS_ORDER, shape):
        allowed = axis in _DCN_AXES
        if allowed and remaining > 1 and size % remaining == 0:
            dcn.append(remaining)
            ici.append(size // remaining)
            remaining = 1
        elif allowed and remaining > 1 and remaining % size == 0 and size > 1:
            dcn.append(size)
            ici.append(1)
            remaining //= size
        else:
            dcn.append(1)
            ici.append(size)
    if remaining != 1:
        raise ValueError(
            f"cannot place {n_slices} slices onto mesh shape {shape}; "
            "make an outer axis (pp/dp/fsdp) a multiple of the slice count")
    if math.prod(ici) != per_slice:
        raise ValueError(
            f"inner (ICI) mesh {ici} needs {math.prod(ici)} devices per "
            f"slice but each slice has {per_slice}")
    return tuple(dcn), tuple(ici)


def local_mesh(**axes: int) -> Mesh:
    """Mesh over this process's addressable devices only (single-host debug)."""
    return prepare_mesh(MeshSpec(**axes) if axes else None,
                        devices=jax.local_devices())


def mesh_shape_for(n_devices: int, model_axes: int = 1) -> MeshSpec:
    """Heuristic default: put `model_axes` devices on tp, rest on dp."""
    if n_devices % model_axes:
        raise ValueError(f"{n_devices} % {model_axes} != 0")
    return MeshSpec(dp=n_devices // model_axes, tp=model_axes)


def device_count(mesh: Mesh) -> int:
    return int(np.prod(list(mesh.shape.values())))
