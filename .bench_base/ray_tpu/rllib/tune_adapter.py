"""Algorithms as Tune trainables.

Parity: reference rllib/algorithms/algorithm.py:227 — Algorithm IS a
tune.Trainable (setup builds from the config, step = train(), save/
load_checkpoint = get/set_state) — re-shaped to this stack's function-
trainable contract: ``tune_trainable(ConfigCls)`` returns a function
the Tuner runs in a trial actor, with hyperparameters arriving through
the trial config dict, metrics flowing through ``train.report``, and
fault tolerance via checkpointed algorithm state.

Usage::

    from ray_tpu import tune
    from ray_tpu.rllib import PPOConfig
    from ray_tpu.rllib.tune_adapter import tune_trainable

    tuner = tune.Tuner(
        tune_trainable(PPOConfig),
        param_space={"lr": tune.grid_search([1e-4, 3e-4]),
                     "env": "CartPole-v1",
                     "_num_iterations": 10},
        tune_config=tune.TuneConfig(metric="episode_return_mean",
                                    mode="max"))
    results = tuner.fit()
"""
from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Dict, Type

# trial-control keys the adapter consumes (not algorithm hyperparams)
_ITER_KEY = "_num_iterations"
_CKPT_EVERY_KEY = "_checkpoint_every"


def tune_trainable(config_cls: Type) -> Callable[[Dict[str, Any]], None]:
    """Wrap an algorithm config class (PPOConfig, DQNConfig, ...) as a
    Tune function trainable. Every trial-config key that names a field
    of the config dataclass is applied to it; ``_num_iterations``
    bounds the training loop (default 10) and ``_checkpoint_every``
    controls state checkpoints (default every 5 iterations, enabling
    trial resume and PBT exploitation)."""

    def trainable(config: Dict[str, Any]) -> None:
        from ray_tpu import train
        from ray_tpu.train import Checkpoint

        cfg = config_cls()
        for k, v in config.items():
            if k.startswith("_"):
                continue
            if not hasattr(cfg, k):
                raise ValueError(
                    f"{config_cls.__name__} has no field {k!r}")
            setattr(cfg, k, v)
        algo = cfg.build()
        try:
            start = 0
            ckpt = train.get_checkpoint()
            if ckpt is not None:
                with open(os.path.join(ckpt.as_directory(),
                                       "algo_state.pkl"), "rb") as f:
                    algo.set_state(pickle.load(f))
                start = algo.iteration
            iters = int(config.get(_ITER_KEY, 10))
            every = int(config.get(_CKPT_EVERY_KEY, 5))
            import numpy as np
            for i in range(start, iters):
                metrics = {
                    k: (v.item() if isinstance(
                        v, (np.floating, np.integer, np.bool_)) else v)
                    for k, v in algo.train().items()}
                out_ckpt = None
                if (i + 1) % every == 0 or i + 1 == iters:
                    import tempfile
                    # the rtpu_ckpt_ prefix opts into the train worker's
                    # post-report temp-dir reclamation
                    d = tempfile.mkdtemp(prefix="rtpu_ckpt_")
                    with open(os.path.join(d, "algo_state.pkl"),
                              "wb") as f:
                        pickle.dump(algo.get_state(), f)
                    out_ckpt = Checkpoint.from_directory(d)
                train.report(dict(metrics), checkpoint=out_ckpt)
        finally:
            algo.stop()

    trainable.__name__ = f"{config_cls.__name__}_trainable"
    return trainable
