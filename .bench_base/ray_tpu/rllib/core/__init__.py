from ray_tpu.rllib.core.rl_module import ActorCriticModule, Categorical
from ray_tpu.rllib.core.learner import PPOLearner, LearnerGroup

__all__ = ["ActorCriticModule", "Categorical", "PPOLearner", "LearnerGroup"]
