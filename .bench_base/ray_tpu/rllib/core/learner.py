"""JAX PPO Learner + LearnerGroup.

Parity: reference rllib/core/learner/learner.py (update loop),
rllib/core/learner/learner_group.py:55,152-167 (group of learner actors
driven through FaultTolerantActorManager), and the PPO loss of
rllib/algorithms/ppo/ppo_torch_learner.py — re-designed TPU-first: the
ENTIRE update (value computation, GAE, advantage normalisation, epochs x
minibatch SGD) is ONE jitted function built from lax.scan, so on TPU it
compiles to a single XLA program with no host round-trips between
minibatches. Multi-device scaling shards the batch axis over a `dp` mesh
axis via sharding constraints (XLA inserts the gradient psum) instead of
torch-DDP allreduce wiring.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.core.rl_module import ActorCriticModule, Categorical

Params = dict


@dataclasses.dataclass
class PPOLearnerConfig:
    obs_dim: int = 0
    num_actions: int = 0
    hidden: Sequence[int] = (64, 64)
    lr: float = 3e-4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    vf_coef: float = 0.5
    vf_clip: float = 10.0
    ent_coef: float = 0.01
    max_grad_norm: float = 0.5
    num_epochs: int = 4
    num_minibatches: int = 4
    target_kl: float = 0.03   # stop epoch/minibatch SGD when exceeded
    continuous: bool = False  # Box action space (diag-gaussian head)
    seed: int = 0
    # Data-parallel width INSIDE the learner: the batch's env axis is
    # sharded over a `dp` mesh of this many local devices and XLA
    # inserts the gradient psum — the TPU-native form of the reference's
    # k-GPU DDP learners (torch_learner.py:566). 1 = single device.
    num_devices: int = 1
    # Learner-side connector pipeline (reference rllib/connectors/
    # learner/): LearnerConnector instances applied to the numpy batch
    # BEFORE the jitted update. A pipeline containing
    # GeneralAdvantageEstimation switches the jit to consume the
    # connector-computed `advantages`/`value_targets` (build-time
    # decision — no retracing).
    learner_connectors: Optional[Sequence] = None


class PPOLearner:
    """Holds module params + optimizer state; `update(batch)` is jitted.

    Batch layout (time-major, from SingleAgentEnvRunner.sample):
      obs         (T+1, N, obs_dim) — includes bootstrap observation
      actions     (T, N) int32
      logp        (T, N) f32        — behaviour log-probs
      rewards     (T, N) f32
      terminateds (T, N) f32        — true termination (no bootstrap)
      dones       (T, N) f32        — terminated | truncated (GAE cut;
                                      truncation still bootstraps off
                                      the final obs)
      mask        (T, N) f32        — 0 on autoreset filler transitions
    """

    def __init__(self, config: PPOLearnerConfig,
                 module: Optional[ActorCriticModule] = None,
                 mesh=None):
        from ray_tpu._private.jaxenv import pin_platform_from_env
        pin_platform_from_env()
        self.config = config
        self.module = module or ActorCriticModule(
            config.obs_dim, config.num_actions, tuple(config.hidden),
            continuous=config.continuous)
        self.mesh = mesh
        self._tx = optax.chain(
            optax.clip_by_global_norm(config.max_grad_norm),
            optax.adam(config.lr, eps=1e-5))
        key = jax.random.PRNGKey(config.seed)
        self._perm_key, init_key = jax.random.split(key)
        self.params = self.module.init(init_key)
        self.opt_state = self._tx.init(self.params)
        from ray_tpu.rllib.connectors import (GeneralAdvantageEstimation,
                                              LearnerConnectorPipeline)
        self._connectors = (
            LearnerConnectorPipeline(list(config.learner_connectors))
            if config.learner_connectors else None)
        self._precomputed_adv = bool(self._connectors and any(
            isinstance(c, GeneralAdvantageEstimation)
            for c in self._connectors.connectors))
        self._values_fn = jax.jit(
            lambda p, o: self.module.forward(p, o)[1])
        if config.num_devices > 1 and mesh is None:
            from jax.sharding import Mesh
            devs = jax.devices()
            if len(devs) < config.num_devices:
                raise ValueError(
                    f"num_devices={config.num_devices} but only "
                    f"{len(devs)} local devices visible")
            self.mesh = Mesh(
                np.array(devs[:config.num_devices]), ("dp",))
        if self.mesh is not None and "dp" in self.mesh.shape:
            from jax.sharding import NamedSharding, PartitionSpec as P
            mesh = self.mesh

            def shard_for(name):
                # time-major (T, N, ...) leaves shard the env axis
                return NamedSharding(
                    mesh, P(*((None, "dp") if name != "obs"
                              else (None, "dp", None))))
            repl = NamedSharding(mesh, P())
            batch_keys = ["obs", "actions", "logp", "rewards",
                          "terminateds", "dones", "mask"]
            if self._precomputed_adv:
                batch_keys += ["advantages", "value_targets"]
            self._update_fn = jax.jit(
                self._build_update(),
                in_shardings=(repl, repl,
                              {k: shard_for(k) for k in batch_keys},
                              repl),
                out_shardings=(repl, repl, repl))
        else:
            self._update_fn = jax.jit(self._build_update())
        self._timer = {"updates": 0, "update_time": 0.0,
                       "minibatches": 0, "transitions": 0}

    # ------------------------------------------------------------- jit
    def _build_update(self):
        c = self.config
        module = self.module

        def gae(values, rewards, terms, dones):
            # values (T+1, N); recursion runs backwards over time.
            # terminated cuts the bootstrap; done (incl. truncation)
            # cuts only the advantage chain — truncation bootstraps off
            # V(final obs), which gymnasium delivers at the done step.
            def step(carry, inp):
                v_t, v_tp1, r_t, term_t, d_t = inp
                delta = r_t + c.gamma * v_tp1 * (1 - term_t) - v_t
                adv = delta + c.gamma * c.gae_lambda * (1 - d_t) * carry
                return adv, adv
            _, advs = jax.lax.scan(
                step, jnp.zeros_like(values[0]),
                (values[:-1], values[1:], rewards, terms, dones),
                reverse=True)
            return advs

        def loss_fn(params, mb):
            logits, value = module.forward(params, mb["obs"])
            logp = module.dist_log_prob(params, logits, mb["actions"])
            ratio = jnp.exp(logp - mb["logp"])
            adv = mb["adv"]
            pg = -jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - c.clip_eps, 1 + c.clip_eps) * adv)
            v_err = jnp.square(value - mb["vtarg"])
            v_clipped = mb["vpred"] + jnp.clip(
                value - mb["vpred"], -c.vf_clip, c.vf_clip)
            v_err = jnp.maximum(v_err, jnp.square(v_clipped - mb["vtarg"]))
            ent = module.dist_entropy(params, logits)
            m = mb["mask"]
            denom = jnp.maximum(jnp.sum(m), 1.0)
            pg_loss = jnp.sum(pg * m) / denom
            v_loss = 0.5 * jnp.sum(v_err * m) / denom
            ent_loss = jnp.sum(ent * m) / denom
            total = pg_loss + c.vf_coef * v_loss - c.ent_coef * ent_loss
            kl = jnp.sum((mb["logp"] - logp) * m) / denom
            clipped = jnp.sum((jnp.abs(ratio - 1) > c.clip_eps) * m) / denom
            return total, {"policy_loss": pg_loss, "vf_loss": v_loss,
                           "entropy": ent_loss, "kl": kl,
                           "clip_frac": clipped}

        precomputed = self._precomputed_adv

        def update(params, opt_state, batch, perm_key):
            obs, rewards = batch["obs"], batch["rewards"]
            terms = batch["terminateds"]
            dones, mask = batch["dones"], batch["mask"]
            T, N = rewards.shape
            denom = jnp.maximum(jnp.sum(mask), 1.0)
            _, values = module.forward(params, obs)      # (T+1, N)
            if precomputed:
                # the learner-connector pipeline (GAE + standardize)
                # already produced these on the host
                adv = batch["advantages"]
                vtarg = batch["value_targets"]
            else:
                adv = gae(values, rewards, terms, dones)
                vtarg = adv + values[:-1]
                # Normalise advantages over valid transitions only.
                mu = jnp.sum(adv * mask) / denom
                var = jnp.sum(jnp.square(adv - mu) * mask) / denom
                adv = (adv - mu) * jax.lax.rsqrt(var + 1e-8)

            act = batch["actions"]
            flat = {
                "obs": obs[:-1].reshape(T * N, -1),
                "actions": (act.reshape(T * N, -1) if act.ndim == 3
                            else act.reshape(T * N)),
                "logp": batch["logp"].reshape(T * N),
                "adv": adv.reshape(T * N),
                "vtarg": vtarg.reshape(T * N),
                "vpred": values[:-1].reshape(T * N),
                "mask": mask.reshape(T * N),
            }
            B = T * N
            mb_size = B // c.num_minibatches

            def epoch(carry, key):
                params, opt_state, stop = carry
                perm = jax.random.permutation(key, B)

                def minibatch(carry, idx):
                    params, opt_state, stop = carry
                    mb = jax.tree_util.tree_map(lambda x: x[idx], flat)
                    (_, metrics), grads = jax.value_and_grad(
                        loss_fn, has_aux=True)(params, mb)
                    updates, new_opt = self._tx.update(
                        grads, opt_state, params)
                    new_params = optax.apply_updates(params, updates)
                    # KL early stop (the reference PPO's kl-threshold
                    # guard): once exceeded, remaining minibatches pass
                    # through unchanged — data-dependent but jit-legal
                    # via where-selects, no host round-trip.
                    keep = jnp.logical_not(stop)
                    sel = lambda new, old: jax.tree_util.tree_map(
                        lambda a, b: jnp.where(keep, a, b), new, old)
                    params = sel(new_params, params)
                    opt_state = sel(new_opt, opt_state)
                    stop = jnp.logical_or(
                        stop, jnp.abs(metrics["kl"]) > c.target_kl)
                    return (params, opt_state, stop), metrics

                idxs = perm[:mb_size * c.num_minibatches].reshape(
                    c.num_minibatches, mb_size)
                (params, opt_state, stop), metrics = jax.lax.scan(
                    minibatch, (params, opt_state, stop), idxs)
                return (params, opt_state, stop), metrics

            keys = jax.random.split(perm_key, c.num_epochs)
            (params, opt_state, _), metrics = jax.lax.scan(
                epoch, (params, opt_state, jnp.asarray(False)), keys)
            metrics = jax.tree_util.tree_map(lambda x: x[-1, -1], metrics)
            metrics["vf_explained_var"] = 1.0 - (
                jnp.sum(jnp.square(vtarg - values[:-1]) * mask)
                / jnp.maximum(jnp.sum(jnp.square(
                    vtarg - jnp.sum(vtarg * mask) / denom) * mask), 1e-8))
            return params, opt_state, metrics

        return update

    # ------------------------------------------------------------- api
    def compute_values(self, obs: np.ndarray) -> np.ndarray:
        """Value predictions for a (T+1, N, obs) stack — the module
        query learner connectors (GAE) use."""
        return np.asarray(self._values_fn(self.params, obs))

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        t0 = time.perf_counter()
        if self._connectors is not None:
            batch = self._connectors(dict(batch), self)
        self._perm_key, sub = jax.random.split(self._perm_key)
        self.params, self.opt_state, metrics = self._update_fn(
            self.params, self.opt_state, batch, sub)
        metrics = {k: float(v) for k, v in metrics.items()}
        dt = time.perf_counter() - t0
        T, N = batch["rewards"].shape
        self._timer["updates"] += 1
        self._timer["update_time"] += dt
        self._timer["minibatches"] += (self.config.num_epochs
                                       * self.config.num_minibatches)
        self._timer["transitions"] += T * N
        metrics["update_time_s"] = dt
        return metrics

    def sgd_throughput(self) -> Dict[str, float]:
        t = max(self._timer["update_time"], 1e-9)
        return {
            "minibatch_updates_per_s": self._timer["minibatches"] / t,
            "learner_transitions_per_s": (
                self._timer["transitions"] * self.config.num_epochs / t),
        }

    def get_weights(self) -> Params:
        return jax.device_get(self.params)

    def set_weights(self, weights: Params) -> None:
        self.params = jax.device_put(weights)

    def get_state(self) -> Dict[str, Any]:
        state = {"params": jax.device_get(self.params),
                 "opt_state": jax.device_get(self.opt_state)}
        if self._connectors is not None:
            state["connectors"] = self._connectors.get_state()
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = jax.device_put(state["params"])
        self.opt_state = jax.device_put(state["opt_state"])
        if self._connectors is not None and "connectors" in state:
            self._connectors.set_state(state["connectors"])

    def ping(self) -> str:
        return "pong"


class LearnerGroup:
    """The learner scaling unit.

    The reference scales learners by adding DDP-wrapped GPU processes
    (learner_group.py:152-167, torch_learner.py:566). On TPU the same
    scaling is a WIDER MESH, not more processes: `num_learners=k` runs
    ONE learner whose update shards the batch's env axis over a k-device
    `dp` mesh — XLA inserts the gradient psum exactly where DDP would
    allreduce, with bitwise-stable single-program semantics instead of
    k redundant replicas. `remote=True` hosts that learner in an actor
    (off the driver); cross-host learner scale-out rides
    jax.distributed (ray_tpu.train.JaxBackend), where the same dp mesh
    simply spans hosts.

    num_learners=0 -> local single-device learner (reference local mode).
    """

    def __init__(self, config: PPOLearnerConfig, num_learners: int = 0,
                 num_cpus_per_learner: float = 1.0,
                 remote: Optional[bool] = None):
        if num_learners > 0:
            config = dataclasses.replace(config, num_devices=num_learners)
        self.config = config
        self._remote = (remote if remote is not None else num_learners > 0)
        self._local: Optional[PPOLearner] = None
        self._manager = None
        if not self._remote:
            self._local = PPOLearner(config)
        else:
            import ray_tpu
            from ray_tpu.rllib.actor_manager import FaultTolerantActorManager

            remote_cls = ray_tpu.remote(
                num_cpus=num_cpus_per_learner)(PPOLearner)
            self._manager = FaultTolerantActorManager(
                [remote_cls.remote(config)])

    @property
    def is_local(self) -> bool:
        return self._local is not None

    def _call(self, name, *args):
        results = self._manager.foreach_actor(name, args=args)
        ok = results.values()
        if not ok:
            raise RuntimeError(f"learner call {name} failed: "
                               f"{[r.error for r in results]}")
        return ok[0]

    def update(self, batch) -> Dict[str, float]:
        if self._local is not None:
            return self._local.update(batch)
        return self._call("update", batch)

    def get_weights(self) -> Params:
        if self._local is not None:
            return self._local.get_weights()
        return self._call("get_weights")

    def set_weights(self, weights: Params) -> None:
        if self._local is not None:
            self._local.set_weights(weights)
        else:
            self._call("set_weights", weights)

    def get_state(self):
        if self._local is not None:
            return self._local.get_state()
        return self._call("get_state")

    def set_state(self, state) -> None:
        if self._local is not None:
            self._local.set_state(state)
        else:
            self._call("set_state", state)

    def sgd_throughput(self) -> Dict[str, float]:
        if self._local is not None:
            return self._local.sgd_throughput()
        return self._call("sgd_throughput")

    def shutdown(self) -> None:
        if self._manager is not None:
            self._manager.clear()
