"""RLModule-equivalent: the neural net + action-distribution bundle.

Parity: reference rllib/core/rl_module/rl_module.py (framework-agnostic
module with forward_inference/forward_train) — re-done as pure JAX
pytrees + functions (no torch Module): `init` builds the param tree,
`forward` returns (logits, value), and the distribution helpers are
static functions usable inside jit on both the learner (TPU mesh) and
the env-runner (CPU) side.
"""
from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

Params = dict


class Categorical:
    """Minimal categorical distribution over logits, jit-friendly."""

    @staticmethod
    def sample(logits: jax.Array, key: jax.Array) -> jax.Array:
        return jax.random.categorical(key, logits, axis=-1)

    @staticmethod
    def log_prob(logits: jax.Array, actions: jax.Array) -> jax.Array:
        logp = jax.nn.log_softmax(logits, axis=-1)
        return jnp.take_along_axis(
            logp, actions[..., None].astype(jnp.int32), axis=-1)[..., 0]

    @staticmethod
    def entropy(logits: jax.Array) -> jax.Array:
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


_LOG_2PI = 1.8378770664093453


class DiagGaussian:
    """Diagonal gaussian over continuous actions (state-independent
    log_std, the reference's default for Box spaces). All shapes
    (..., A); log_prob/entropy reduce over the action dim."""

    @staticmethod
    def sample(mean: jax.Array, log_std: jax.Array,
               key: jax.Array) -> jax.Array:
        return mean + jnp.exp(log_std) * jax.random.normal(
            key, mean.shape)

    @staticmethod
    def log_prob(mean: jax.Array, log_std: jax.Array,
                 actions: jax.Array) -> jax.Array:
        z = (actions - mean) * jnp.exp(-log_std)
        return jnp.sum(-0.5 * jnp.square(z) - log_std - 0.5 * _LOG_2PI,
                       axis=-1)

    @staticmethod
    def entropy(log_std: jax.Array,
                like: jax.Array) -> jax.Array:
        """Entropy broadcast to `like`'s leading shape (state-independent
        std makes it constant per state)."""
        ent = jnp.sum(log_std + 0.5 * (_LOG_2PI + 1.0), axis=-1)
        return jnp.broadcast_to(ent, like.shape[:-1])


@dataclasses.dataclass(frozen=True)
class ActorCriticModule:
    """MLP torso with separate policy/value heads.

    Mirrors the reference's default RLModule for classic-control tasks
    (rllib/core/rl_module/default_model_config.py): tanh MLP encoder,
    scalar value head, and either a categorical head (Discrete spaces;
    `num_actions` = n) or a diag-gaussian head with state-independent
    log_std (Box spaces; `continuous=True`, `num_actions` = action dim).
    """

    obs_dim: int
    num_actions: int
    hidden: Sequence[int] = (64, 64)
    continuous: bool = False

    def init(self, key: jax.Array) -> Params:
        keys = jax.random.split(key, 2 * len(self.hidden) + 2)
        ki = iter(keys)

        def dense(key, din, dout, scale):
            w = jax.random.orthogonal(key, max(din, dout))[:din, :dout]
            return {"w": (w * scale).astype(jnp.float32),
                    "b": jnp.zeros((dout,), jnp.float32)}

        params: Params = {"pi": [], "vf": []}
        for head, out_dim, out_scale in (("pi", self.num_actions, 0.01),
                                         ("vf", 1, 1.0)):
            din = self.obs_dim
            layers = []
            for h in self.hidden:
                layers.append(dense(next(ki), din, h, jnp.sqrt(2.0)))
                din = h
            layers.append(dense(next(ki), din, out_dim, out_scale))
            params[head] = layers
        if self.continuous:
            params["log_std"] = jnp.zeros((self.num_actions,),
                                          jnp.float32)
        return params

    # ------------------------------------------- distribution dispatch
    def dist_log_prob(self, params: Params, pi_out: jax.Array,
                      actions: jax.Array) -> jax.Array:
        if self.continuous:
            return DiagGaussian.log_prob(pi_out, params["log_std"],
                                         actions)
        return Categorical.log_prob(pi_out, actions)

    def dist_entropy(self, params: Params,
                     pi_out: jax.Array) -> jax.Array:
        if self.continuous:
            return DiagGaussian.entropy(params["log_std"], pi_out)
        return Categorical.entropy(pi_out)

    @staticmethod
    def _mlp(layers, x):
        for layer in layers[:-1]:
            x = jnp.tanh(x @ layer["w"] + layer["b"])
        last = layers[-1]
        return x @ last["w"] + last["b"]

    def forward(self, params: Params, obs: jax.Array
                ) -> Tuple[jax.Array, jax.Array]:
        """obs (..., obs_dim) -> (logits (..., A), value (...))."""
        logits = self._mlp(params["pi"], obs)
        value = self._mlp(params["vf"], obs)[..., 0]
        return logits, value

    def action_logp(self, params: Params, obs: jax.Array, key: jax.Array
                    ) -> Tuple[jax.Array, jax.Array]:
        logits, _ = self.forward(params, obs)
        action = Categorical.sample(logits, key)
        return action, Categorical.log_prob(logits, action)

    # ----------------------------------------------- numpy (env runner)
    @staticmethod
    def forward_policy_np(params_np: Params, obs):
        """Pure-numpy policy logits for env-runner-side inference.

        Tiny classic-control MLPs are dominated by per-call dispatch
        overhead under jit; the env runner therefore samples with plain
        numpy (mathematically identical to `forward`'s policy head) and
        keeps JAX for the learner, where the batch is big enough for XLA
        to win."""
        import numpy as np
        x = obs
        layers = params_np["pi"]
        for layer in layers[:-1]:
            x = np.tanh(x @ layer["w"] + layer["b"])
        return x @ layers[-1]["w"] + layers[-1]["b"]

    def sample_np(self, logits, rng, params_np: Params = None):
        """Numpy action sample + log-prob (env-runner side).

        Discrete: Gumbel-max categorical. Continuous (needs params_np
        for log_std): diag-gaussian around the mean head."""
        import numpy as np
        if self.continuous:
            log_std = np.asarray(params_np["log_std"])
            std = np.exp(log_std)
            action = logits + std * rng.standard_normal(logits.shape)
            z = (action - logits) / std
            logp = (-0.5 * np.square(z) - log_std
                    - 0.5 * _LOG_2PI).sum(-1)
            return action.astype(np.float32), logp.astype(np.float32)
        z = logits - logits.max(axis=-1, keepdims=True)
        logp_all = z - np.log(np.exp(z).sum(axis=-1, keepdims=True))
        g = rng.gumbel(size=logits.shape)
        action = np.argmax(logits + g, axis=-1)
        logp = np.take_along_axis(
            logp_all, action[..., None], axis=-1)[..., 0]
        return action.astype(np.int32), logp.astype(np.float32)


@dataclasses.dataclass(frozen=True)
class ConvActorCriticModule:
    """CNN torso for pixel observations (reference model catalog's
    default conv_filters for image spaces, rllib/models/catalog.py) —
    NHWC conv stack -> flatten -> dense -> policy/value heads. Integer
    (uint8) inputs are normalized to [0, 1] inside forward, keyed on
    dtype; float inputs are assumed pre-scaled (the EnvRunner scales
    integer env observations in numpy before buffering)."""

    obs_shape: Tuple[int, int, int]           # (H, W, C)
    num_actions: int
    # (out_channels, kernel, stride) per conv layer; default matches
    # the classic 84x84 Atari stack
    conv_filters: Sequence[Tuple[int, int, int]] = (
        (16, 8, 4), (32, 4, 2), (32, 3, 1))
    hidden: int = 256

    def init(self, key: jax.Array) -> Params:
        keys = jax.random.split(key, len(self.conv_filters) + 3)
        ki = iter(keys)
        params: Params = {"conv": []}
        c_in = self.obs_shape[-1]
        h, w = self.obs_shape[0], self.obs_shape[1]
        for c_out, k, s in self.conv_filters:
            fan_in = k * k * c_in
            params["conv"].append({
                "w": (jax.random.normal(next(ki), (k, k, c_in, c_out))
                      * jnp.sqrt(2.0 / fan_in)).astype(jnp.float32),
                "b": jnp.zeros((c_out,), jnp.float32)})
            h = -(-(h - k + 1) // s)         # VALID conv output size
            w = -(-(w - k + 1) // s)
            c_in = c_out
        flat = h * w * c_in
        if flat <= 0:
            raise ValueError(
                f"conv_filters collapse {self.obs_shape} to nothing")

        def dense(key, din, dout, scale):
            wshape = (din, dout)
            wkey = jax.random.normal(key, wshape) * scale / jnp.sqrt(din)
            return {"w": wkey.astype(jnp.float32),
                    "b": jnp.zeros((dout,), jnp.float32)}

        params["torso"] = dense(next(ki), flat, self.hidden, 1.0)
        params["pi"] = dense(next(ki), self.hidden, self.num_actions,
                             0.01)
        params["vf"] = dense(next(ki), self.hidden, 1, 1.0)
        return params

    def forward(self, params: Params, obs: jax.Array
                ) -> Tuple[jax.Array, jax.Array]:
        """obs (..., H, W, C) uint8/float -> (logits (..., A),
        value (...))."""
        lead = obs.shape[:-3]
        x = obs.reshape((-1,) + tuple(self.obs_shape))
        # normalization keyed on dtype, not batch content: integer
        # (pixel) inputs always get /255, floats are assumed pre-scaled
        is_int = jnp.issubdtype(obs.dtype, jnp.integer)
        x = x.astype(jnp.float32)
        if is_int:
            x = x / 255.0
        for layer, (c_out, k, s) in zip(params["conv"],
                                        self.conv_filters):
            x = jax.lax.conv_general_dilated(
                x, layer["w"], window_strides=(s, s), padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            x = jax.nn.relu(x + layer["b"])
        x = x.reshape(x.shape[0], -1)
        x = jnp.tanh(x @ params["torso"]["w"] + params["torso"]["b"])
        logits = x @ params["pi"]["w"] + params["pi"]["b"]
        value = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
        return (logits.reshape(lead + (self.num_actions,)),
                value.reshape(lead))

    def dist_log_prob(self, params, pi_out, actions):
        return Categorical.log_prob(pi_out, actions)

    def dist_entropy(self, params, pi_out):
        return Categorical.entropy(pi_out)
