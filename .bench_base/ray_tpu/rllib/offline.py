"""Offline RL: experience recording + behavior cloning on ray_tpu.data.

Parity: reference rllib/offline (offline_data.py readers/writers feeding
the learner; the BC/MARWIL family trains from recorded episodes). The
TPU-shaped version: experiences are ray_tpu.data Datasets (jsonl/parquet
— the same substrate as SFT data), and BC is a single-jit supervised
update maximizing log pi(a|s) over dataset batches.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.core.rl_module import ActorCriticModule


def record_transitions(env_name: str, policy_fn: Callable, path: str,
                       num_steps: int = 5000, num_envs: int = 8,
                       seed: int = 0) -> str:
    """Roll a policy (obs_batch -> action_batch) and write transitions
    as jsonl rows {obs, action, reward, terminated} (reference offline
    output writer shape). Returns the written path."""
    import gymnasium as gym

    from ray_tpu import data as rd
    envs = gym.make_vec(env_name, num_envs=num_envs,
                        vectorization_mode="sync")
    obs, _ = envs.reset(seed=seed)
    prev_done = np.zeros(num_envs, bool)
    eps_counter = np.arange(num_envs)        # episode ids per env lane
    next_eps = num_envs
    rows = []
    while len(rows) < num_steps:
        action = np.asarray(policy_fn(obs.astype(np.float32)))
        nobs, reward, term, trunc, _ = envs.step(action)
        done = term | trunc
        valid = ~prev_done
        for i in np.nonzero(valid)[0]:
            rows.append({"obs": obs[i].astype(np.float32),
                         "action": action[i],
                         "reward": float(reward[i]),
                         "new_obs": nobs[i].astype(np.float32),
                         "terminated": bool(term[i]),
                         "eps_id": int(eps_counter[i])})
        for i in np.nonzero(done)[0]:
            eps_counter[i] = next_eps
            next_eps += 1
        prev_done = done
        obs = nobs
    envs.close()
    ds = rd.from_items(rows, override_num_blocks=8)
    ds.write_jsonl(path)
    return path




from ray_tpu.rllib.algorithm_config import AlgorithmConfig


class _OfflineConfigMixin(AlgorithmConfig):
    """Offline configs share the unified AlgorithmConfig surface, plus
    the offline-data source group (reference config.offline_data())."""

    # legacy alias: subclasses may still set _ALGO
    _ALGO: type = None

    def offline_data(self, input_path: str):
        self.input_path = input_path
        return self

    def build(self):
        return (self.algo_class or self._ALGO)(self)

@dataclasses.dataclass
class BCConfig(_OfflineConfigMixin):
    env: str = "CartPole-v1"
    input_path: str = ""                 # jsonl dir/file of transitions
    hidden: Sequence[int] = (64, 64)
    lr: float = 1e-3
    train_batch_size: int = 256
    num_batches_per_iteration: int = 50
    seed: int = 0


class BC:
    """Behavior cloning: maximize log pi(a|s) over the offline dataset."""

    def __init__(self, config: BCConfig):
        if not config.input_path:
            raise ValueError("BC needs offline_data(input_path=...)")
        import gymnasium as gym

        from ray_tpu import data as rd
        self.config = config
        env = gym.make(config.env)
        obs_dim = int(np.prod(env.observation_space.shape))
        space = env.action_space
        self._continuous = not hasattr(space, "n")
        num_actions = (int(np.prod(space.shape)) if self._continuous
                       else int(space.n))
        env.close()
        self.module = ActorCriticModule(obs_dim, num_actions,
                                        tuple(config.hidden),
                                        continuous=self._continuous)
        self.params = self.module.init(jax.random.PRNGKey(config.seed))
        self._tx = optax.adam(config.lr)
        self.opt_state = self._tx.init(self.params)
        self._dataset = rd.read_json(config.input_path)
        self._update_fn = jax.jit(self._build_update())
        self.iteration = 0

    def _build_update(self):
        module = self.module

        def loss_fn(params, obs, actions):
            logits, _ = module.forward(params, obs)
            logp = module.dist_log_prob(params, logits, actions)
            return -jnp.mean(logp)

        def update(params, opt_state, obs, actions):
            loss, grads = jax.value_and_grad(loss_fn)(params, obs,
                                                      actions)
            updates, opt_state = self._tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        return update

    def train(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.perf_counter()
        losses = []
        batches = self._dataset.iter_batches(
            batch_size=c.train_batch_size, drop_last=True,
            local_shuffle_buffer_size=4 * c.train_batch_size,
            seed=c.seed + self.iteration)
        for _, batch in zip(range(c.num_batches_per_iteration), batches):
            obs = np.stack([np.asarray(o, np.float32)
                            for o in batch["obs"]])
            if self._continuous:
                actions = np.stack([np.asarray(a, np.float32)
                                    for a in batch["action"]])
            else:
                actions = np.asarray(batch["action"], np.int64)
            self.params, self.opt_state, loss = self._update_fn(
                self.params, self.opt_state, jnp.asarray(obs),
                jnp.asarray(actions))
            losses.append(float(loss))
        self.iteration += 1
        return {"training_iteration": self.iteration,
                "bc_loss": float(np.mean(losses)) if losses else
                float("nan"),
                "num_batches": len(losses),
                "time_iteration_s": time.perf_counter() - t0}

    def evaluate(self, num_episodes: int = 10,
                 seed: int = 123) -> Dict[str, float]:
        """Greedy rollout return of the cloned policy."""
        import gymnasium as gym
        env = gym.make(self.config.env)
        params_np = jax.tree_util.tree_map(np.asarray, self.params)
        returns = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=seed + ep)
            total, done = 0.0, False
            while not done:
                pi_out = self.module.forward_policy_np(
                    params_np, obs.astype(np.float32)[None])
                action = (pi_out[0] if self._continuous
                          else int(np.argmax(pi_out[0])))
                obs, r, term, trunc, _ = env.step(action)
                total += float(r)
                done = term or trunc
            returns.append(total)
        env.close()
        return {"episode_return_mean": float(np.mean(returns)),
                "num_episodes": num_episodes}


BCConfig._ALGO = BC


def _load_transitions(input_path: str):
    """Load an offline jsonl dataset into flat arrays (rows keep
    insertion order, so per-eps_id sequences are time-ordered)."""
    from ray_tpu import data as rd
    rows = rd.read_json(input_path).take_all()
    obs = np.stack([np.asarray(r["obs"], np.float32) for r in rows])
    actions = np.asarray([r["action"] for r in rows])
    rewards = np.asarray([r["reward"] for r in rows], np.float32)
    terms = np.asarray([r["terminated"] for r in rows], np.float32)
    new_obs = (np.stack([np.asarray(r["new_obs"], np.float32)
                         for r in rows])
               if "new_obs" in rows[0] else None)
    eps_ids = (np.asarray([r["eps_id"] for r in rows])
               if "eps_id" in rows[0] else None)
    return obs, actions, rewards, new_obs, terms, eps_ids


def _returns_to_go(rewards, eps_ids, gamma: float) -> np.ndarray:
    """Discounted return-to-go per episode (reference
    postprocessing compute advantages for MARWIL)."""
    if eps_ids is None:
        raise ValueError(
            "dataset lacks eps_id column (re-record with this version's "
            "record_transitions) — MARWIL needs episode boundaries")
    rtg = np.zeros_like(rewards)
    for eid in np.unique(eps_ids):
        idx = np.nonzero(eps_ids == eid)[0]       # time-ordered
        acc = 0.0
        for j in idx[::-1]:
            acc = rewards[j] + gamma * acc
            rtg[j] = acc
    return rtg


@dataclasses.dataclass
class MARWILConfig(_OfflineConfigMixin):
    """Reference rllib/algorithms/marwil/marwil.py: exponentially
    advantage-weighted imitation (beta=0 reduces to BC)."""
    env: str = "CartPole-v1"
    input_path: str = ""
    hidden: Sequence[int] = (64, 64)
    lr: float = 1e-3
    beta: float = 1.0
    vf_coef: float = 1.0
    gamma: float = 0.99
    train_batch_size: int = 256
    num_batches_per_iteration: int = 50
    seed: int = 0


class MARWIL:
    """Advantage-weighted behavior cloning: maximize
    exp(beta * Â(s, a)) * log pi(a|s) with a monte-carlo value baseline
    (reference marwil_torch_learner loss)."""

    def __init__(self, config: MARWILConfig):
        if not config.input_path:
            raise ValueError("MARWIL needs offline_data(input_path=...)")
        import gymnasium as gym
        self.config = config
        env = gym.make(config.env)
        obs_dim = int(np.prod(env.observation_space.shape))
        space = env.action_space
        self._continuous = not hasattr(space, "n")
        num_actions = (int(np.prod(space.shape)) if self._continuous
                       else int(space.n))
        env.close()
        self.module = ActorCriticModule(obs_dim, num_actions,
                                        tuple(config.hidden),
                                        continuous=self._continuous)
        self.params = self.module.init(jax.random.PRNGKey(config.seed))
        self._tx = optax.adam(config.lr)
        self.opt_state = self._tx.init(self.params)
        obs, actions, rewards, _nobs, _terms, eps_ids = \
            _load_transitions(config.input_path)
        self._obs = obs
        self._actions = (actions.astype(np.float32) if self._continuous
                         else actions.astype(np.int32))
        self._rtg = _returns_to_go(rewards, eps_ids, config.gamma)
        self._rng = np.random.default_rng(config.seed)
        self._update_fn = jax.jit(self._build_update())
        self.iteration = 0

    def _build_update(self):
        c = self.config
        module = self.module

        def loss_fn(params, obs, actions, rtg):
            logits, value = module.forward(params, obs)
            logp = module.dist_log_prob(params, logits, actions)
            adv = rtg - value
            # batch-normalized advantage inside the exp weight
            adv_n = adv / (jnp.std(jax.lax.stop_gradient(adv)) + 1e-6)
            w = jnp.minimum(
                jnp.exp(c.beta * jax.lax.stop_gradient(adv_n)), 20.0)
            pi_loss = -jnp.mean(w * logp)
            vf_loss = jnp.mean(jnp.square(adv))
            return pi_loss + c.vf_coef * vf_loss, (pi_loss, vf_loss)

        def update(params, opt_state, obs, actions, rtg):
            (loss, (pi_l, vf_l)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, obs, actions, rtg)
            updates, opt_state = self._tx.update(grads, opt_state)
            return (optax.apply_updates(params, updates), opt_state,
                    loss, pi_l, vf_l)

        return update

    def train(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.perf_counter()
        n = len(self._obs)
        losses, pi_ls, vf_ls = [], [], []
        for _ in range(c.num_batches_per_iteration):
            idx = self._rng.integers(0, n, c.train_batch_size)
            self.params, self.opt_state, loss, pi_l, vf_l = \
                self._update_fn(self.params, self.opt_state,
                                jnp.asarray(self._obs[idx]),
                                jnp.asarray(self._actions[idx]),
                                jnp.asarray(self._rtg[idx]))
            losses.append(float(loss))
            pi_ls.append(float(pi_l))
            vf_ls.append(float(vf_l))
        self.iteration += 1
        return {"training_iteration": self.iteration,
                "marwil_loss": float(np.mean(losses)),
                "policy_loss": float(np.mean(pi_ls)),
                "vf_loss": float(np.mean(vf_ls)),
                "time_iteration_s": time.perf_counter() - t0}

    evaluate = BC.evaluate


@dataclasses.dataclass
class CQLConfig(_OfflineConfigMixin):
    """Discrete conservative Q-learning (reference
    rllib/algorithms/cql: CQL(H) regularizer over a Q-learning core)."""
    env: str = "CartPole-v1"
    input_path: str = ""
    hidden: Sequence[int] = (64, 64)
    lr: float = 5e-4
    gamma: float = 0.99
    cql_alpha: float = 1.0
    target_network_update_freq: int = 100
    train_batch_size: int = 256
    num_batches_per_iteration: int = 50
    seed: int = 0


class CQL:
    """Offline Q-learning with the conservative penalty
    E[logsumexp Q(s,·) - Q(s, a_data)] that pushes down out-of-
    distribution action values (CQL(H), Kumar et al. 2020)."""

    def __init__(self, config: CQLConfig):
        if not config.input_path:
            raise ValueError("CQL needs offline_data(input_path=...)")
        import gymnasium as gym

        from ray_tpu.rllib.algorithms.dqn import QModule
        self.config = config
        env = gym.make(config.env)
        if not hasattr(env.action_space, "n"):
            raise ValueError("discrete CQL needs a Discrete action "
                             "space (continuous CQL rides SAC)")
        obs_dim = int(np.prod(env.observation_space.shape))
        num_actions = int(env.action_space.n)
        env.close()
        self.module = QModule(obs_dim, num_actions,
                              tuple(config.hidden))
        self.params = self.module.init(jax.random.PRNGKey(config.seed))
        self.target_params = jax.tree_util.tree_map(jnp.copy, self.params)
        self._tx = optax.adam(config.lr)
        self.opt_state = self._tx.init(self.params)
        obs, actions, rewards, new_obs, terms, _eps = \
            _load_transitions(config.input_path)
        if new_obs is None:
            raise ValueError(
                "dataset lacks new_obs (re-record with this version's "
                "record_transitions) — CQL needs next observations")
        self._data = (obs, actions.astype(np.int32), rewards, new_obs,
                      terms)
        self._rng = np.random.default_rng(config.seed)
        self._update_fn = jax.jit(self._build_update())
        self._num_updates = 0
        self.iteration = 0

    def _build_update(self):
        c = self.config
        module = self.module

        def loss_fn(params, target_params, obs, actions, rewards,
                    new_obs, terms):
            q = module.forward(params, obs)
            q_sa = jnp.take_along_axis(q, actions[:, None],
                                       axis=-1)[:, 0]
            q_next_t = module.forward(target_params, new_obs)
            a_star = jnp.argmax(module.forward(params, new_obs), -1)
            q_next = jnp.take_along_axis(q_next_t, a_star[:, None],
                                         axis=-1)[:, 0]
            target = rewards + c.gamma * (1 - terms) * \
                jax.lax.stop_gradient(q_next)
            td = jnp.mean(jnp.square(q_sa - target))
            # conservative term: push down OOD actions, up dataset ones
            cql = jnp.mean(jax.scipy.special.logsumexp(q, axis=-1)
                           - q_sa)
            return td + c.cql_alpha * cql, (td, cql)

        def update(params, target_params, opt_state, *batch):
            (loss, (td, cql)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, *batch)
            updates, opt_state = self._tx.update(grads, opt_state)
            return (optax.apply_updates(params, updates), opt_state,
                    loss, td, cql)

        return update

    def train(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.perf_counter()
        obs, actions, rewards, new_obs, terms = self._data
        n = len(obs)
        tds, cqls = [], []
        for _ in range(c.num_batches_per_iteration):
            idx = self._rng.integers(0, n, c.train_batch_size)
            (self.params, self.opt_state, _loss, td, cql) = \
                self._update_fn(
                    self.params, self.target_params, self.opt_state,
                    jnp.asarray(obs[idx]), jnp.asarray(actions[idx]),
                    jnp.asarray(rewards[idx]),
                    jnp.asarray(new_obs[idx]), jnp.asarray(terms[idx]))
            tds.append(float(td))
            cqls.append(float(cql))
            self._num_updates += 1
            if self._num_updates % c.target_network_update_freq == 0:
                self.target_params = jax.tree_util.tree_map(
                    jnp.copy, self.params)
        self.iteration += 1
        return {"training_iteration": self.iteration,
                "td_loss": float(np.mean(tds)),
                "cql_loss": float(np.mean(cqls)),
                "num_updates_lifetime": self._num_updates,
                "time_iteration_s": time.perf_counter() - t0}

    def evaluate(self, num_episodes: int = 10,
                 seed: int = 123) -> Dict[str, float]:
        """Greedy Q rollout."""
        import gymnasium as gym
        env = gym.make(self.config.env)
        params_np = jax.tree_util.tree_map(np.asarray, self.params)
        returns = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=seed + ep)
            total, done = 0.0, False
            while not done:
                q = self.module.forward_np(params_np,
                                           obs.astype(np.float32)[None])
                obs, r, term, trunc, _ = env.step(int(np.argmax(q[0])))
                total += float(r)
                done = term or trunc
            returns.append(total)
        env.close()
        return {"episode_return_mean": float(np.mean(returns)),
                "num_episodes": num_episodes}


MARWILConfig._ALGO = MARWIL
CQLConfig._ALGO = CQL
