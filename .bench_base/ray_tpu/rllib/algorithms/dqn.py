"""DQN: replay-buffer off-policy Q-learning (double-DQN update).

Parity: reference rllib/algorithms/dqn (new-stack DQN with
prioritized replay, target network, double-Q) — sized to this stack:
one SINGLE-JIT update (double-DQN TD loss + adam + importance weights),
epsilon-greedy env runners on a linear schedule, target-network sync
every `target_network_update_freq` updates, uniform or prioritized
buffer from rllib.utils.replay_buffers.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.utils.replay_buffers import (PrioritizedReplayBuffer,
                                                ReplayBuffer)
from ray_tpu.rllib.utils.schedules import LinearSchedule


# ------------------------------------------------------------ q module
def _fnoise(x):
    """Factorized-noise squash f(x) = sign(x)·sqrt(|x|) (NoisyNet)."""
    return jnp.sign(x) * jnp.sqrt(jnp.abs(x))


@dataclasses.dataclass(frozen=True)
class QModule:
    """MLP Q-network: obs -> Q(s, ·) or a return DISTRIBUTION.

    Rainbow components (reference rllib/algorithms/dqn — dueling heads,
    distributional C51, noisy nets):
    - dueling: torso feeds separate value/advantage heads combined as
      V + A - mean(A) (per-atom in distributional mode).
    - num_atoms > 1: C51 — heads emit logits over a fixed support
      linspace(v_min, v_max, num_atoms); Q(s,a) = E_p[z].
    - noisy: head layers carry factorized-Gaussian parameter noise
      (w = mu + sigma·f(eps_out)⊗f(eps_in)); sampling a fresh eps per
      forward IS the exploration, replacing epsilon-greedy."""

    obs_dim: int
    num_actions: int
    hidden: Sequence[int] = (64, 64)
    dueling: bool = False
    num_atoms: int = 1
    v_min: float = -10.0
    v_max: float = 10.0
    noisy: bool = False
    sigma0: float = 0.5

    @property
    def support(self) -> jax.Array:
        return jnp.linspace(self.v_min, self.v_max, self.num_atoms)

    def _dense(self, key, din, dout, scale, head: bool = False):
        w = jax.random.orthogonal(key, max(din, dout))[:din, :dout]
        layer = {"w": (w * scale).astype(jnp.float32),
                 "b": jnp.zeros((dout,), jnp.float32)}
        if head and self.noisy:
            s = self.sigma0 / np.sqrt(din)
            layer["w_sig"] = jnp.full((din, dout), s, jnp.float32)
            layer["b_sig"] = jnp.full((dout,), s, jnp.float32)
        return layer

    def init(self, key: jax.Array) -> dict:
        keys = jax.random.split(key, len(self.hidden) + 3)
        ki = iter(keys)
        layers = []
        din = self.obs_dim
        for h in self.hidden:
            layers.append(self._dense(next(ki), din, h, jnp.sqrt(2.0)))
            din = h
        K = self.num_atoms
        if self.dueling:
            return {"q": layers,
                    "adv": [self._dense(next(ki), din,
                                        self.num_actions * K, 0.01,
                                        head=True)],
                    "val": [self._dense(next(ki), din, K, 1.0,
                                        head=True)]}
        layers.append(self._dense(next(ki), din,
                                  self.num_actions * K, 0.01, head=True))
        return {"q": layers}

    @staticmethod
    def _apply(layer: dict, x, key):
        """One dense layer; with noise params AND a key, apply
        factorized-Gaussian parameter noise (mu-only when key is None —
        the deterministic/eval path)."""
        w, b = layer["w"], layer["b"]
        if "w_sig" in layer and key is not None:
            k_in, k_out = jax.random.split(key)
            e_in = _fnoise(jax.random.normal(k_in, (w.shape[0],)))
            e_out = _fnoise(jax.random.normal(k_out, (w.shape[1],)))
            w = w + layer["w_sig"] * (e_in[:, None] * e_out[None, :])
            b = b + layer["b_sig"] * e_out
        return x @ w + b

    def _head_out(self, params: dict, obs, key):
        """Raw head output: (B, A) for scalar Q, (B, A, K) logits for
        distributional."""
        x = obs
        torso = params["q"] if self.dueling else params["q"][:-1]
        for layer in torso:
            x = jnp.tanh(x @ layer["w"] + layer["b"])
        A, K = self.num_actions, self.num_atoms
        if self.dueling:
            ka, kv = ((None, None) if key is None
                      else jax.random.split(key))
            a = self._apply(params["adv"][0], x, ka)
            v = self._apply(params["val"][0], x, kv)
            if K == 1:
                return v + a - jnp.mean(a, axis=-1, keepdims=True)
            a = a.reshape(a.shape[0], A, K)
            v = v.reshape(v.shape[0], 1, K)
            return v + a - jnp.mean(a, axis=1, keepdims=True)
        out = self._apply(params["q"][-1], x, key)
        return out if K == 1 else out.reshape(out.shape[0], A, K)

    def forward_dist(self, params: dict, obs, key=None) -> jax.Array:
        """(B, A, K) return-distribution logits (num_atoms > 1 only)."""
        return self._head_out(params, obs, key)

    def forward(self, params: dict, obs, key=None) -> jax.Array:
        """(B, A) Q-values (expectation over the support in C51)."""
        out = self._head_out(params, obs, key)
        if self.num_atoms == 1:
            return out
        return jnp.sum(jax.nn.softmax(out, axis=-1) * self.support,
                       axis=-1)

    def forward_np(self, params_np: dict, obs,
                   rng: Optional[np.random.Generator] = None
                   ) -> np.ndarray:
        """Numpy action-value path for env runners; `rng` samples the
        NoisyNet exploration noise."""
        x = obs
        torso = (params_np["q"] if self.dueling
                 else params_np["q"][:-1])
        for layer in torso:
            x = np.tanh(x @ layer["w"] + layer["b"])

        def apply(layer, x):
            w, b = layer["w"], layer["b"]
            if "w_sig" in layer and rng is not None:
                e_in = rng.standard_normal(w.shape[0])
                e_out = rng.standard_normal(w.shape[1])
                f = lambda v: np.sign(v) * np.sqrt(np.abs(v))
                e_in, e_out = f(e_in), f(e_out)
                w = w + layer["w_sig"] * (e_in[:, None] * e_out[None, :])
                b = b + layer["b_sig"] * e_out
            return x @ w + b

        A, K = self.num_actions, self.num_atoms
        if self.dueling:
            a = apply(params_np["adv"][0], x)
            v = apply(params_np["val"][0], x)
            if K == 1:
                return v + a - a.mean(axis=-1, keepdims=True)
            a = a.reshape(len(a), A, K)
            v = v.reshape(len(v), 1, K)
            logits = v + a - a.mean(axis=1, keepdims=True)
        else:
            out = apply(params_np["q"][-1], x)
            if K == 1:
                return out
            logits = out.reshape(len(out), A, K)
        z = logits - logits.max(axis=-1, keepdims=True)
        p = np.exp(z)
        p /= p.sum(axis=-1, keepdims=True)
        support = np.linspace(self.v_min, self.v_max, K)
        return (p * support).sum(axis=-1)


class QEnvRunner:
    """Epsilon-greedy vectorized sampler emitting FLAT transitions
    (s, a, r, s', done) — the off-policy contract, unlike the
    time-major on-policy runner."""

    def __init__(self, config: "DQNConfig", worker_index: int = 0):
        from ray_tpu._private.jaxenv import pin_platform_from_env
        pin_platform_from_env()
        import gymnasium as gym
        self.config = config
        seed = config.seed + 1000 * worker_index
        self._envs = gym.make_vec(config.env,
                                  num_envs=config.num_envs_per_env_runner,
                                  vectorization_mode="sync")
        space = self._envs.single_action_space
        if not hasattr(space, "n"):
            raise ValueError("DQN needs a discrete action space")
        self.module = QModule(
            int(np.prod(self._envs.single_observation_space.shape)),
            int(space.n), tuple(config.hidden),
            dueling=config.dueling, num_atoms=config.num_atoms,
            v_min=config.v_min, v_max=config.v_max,
            noisy=config.noisy, sigma0=config.noisy_sigma0)
        # n-step returns: per-env pending transition windows (reference
        # rainbow n_step; horizon shortens at episode end)
        self._nstep = max(1, int(config.n_step))
        self._pending = [[] for _ in
                         range(config.num_envs_per_env_runner)]
        self.params = jax.tree_util.tree_map(
            np.asarray, self.module.init(jax.random.PRNGKey(seed)))
        self._rng = np.random.default_rng(seed + 1)
        self._obs, _ = self._envs.reset(seed=seed)
        self._prev_done = np.zeros(config.num_envs_per_env_runner, bool)
        self._eps = LinearSchedule(config.epsilon_timesteps,
                                   config.final_epsilon,
                                   config.initial_epsilon)
        self._steps = 0
        self._ep_ret = np.zeros(config.num_envs_per_env_runner)
        self._recent: list = []

    def ping(self):
        return "pong"

    def set_weights(self, weights) -> None:
        self.params = jax.tree_util.tree_map(np.asarray, weights)

    def _emit_nstep(self, rows, env_i: int, flush: bool) -> None:
        """Pop matured windows: (s0, a0, sum gamma^k r_k, s_h, term_h,
        horizon h). On flush (episode boundary) every remaining entry
        emits with its shortened horizon."""
        g = self.config.gamma
        buf = self._pending[env_i]
        while buf and (flush or len(buf) >= self._nstep):
            horizon = min(len(buf), self._nstep)
            R = 0.0
            for k in range(horizon):
                R += (g ** k) * buf[k][2]
            o0, a0 = buf[0][0], buf[0][1]
            nobs_h, term_h = buf[horizon - 1][3], buf[horizon - 1][4]
            rows["obs"].append(o0)
            rows["actions"].append(a0)
            rows["rewards"].append(np.float32(R))
            rows["new_obs"].append(nobs_h)
            rows["terminateds"].append(np.float32(term_h))
            rows["nsteps"].append(np.float32(horizon))
            buf.pop(0)

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        rows = {k: [] for k in ("obs", "actions", "rewards", "new_obs",
                                "terminateds", "nsteps")}
        N = self.config.num_envs_per_env_runner
        for _ in range(num_steps):
            if self.config.noisy:
                # NoisyNet: a fresh parameter-noise sample per step IS
                # the exploration — no epsilon
                q = self.module.forward_np(
                    self.params, self._obs.astype(np.float32),
                    rng=self._rng)
                action = q.argmax(-1).astype(np.int32)
            else:
                q = self.module.forward_np(self.params,
                                           self._obs.astype(np.float32))
                greedy = q.argmax(-1)
                explore = (self._rng.random(N)
                           < self._eps(self._steps))
                random_a = self._rng.integers(0, q.shape[-1], N)
                action = np.where(explore, random_a,
                                  greedy).astype(np.int32)
            nobs, reward, term, trunc, _ = self._envs.step(action)
            done = term | trunc
            valid = ~self._prev_done     # autoreset filler: drop
            for i in np.nonzero(valid)[0]:
                self._pending[i].append(
                    (self._obs[i].astype(np.float32),
                     np.int32(action[i]), float(reward[i]),
                     nobs[i].astype(np.float32), bool(term[i])))
                self._emit_nstep(rows, i, flush=bool(done[i]))
            self._ep_ret[valid] += reward[valid]
            for i in np.nonzero(done & valid)[0]:
                self._recent.append(float(self._ep_ret[i]))
                self._ep_ret[i] = 0.0
            self._recent = self._recent[-100:]
            self._prev_done = done
            self._obs = nobs
            self._steps += N
        if not rows["rewards"]:
            obs_shape = self._obs.shape[1:]
            return {"obs": np.empty((0,) + obs_shape, np.float32),
                    "actions": np.empty((0,), np.int32),
                    "rewards": np.empty((0,), np.float32),
                    "new_obs": np.empty((0,) + obs_shape, np.float32),
                    "terminateds": np.empty((0,), np.float32),
                    "nsteps": np.empty((0,), np.float32)}
        return {k: np.stack(v) for k, v in rows.items()}

    def get_metrics(self) -> Dict[str, Any]:
        return {"episode_return_mean": (float(np.mean(self._recent))
                                        if self._recent else float("nan")),
                "num_episodes": len(self._recent),
                "epsilon": self._eps(self._steps),
                "num_env_steps_sampled": self._steps}

    def stop(self) -> None:
        self._envs.close()


@dataclasses.dataclass
class DQNConfig(AlgorithmConfig):
    env: str = "CartPole-v1"
    num_env_runners: int = 0              # 0 = local
    num_envs_per_env_runner: int = 8
    rollout_steps_per_iteration: int = 64
    hidden: Sequence[int] = (64, 64)
    lr: float = 5e-4
    gamma: float = 0.99
    buffer_size: int = 50_000
    prioritized_replay: bool = True
    train_batch_size: int = 64
    num_updates_per_iteration: int = 16
    learning_starts: int = 500            # env steps before updates
    target_network_update_freq: int = 100  # in updates
    dueling: bool = False                  # V + A - mean(A) heads
    n_step: int = 1                        # multi-step TD returns
    # rainbow: distributional C51 (num_atoms > 1) + noisy nets
    num_atoms: int = 1
    v_min: float = -10.0
    v_max: float = 10.0
    noisy: bool = False                    # NoisyNet exploration
    noisy_sigma0: float = 0.5
    initial_epsilon: float = 1.0
    final_epsilon: float = 0.02
    epsilon_timesteps: int = 10_000
    double_q: bool = True
    seed: int = 0

class DQN:
    """Iterative trainer: sample -> buffer -> k double-DQN updates."""

    def __init__(self, config: DQNConfig):
        self.config = config
        c = config
        if c.num_env_runners == 0:
            self._runners = [QEnvRunner(c)]
            self._remote = False
        else:
            import ray_tpu
            cls = ray_tpu.remote(num_cpus=1)(QEnvRunner)
            self._runners = [cls.remote(c, worker_index=i + 1)
                             for i in range(c.num_env_runners)]
            self._remote = True
        self.module = (self._runners[0].module if not self._remote
                       else QModule(*self._probe_dims(), tuple(c.hidden),
                                    dueling=c.dueling,
                                    num_atoms=c.num_atoms, v_min=c.v_min,
                                    v_max=c.v_max, noisy=c.noisy,
                                    sigma0=c.noisy_sigma0))
        self.params = self.module.init(jax.random.PRNGKey(c.seed))
        self.target_params = jax.tree_util.tree_map(jnp.copy, self.params)
        self._tx = optax.adam(c.lr)
        self.opt_state = self._tx.init(self.params)
        self.buffer = (PrioritizedReplayBuffer(c.buffer_size,
                                               seed=c.seed)
                       if c.prioritized_replay
                       else ReplayBuffer(c.buffer_size, seed=c.seed))
        self._update_fn = jax.jit(self._build_update())
        self._noise_key = jax.random.PRNGKey(c.seed + 17)
        self._num_updates = 0
        self._total_steps = 0
        self.iteration = 0

    def _probe_dims(self) -> Tuple[int, int]:
        import gymnasium as gym
        env = gym.make(self.config.env)
        dims = (int(np.prod(env.observation_space.shape)),
                int(env.action_space.n))
        env.close()
        return dims

    def _build_update(self):
        c = self.config
        module = self.module

        def g_eff_of(batch):
            # n-step bootstrap: reward already sums gamma^k r_k over
            # the window; discount the tail by gamma^horizon
            return c.gamma ** batch.get(
                "nsteps", jnp.ones_like(batch["rewards"]))

        def loss_scalar(params, target_params, batch, key):
            k1, k2, k3 = jax.random.split(key, 3)
            q = module.forward(params, batch["obs"], k1)
            q_sa = jnp.take_along_axis(
                q, batch["actions"][:, None].astype(jnp.int32),
                axis=-1)[:, 0]
            q_next_target = module.forward(target_params,
                                           batch["new_obs"], k2)
            if c.double_q:
                # k3, not k2: online action selection must not share the
                # target net's noise realization (correlated parameter
                # noise would re-couple selection and evaluation)
                a_star = jnp.argmax(
                    module.forward(params, batch["new_obs"], k3), axis=-1)
                q_next = jnp.take_along_axis(
                    q_next_target, a_star[:, None], axis=-1)[:, 0]
            else:
                q_next = jnp.max(q_next_target, axis=-1)
            target = (batch["rewards"]
                      + g_eff_of(batch) * (1.0 - batch["terminateds"])
                      * jax.lax.stop_gradient(q_next))
            td = q_sa - target
            w = batch.get("weights", jnp.ones_like(td))
            return jnp.mean(w * jnp.square(td)), jnp.abs(td)

        def loss_c51(params, target_params, batch, key):
            """Distributional C51 (reference rainbow): project the
            Bellman-updated target distribution onto the fixed support
            and minimise cross-entropy. The per-sample cross-entropy
            doubles as the priority signal."""
            K = c.num_atoms
            z = module.support                      # (K,)
            dz = (c.v_max - c.v_min) / (K - 1)
            k1, k2, k3 = jax.random.split(key, 3)
            logits = module.forward_dist(params, batch["obs"], k1)
            logp_sa = jax.nn.log_softmax(jnp.take_along_axis(
                logits, batch["actions"][:, None, None].astype(
                    jnp.int32).repeat(K, axis=2), axis=1)[:, 0],
                axis=-1)                            # (B, K)
            t_logits = module.forward_dist(target_params,
                                           batch["new_obs"], k2)
            p_next = jax.nn.softmax(t_logits, axis=-1)   # (B, A, K)
            if c.double_q:
                q_online = module.forward(params, batch["new_obs"], k3)
                a_star = jnp.argmax(q_online, axis=-1)
            else:
                a_star = jnp.argmax(jnp.sum(p_next * z, -1), axis=-1)
            p_a = jnp.take_along_axis(
                p_next, a_star[:, None, None].repeat(K, axis=2),
                axis=1)[:, 0]                       # (B, K)
            Tz = jnp.clip(
                batch["rewards"][:, None]
                + g_eff_of(batch)[:, None]
                * (1.0 - batch["terminateds"])[:, None] * z[None, :],
                c.v_min, c.v_max)                   # (B, K)
            b = (Tz - c.v_min) / dz
            lo = jnp.clip(jnp.floor(b), 0, K - 1)
            hi = jnp.clip(lo + 1, 0, K - 1)
            # when b lands exactly on an atom (lo == hi at the top
            # edge), give it full mass instead of losing it
            w_lo = (hi - b) + (lo == hi)
            w_hi = b - lo
            onehot_lo = jax.nn.one_hot(lo.astype(jnp.int32), K)
            onehot_hi = jax.nn.one_hot(hi.astype(jnp.int32), K)
            m = jnp.sum(
                p_a[:, :, None] * (w_lo[:, :, None] * onehot_lo
                                   + w_hi[:, :, None] * onehot_hi),
                axis=1)                             # (B, K)
            m = jax.lax.stop_gradient(m)
            xent = -jnp.sum(m * logp_sa, axis=-1)   # (B,)
            w = batch.get("weights", jnp.ones_like(xent))
            return jnp.mean(w * xent), xent

        loss_fn = loss_c51 if c.num_atoms > 1 else loss_scalar

        def update(params, target_params, opt_state, batch, key):
            (loss, td), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch,
                                       key)
            updates, opt_state = self._tx.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td

        return update

    # ------------------------------------------------------------- api
    def train(self) -> Dict[str, Any]:
        import ray_tpu
        c = self.config
        t0 = time.perf_counter()
        weights = jax.device_get(self.params)
        if self._remote:
            ref = ray_tpu.put(weights)
            # weights FIRST (actor-call ordering applies them before the
            # sample), matching the local path's semantics
            for r in self._runners:
                r.set_weights.remote(ref)
            batches = ray_tpu.get([
                r.sample.remote(c.rollout_steps_per_iteration)
                for r in self._runners])
        else:
            self._runners[0].set_weights(weights)
            batches = [self._runners[0].sample(
                c.rollout_steps_per_iteration)]
        for b in batches:
            if len(b["rewards"]):
                self.buffer.add(b)
                self._total_steps += len(b["rewards"])

        loss = float("nan")
        if self._total_steps >= c.learning_starts:
            for _ in range(c.num_updates_per_iteration):
                batch = self.buffer.sample(c.train_batch_size)
                dev = {k: jnp.asarray(v) for k, v in batch.items()
                       if k != "batch_indexes"}
                self._noise_key, sub = jax.random.split(self._noise_key)
                self.params, self.opt_state, loss_j, td = \
                    self._update_fn(self.params, self.target_params,
                                    self.opt_state, dev, sub)
                loss = float(loss_j)
                self._num_updates += 1
                if isinstance(self.buffer, PrioritizedReplayBuffer):
                    self.buffer.update_priorities(
                        batch["batch_indexes"], np.asarray(td))
                if self._num_updates % c.target_network_update_freq == 0:
                    self.target_params = jax.tree_util.tree_map(
                        jnp.copy, self.params)
        self.iteration += 1
        if self._remote:
            metrics = ray_tpu.get(
                self._runners[0].get_metrics.remote())
        else:
            metrics = self._runners[0].get_metrics()
        metrics.update({
            "training_iteration": self.iteration,
            "num_env_steps_sampled_lifetime": self._total_steps,
            "num_updates_lifetime": self._num_updates,
            "td_loss": loss,
            "buffer_size": len(self.buffer),
            "time_iteration_s": time.perf_counter() - t0,
        })
        return metrics

    def get_state(self) -> Dict[str, Any]:
        """Checkpointable trainer state (replay buffer contents stay
        local — the reference's DQN checkpoints exclude them too by
        default)."""
        return {"params": jax.device_get(self.params),
                "target_params": jax.device_get(self.target_params),
                "opt_state": jax.device_get(self.opt_state),
                "num_updates": self._num_updates,
                "total_steps": self._total_steps,
                "iteration": self.iteration}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = jax.device_put(state["params"])
        self.target_params = jax.device_put(state["target_params"])
        self.opt_state = jax.device_put(state["opt_state"])
        self._num_updates = state.get("num_updates", 0)
        self._total_steps = state.get("total_steps", 0)
        self.iteration = state.get("iteration", 0)

    def stop(self) -> None:
        import ray_tpu
        for r in self._runners:
            try:
                if self._remote:
                    ray_tpu.kill(r)
                else:
                    r.stop()
            except BaseException:
                pass


DQNConfig.algo_class = DQN
