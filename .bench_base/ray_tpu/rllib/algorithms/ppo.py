"""PPO: GAE + clipped-surrogate on the new-stack component layout.

Parity: reference rllib/algorithms/ppo/ppo.py:411 (training_step
:420-489 — synchronous_parallel_sample over the EnvRunnerGroup, then
learner_group.update, then weight sync) and algorithm_config.py's
builder pattern, sized to the TPU-native stack: one jitted learner
update per iteration, CPU env-runner actors, weights fanned out through
the object store.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Optional, Sequence

import numpy as np

from ray_tpu.rllib.core.learner import LearnerGroup, PPOLearnerConfig
from ray_tpu.rllib.env.env_runner import EnvRunnerConfig
from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup


from ray_tpu.rllib.algorithm_config import AlgorithmConfig


@dataclasses.dataclass
class PPOConfig(AlgorithmConfig):
    env: str = "CartPole-v1"
    # --- rollouts
    num_env_runners: int = 0           # 0 = local in-process runner
    num_envs_per_env_runner: int = 32
    rollout_length: int = 64
    # --- model
    hidden: Sequence[int] = (64, 64)
    # --- training
    lr: float = 3e-4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    vf_coef: float = 0.5
    vf_clip: float = 10.0
    ent_coef: float = 0.01
    max_grad_norm: float = 0.5
    num_epochs: int = 4
    num_minibatches: int = 8
    target_kl: float = 0.05
    num_learners: int = 0              # 0 = local in-process learner
    seed: int = 0
    # learner-side connector pipeline (reference rllib/connectors/
    # learner/): e.g. [GeneralAdvantageEstimation(...),
    # StandardizeAdvantages()] moves GAE out of the jit into a
    # composable host-side pipeline
    learner_connectors: Optional[Sequence] = None

class PPO:
    """Iterative trainer: each `train()` = sample -> update -> sync."""

    def __init__(self, config: PPOConfig):
        self.config = config
        self._probe_env()
        self.env_runner_group = EnvRunnerGroup(
            EnvRunnerConfig(
                env=config.env,
                num_envs=config.num_envs_per_env_runner,
                rollout_length=config.rollout_length,
                hidden=tuple(config.hidden),
                seed=config.seed),
            num_env_runners=config.num_env_runners)
        self.learner_group = LearnerGroup(
            PPOLearnerConfig(
                obs_dim=self._obs_dim, num_actions=self._num_actions,
                hidden=tuple(config.hidden), lr=config.lr,
                gamma=config.gamma, gae_lambda=config.gae_lambda,
                clip_eps=config.clip_eps, vf_coef=config.vf_coef,
                vf_clip=config.vf_clip, ent_coef=config.ent_coef,
                max_grad_norm=config.max_grad_norm,
                num_epochs=config.num_epochs,
                num_minibatches=config.num_minibatches,
                target_kl=config.target_kl,
                continuous=self._continuous,
                seed=config.seed,
                learner_connectors=config.learner_connectors),
            num_learners=config.num_learners)
        self.iteration = 0
        self._total_env_steps = 0
        # Runners start from the learner's weights.
        self.env_runner_group.sync_weights(self.learner_group.get_weights())

    def _probe_env(self) -> None:
        import gymnasium as gym
        env = gym.make(self.config.env)
        self._obs_dim = int(np.prod(env.observation_space.shape))
        space = env.action_space
        self._continuous = not hasattr(space, "n")
        self._num_actions = (int(np.prod(space.shape))
                             if self._continuous else int(space.n))
        env.close()

    # ------------------------------------------------------------ api
    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        batches = self.env_runner_group.sample()
        t_sample = time.perf_counter() - t0
        # Concatenate runner batches on the env axis (all time-major).
        batch = {k: np.concatenate([b[k] for b in batches], axis=1)
                 for k in batches[0]}
        t1 = time.perf_counter()
        learner_metrics = self.learner_group.update(batch)
        t_update = time.perf_counter() - t1
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights())
        self.env_runner_group.probe_unhealthy_env_runners()
        self.iteration += 1
        self._total_env_steps += int(batch["mask"].sum())
        metrics = self.env_runner_group.aggregate_metrics()
        metrics.update(learner_metrics)
        metrics.update(self.learner_group.sgd_throughput())
        metrics.update({
            "training_iteration": self.iteration,
            "num_env_steps_sampled_lifetime": self._total_env_steps,
            "time_sample_s": t_sample,
            "time_update_s": t_update,
            "env_steps_per_s": batch["mask"].sum() / max(
                time.perf_counter() - t0, 1e-9),
        })
        return metrics

    def get_state(self) -> Dict[str, Any]:
        return {"learner": self.learner_group.get_state(),
                "iteration": self.iteration,
                "total_env_steps": self._total_env_steps}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.learner_group.set_state(state["learner"])
        self.iteration = state.get("iteration", 0)
        self._total_env_steps = state.get("total_env_steps", 0)
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights())

    def stop(self) -> None:
        self.env_runner_group.stop()
        self.learner_group.shutdown()


PPOConfig.algo_class = PPO
