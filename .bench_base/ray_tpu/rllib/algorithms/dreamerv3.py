"""DreamerV3: model-based RL via a recurrent state-space world model.

Parity (structure, not scale) with reference rllib/algorithms/dreamerv3
(dreamerv3.py + tf world_model.py / actor_network.py / critic_network.py,
itself after Hafner et al. 2023):
- RSSM world model: GRU deterministic path + categorical stochastic
  latents (straight-through gradients), encoder/decoder in symlog
  space, reward + continue heads, KL balancing with free bits.
- Actor + critic trained purely on IMAGINED rollouts through the world
  model: lambda-returns over predicted rewards/continues, return-range
  normalization (EMA of the 5th..95th percentile spread), entropy-
  regularized REINFORCE for the discrete actor.
- A recurrent env runner carries (h, z) across real steps and resets
  them at episode boundaries.

Everything trains in ONE jitted update per iteration — world model,
actor, and critic — the TPU-native shape of the reference's three
optimizers. Discrete action spaces (the reference's Atari/gym path).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm_config import AlgorithmConfig


def symlog(x):
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def _dense(key, din, dout, scale=1.0):
    w = jax.random.normal(key, (din, dout)) * scale / np.sqrt(din)
    return {"w": w.astype(jnp.float32),
            "b": jnp.zeros((dout,), jnp.float32)}


def _mlp_init(key, dims, scale_last=1.0):
    keys = jax.random.split(key, len(dims) - 1)
    return [_dense(k, dims[i], dims[i + 1],
                   1.0 if i < len(dims) - 2 else scale_last)
            for i, k in enumerate(keys)]


def _mlp(layers, x):
    for layer in layers[:-1]:
        x = jax.nn.silu(x @ layer["w"] + layer["b"])
    last = layers[-1]
    return x @ last["w"] + last["b"]


def _gru_init(key, din, dh):
    k1, k2 = jax.random.split(key)
    return {"wi": _dense(k1, din, 3 * dh),
            "wh": _dense(k2, dh, 3 * dh)}


def _gru(p, h, x):
    gi = x @ p["wi"]["w"] + p["wi"]["b"]
    gh = h @ p["wh"]["w"] + p["wh"]["b"]
    ir, iz, inn = jnp.split(gi, 3, axis=-1)
    hr, hz, hnn = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(ir + hr)
    z = jax.nn.sigmoid(iz + hz)
    n = jnp.tanh(inn + r * hnn)
    return (1.0 - z) * n + z * h


@dataclasses.dataclass
class DreamerV3Config(AlgorithmConfig):
    env: str = "CartPole-v1"
    num_envs: int = 16
    rollout_length: int = 32
    # --- world model
    deter_dim: int = 128                   # GRU state
    n_categoricals: int = 8
    n_classes: int = 8
    embed_dim: int = 64
    units: int = 128                       # MLP width
    wm_lr: float = 4e-4
    free_bits: float = 1.0
    kl_dyn_scale: float = 0.5
    kl_rep_scale: float = 0.1
    # --- behavior (imagination)
    horizon: int = 15
    gamma: float = 0.99
    gae_lambda: float = 0.95
    actor_lr: float = 4e-5
    critic_lr: float = 1e-4
    ent_coef: float = 3e-3
    imag_starts: int = 256                 # imagined trajectories/update
    num_updates_per_iteration: int = 4
    seed: int = 0

class DreamerV3:
    """sample (recurrent runner) -> world-model + behavior updates."""

    def __init__(self, config: DreamerV3Config):
        import gymnasium as gym
        self.config = c = config
        self._envs = gym.make_vec(c.env, num_envs=c.num_envs,
                                  vectorization_mode="sync")
        space = self._envs.single_action_space
        if not hasattr(space, "n"):
            raise ValueError("DreamerV3 (this stack) needs a discrete "
                             "action space")
        self.obs_dim = int(np.prod(
            self._envs.single_observation_space.shape))
        self.num_actions = int(space.n)
        self.z_dim = c.n_categoricals * c.n_classes
        self.feat_dim = c.deter_dim + self.z_dim

        key = jax.random.PRNGKey(c.seed)
        ks = jax.random.split(key, 12)
        u, D = c.units, c.deter_dim
        self.wm = {
            "enc": _mlp_init(ks[0], (self.obs_dim, u, c.embed_dim)),
            "gru": _gru_init(ks[1], self.z_dim + self.num_actions, D),
            "prior": _mlp_init(ks[2], (D, u, self.z_dim)),
            "post": _mlp_init(ks[3], (D + c.embed_dim, u, self.z_dim)),
            "dec": _mlp_init(ks[4], (self.feat_dim, u, self.obs_dim)),
            "rew": _mlp_init(ks[5], (self.feat_dim, u, 1), 0.0),
            "cont": _mlp_init(ks[6], (self.feat_dim, u, 1)),
        }
        self.actor = _mlp_init(ks[7], (self.feat_dim, u, u,
                                       self.num_actions), 0.01)
        self.critic = _mlp_init(ks[8], (self.feat_dim, u, u, 1), 0.0)
        self._wm_tx = optax.chain(optax.clip_by_global_norm(100.0),
                                  optax.adam(c.wm_lr, eps=1e-8))
        self._actor_tx = optax.chain(optax.clip_by_global_norm(100.0),
                                     optax.adam(c.actor_lr, eps=1e-8))
        self._critic_tx = optax.chain(optax.clip_by_global_norm(100.0),
                                      optax.adam(c.critic_lr, eps=1e-8))
        self.wm_opt = self._wm_tx.init(self.wm)
        self.actor_opt = self._actor_tx.init(self.actor)
        self.critic_opt = self._critic_tx.init(self.critic)
        # return-range EMA (reference's percentile return normalizer)
        self.ret_scale = jnp.asarray(1.0)
        self._key = ks[9]

        self._update_fn = jax.jit(self._build_update())
        self._act_fn = jax.jit(self._build_act())

        self._obs, _ = self._envs.reset(seed=c.seed)
        self._h = np.zeros((c.num_envs, D), np.float32)
        self._z = np.zeros((c.num_envs, self.z_dim), np.float32)
        self._prev_a = np.zeros(c.num_envs, np.int64)
        self._prev_done = np.zeros(c.num_envs, bool)
        self._ep_ret = np.zeros(c.num_envs)
        self._recent: list = []
        self._total_steps = 0
        self.iteration = 0

    # ----------------------------------------------------- rssm pieces
    def _sample_z(self, logits, key):
        """Straight-through categorical sample -> flat one-hot z."""
        c = self.config
        lg = logits.reshape(logits.shape[:-1]
                            + (c.n_categoricals, c.n_classes))
        # unimix (1% uniform) keeps gradients alive (reference)
        probs = 0.99 * jax.nn.softmax(lg, -1) + 0.01 / c.n_classes
        lg = jnp.log(probs)
        idx = jax.random.categorical(key, lg)
        one = jax.nn.one_hot(idx, c.n_classes)
        st = one + jax.nn.softmax(lg, -1) - jax.lax.stop_gradient(
            jax.nn.softmax(lg, -1))
        return st.reshape(st.shape[:-2] + (self.z_dim,)), lg

    def _kl(self, lhs_logits, rhs_logits):
        """KL(lhs || rhs) summed over categoricals (both already
        unimixed log-probs of shape (..., n_cat, n_cls))."""
        p = jnp.exp(lhs_logits)
        return jnp.sum(p * (lhs_logits - rhs_logits), axis=(-2, -1))

    # ------------------------------------------------------------- jit
    def _build_act(self):
        c = self.config

        def act(wm, actor, h, z, obs, prev_a, reset, key):
            k1, k2 = jax.random.split(key)
            h = h * (1.0 - reset)[:, None]
            z = z * (1.0 - reset)[:, None]
            a_oh = jax.nn.one_hot(prev_a, self.num_actions) \
                * (1.0 - reset)[:, None]
            h = _gru(wm["gru"], h, jnp.concatenate([z, a_oh], -1))
            embed = _mlp(wm["enc"], symlog(obs))
            post_logits = _mlp(wm["post"],
                               jnp.concatenate([h, embed], -1))
            z, _ = self._sample_z(post_logits, k1)
            feat = jnp.concatenate([h, z], -1)
            a = jax.random.categorical(k2, _mlp(actor, feat))
            return h, z, a

        return act

    def _build_update(self):
        c = self.config

        def observe(wm, batch, key):
            """Posterior scan over the real sequence; returns features
            + per-step stats. batch obs (T+1,N,D); actions (T,N)."""
            obs = symlog(batch["obs"])
            T, N = batch["actions"].shape
            embeds = _mlp(wm["enc"], obs)          # (T+1, N, E)
            a_oh = jax.nn.one_hot(batch["actions"], self.num_actions)
            # state input at t uses a_{t-1} (zero at t=0)
            a_prev = jnp.concatenate(
                [jnp.zeros_like(a_oh[:1]), a_oh[:-1]], 0)
            # reset at t where the previous step ended an episode
            reset = jnp.concatenate(
                [jnp.ones((1, N)), batch["dones"][:-1]], 0)
            keys = jax.random.split(key, T)

            def step(carry, inp):
                h, z = carry
                embed_t, a_t, reset_t, k = inp
                h = h * (1.0 - reset_t)[:, None]
                z = z * (1.0 - reset_t)[:, None]
                h = _gru(wm["gru"], h,
                         jnp.concatenate([z, a_t], -1))
                prior_logits = _mlp(wm["prior"], h)
                post_logits = _mlp(wm["post"],
                                   jnp.concatenate([h, embed_t], -1))
                z, post_lg = self._sample_z(post_logits, k)
                _, prior_lg = self._sample_z(prior_logits, k)
                return (h, z), (h, z, post_lg, prior_lg)

            (h, z), (hs, zs, post_lg, prior_lg) = jax.lax.scan(
                step,
                (jnp.zeros((N, c.deter_dim)),
                 jnp.zeros((N, self.z_dim))),
                (embeds[:T], a_prev, reset, keys))
            return hs, zs, post_lg, prior_lg

        def wm_loss(wm, batch, key):
            obs_sym = symlog(batch["obs"])
            T, N = batch["actions"].shape
            m = batch["mask"]
            hs, zs, post_lg, prior_lg = observe(wm, batch, key)
            feat = jnp.concatenate([hs, zs], -1)   # (T, N, F)
            recon = _mlp(wm["dec"], feat)
            l_rec = jnp.sum(jnp.square(recon - obs_sym[:T]), -1)
            rew_p = _mlp(wm["rew"], feat)[..., 0]
            l_rew = jnp.square(rew_p - symlog(batch["rewards"]))
            cont_p = _mlp(wm["cont"], feat)[..., 0]
            cont_t = 1.0 - batch["terminateds"]
            l_cont = optax.sigmoid_binary_cross_entropy(cont_p, cont_t)
            # KL balancing + free bits (reference dreamerv3)
            kl_dyn = self._kl(jax.lax.stop_gradient(post_lg), prior_lg)
            kl_rep = self._kl(post_lg, jax.lax.stop_gradient(prior_lg))
            l_kl = (c.kl_dyn_scale * jnp.maximum(kl_dyn, c.free_bits)
                    + c.kl_rep_scale * jnp.maximum(kl_rep, c.free_bits))
            denom = jnp.maximum(m.sum(), 1.0)
            loss = jnp.sum((l_rec + l_rew + l_cont + l_kl) * m) / denom
            stats = {"wm_loss": loss,
                     "recon_loss": jnp.sum(l_rec * m) / denom,
                     "reward_loss": jnp.sum(l_rew * m) / denom,
                     "kl_dyn": jnp.sum(kl_dyn * m) / denom}
            return loss, (feat, stats)

        def imagine(wm, actor, start_feat, key):
            """Roll the actor through the PRIOR for `horizon` steps."""
            D = c.deter_dim
            h0 = start_feat[:, :D]
            z0 = start_feat[:, D:]
            keys = jax.random.split(key, c.horizon)

            def step(carry, k):
                h, z = carry
                ka, kz = jax.random.split(k)
                feat = jnp.concatenate([h, z], -1)
                a = jax.random.categorical(ka, _mlp(actor, feat))
                a_oh = jax.nn.one_hot(a, self.num_actions)
                h2 = _gru(wm["gru"], h, jnp.concatenate([z, a_oh], -1))
                z2, _ = self._sample_z(_mlp(wm["prior"], h2), kz)
                return (h2, z2), (feat, a)

            (_, _), (feats, acts) = jax.lax.scan(step, (h0, z0), keys)
            return feats, acts

        def behavior_losses(wm, actor, critic, start_feat, ret_scale,
                            key):
            feats, acts = imagine(wm, actor, start_feat, key)
            feats = jax.lax.stop_gradient(feats)     # (H, B, F)
            rew = symexp(_mlp(wm["rew"], feats)[..., 0])
            cont = jax.nn.sigmoid(_mlp(wm["cont"], feats)[..., 0])
            disc = c.gamma * cont
            v = _mlp(critic, feats)[..., 0]          # (H, B)

            def lam_step(carry, inp):
                r_t, d_t, v_t = inp
                ret = r_t + d_t * ((1 - c.gae_lambda) * v_t
                                   + c.gae_lambda * carry)
                return ret, ret

            # bootstrap from the last value
            _, rets = jax.lax.scan(
                lam_step, v[-1],
                (rew[:-1], disc[:-1], v[1:]), reverse=True)
            rets = jax.lax.stop_gradient(rets)       # (H-1, B)
            v_loss = jnp.mean(jnp.square(v[:-1] - rets))
            # return-range normalization (EMA of P95-P5 spread)
            spread = jnp.percentile(rets, 95) - jnp.percentile(rets, 5)
            new_scale = 0.99 * ret_scale + 0.01 * spread
            adv = (rets - v[:-1]) / jnp.maximum(1.0, new_scale)
            logits = _mlp(actor, feats[:-1])
            logp = jax.nn.log_softmax(logits)
            lp_a = jnp.take_along_axis(
                logp, acts[:-1][..., None], -1)[..., 0]
            ent = -jnp.sum(jnp.exp(logp) * logp, -1)
            a_loss = jnp.mean(-jax.lax.stop_gradient(adv) * lp_a
                              - c.ent_coef * ent)
            return a_loss, v_loss, new_scale, {
                "actor_loss": a_loss, "critic_loss": v_loss,
                "imag_return_mean": rets.mean(),
                "actor_entropy": ent.mean(), "ret_scale": new_scale}

        def update(wm, actor, critic, opts, ret_scale, batch, key):
            wm_opt, actor_opt, critic_opt = opts
            k1, k2, k3 = jax.random.split(key, 3)
            (wl, (feat, wm_stats)), wm_grads = jax.value_and_grad(
                wm_loss, has_aux=True)(wm, batch, k1)
            upd, wm_opt = self._wm_tx.update(wm_grads, wm_opt)
            wm = optax.apply_updates(wm, upd)

            # imagination starts: subsample posterior states
            T, N = batch["actions"].shape
            flat = feat.reshape(T * N, -1)
            idx = jax.random.choice(
                k2, T * N, (min(c.imag_starts, T * N),), replace=False)
            start = jax.lax.stop_gradient(flat[idx])

            # one combined grad pass: a_loss's critic dependence and
            # v_loss's actor dependence are both stop-gradient'd, so
            # d(a_loss+v_loss)/d(actor,critic) equals the separate
            # gradients — 1 behavior eval instead of 3
            def ac_loss_fn(ac):
                actor_p, critic_p = ac
                a_loss, v_loss, new_scale, b_stats = behavior_losses(
                    wm, actor_p, critic_p, start, ret_scale, k3)
                return a_loss + v_loss, (new_scale, b_stats)

            (_, (new_scale, b_stats)), (a_grads, v_grads) = \
                jax.value_and_grad(ac_loss_fn, has_aux=True)(
                    (actor, critic))
            upd, actor_opt = self._actor_tx.update(a_grads, actor_opt)
            actor = optax.apply_updates(actor, upd)
            upd, critic_opt = self._critic_tx.update(v_grads, critic_opt)
            critic = optax.apply_updates(critic, upd)
            stats = {**wm_stats, **b_stats}
            return (wm, actor, critic,
                    (wm_opt, actor_opt, critic_opt), new_scale, stats)

        return update

    # ------------------------------------------------------------- api
    def _sample(self) -> Dict[str, np.ndarray]:
        c = self.config
        T, N = c.rollout_length, c.num_envs
        obs_buf = np.empty((T + 1, N, self.obs_dim), np.float32)
        act_buf = np.empty((T, N), np.int32)
        rew_buf = np.empty((T, N), np.float32)
        term_buf = np.empty((T, N), np.float32)
        done_buf = np.empty((T, N), np.float32)
        mask_buf = np.empty((T, N), np.float32)
        for t in range(T):
            obs_f = self._obs.reshape(N, -1).astype(np.float32)
            obs_buf[t] = obs_f
            self._key, sub = jax.random.split(self._key)
            h, z, a = self._act_fn(
                self.wm, self.actor, jnp.asarray(self._h),
                jnp.asarray(self._z), jnp.asarray(obs_f),
                jnp.asarray(self._prev_a),
                jnp.asarray(self._prev_done, jnp.float32), sub)
            self._h, self._z = np.asarray(h), np.asarray(z)
            a = np.asarray(a)
            nobs, rew, term, trunc, _ = self._envs.step(a)
            done = term | trunc
            valid = ~self._prev_done
            act_buf[t] = a
            rew_buf[t] = rew
            term_buf[t] = term.astype(np.float32)
            done_buf[t] = done.astype(np.float32)
            mask_buf[t] = valid.astype(np.float32)
            self._ep_ret[valid] += rew[valid]
            for i in np.nonzero(done & valid)[0]:
                self._recent.append(float(self._ep_ret[i]))
                self._ep_ret[i] = 0.0
            self._recent = self._recent[-100:]
            self._prev_a = a.astype(np.int64)
            self._prev_done = done
            self._obs = nobs
            self._total_steps += N
        obs_buf[T] = self._obs.reshape(N, -1).astype(np.float32)
        return {"obs": obs_buf, "actions": act_buf, "rewards": rew_buf,
                "terminateds": term_buf, "dones": done_buf,
                "mask": mask_buf}

    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        batch = self._sample()
        dev = {k: jnp.asarray(v) for k, v in batch.items()}
        stats: Dict[str, Any] = {}
        for _ in range(self.config.num_updates_per_iteration):
            self._key, sub = jax.random.split(self._key)
            (self.wm, self.actor, self.critic,
             (self.wm_opt, self.actor_opt, self.critic_opt),
             self.ret_scale, stats_j) = self._update_fn(
                self.wm, self.actor, self.critic,
                (self.wm_opt, self.actor_opt, self.critic_opt),
                self.ret_scale, dev, sub)
            stats = {k: float(v) for k, v in stats_j.items()}
        self.iteration += 1
        stats.update({
            "training_iteration": self.iteration,
            "episode_return_mean": (float(np.mean(self._recent))
                                    if self._recent else float("nan")),
            "num_episodes": len(self._recent),
            "num_env_steps_sampled_lifetime": self._total_steps,
            "time_iteration_s": time.perf_counter() - t0,
        })
        return stats

    def get_state(self) -> Dict[str, Any]:
        return {"wm": jax.device_get(self.wm),
                "actor": jax.device_get(self.actor),
                "critic": jax.device_get(self.critic),
                "opts": jax.device_get((self.wm_opt, self.actor_opt,
                                        self.critic_opt)),
                "ret_scale": float(self.ret_scale),
                "key": jax.device_get(self._key),
                "total_steps": self._total_steps,
                "iteration": self.iteration}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.wm = jax.device_put(state["wm"])
        self.actor = jax.device_put(state["actor"])
        self.critic = jax.device_put(state["critic"])
        if "opts" in state:
            self.wm_opt, self.actor_opt, self.critic_opt = \
                jax.device_put(state["opts"])
        self.ret_scale = jnp.asarray(state.get("ret_scale", 1.0))
        if "key" in state:
            self._key = jnp.asarray(state["key"])
        self._total_steps = state.get("total_steps", 0)
        self.iteration = state.get("iteration", 0)

    def stop(self) -> None:
        self._envs.close()


DreamerV3Config.algo_class = DreamerV3
