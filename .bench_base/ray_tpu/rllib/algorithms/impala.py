"""IMPALA: asynchronous sampling + V-trace off-policy correction.

Parity: reference rllib/algorithms/impala/impala.py (async env-runner
sampling and queued learner consumption, :580-611) and the V-trace
returns of the IMPALA paper (Espeholt et al. 2018) — re-designed for the
TPU stack: instead of aggregator actors + a torch learner thread, the
driver runs one event loop that (a) keeps every env-runner actor
perpetually sampling through `foreach_actor_async`, (b) feeds a bounded
sample queue, and (c) drains the queue into a SINGLE-JIT V-trace update
(values, vtrace targets, losses, optimizer — one XLA program). Runners
act on stale weights by design; rho/c clipping corrects the off-policy
gap. Weights fan out per-runner right before each resubmission, so a
slow runner never blocks a fast one (the async property that gives
IMPALA its throughput edge over synchronous PPO).
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.rl_module import ActorCriticModule, Categorical
from ray_tpu.rllib.env.env_runner import EnvRunnerConfig
from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup


@dataclasses.dataclass
class IMPALAConfig(AlgorithmConfig):
    env: str = "CartPole-v1"
    # --- rollouts (async: runners resample as soon as they finish)
    num_env_runners: int = 2
    num_envs_per_env_runner: int = 16
    rollout_length: int = 32
    # --- model
    hidden: Sequence[int] = (64, 64)
    # --- training
    lr: float = 6e-4
    gamma: float = 0.99
    vtrace_rho_clip: float = 1.0
    vtrace_c_clip: float = 1.0
    vf_coef: float = 0.5
    ent_coef: float = 0.01
    max_grad_norm: float = 40.0
    # updates per train() call and queue bound (batches, not bytes)
    num_updates_per_iteration: int = 8
    sample_queue_size: int = 4
    broadcast_interval: int = 1   # push weights every k-th resubmission
    num_devices: int = 1          # learner dp-mesh width (see LearnerGroup)
    seed: int = 0

    def environment(self, env: str) -> "IMPALAConfig":
        self.env = env
        return self

    def env_runners(self, **kw) -> "IMPALAConfig":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown env_runners option {k!r}")
            setattr(self, k, v)
        return self

    def training(self, **kw) -> "IMPALAConfig":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown IMPALA training option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "IMPALA":
        return IMPALA(self)


def vtrace_returns(values, rewards, terms, dones, behaviour_logp,
                   target_logp, gamma, rho_clip, c_clip):
    """V-trace targets vs_t and policy-gradient advantages (no grads).

    values (T+1, N) — bootstrap value included; everything else (T, N).
    Returns (vs (T, N), pg_adv (T, N), rho_clipped (T, N)).
    """
    rho = jnp.exp(target_logp - behaviour_logp)
    rho_cl = jnp.minimum(rho_clip, rho)
    c = jnp.minimum(c_clip, rho)
    not_term = 1.0 - terms          # termination cuts the bootstrap
    not_done = 1.0 - dones          # any episode end cuts the recursion
    delta = rho_cl * (rewards + gamma * not_term * values[1:]
                      - values[:-1])

    def step(carry, inp):
        delta_t, c_t, nd_t = inp
        ws = delta_t + gamma * nd_t * c_t * carry
        return ws, ws

    _, ws = jax.lax.scan(step, jnp.zeros_like(values[0]),
                         (delta, c, not_done), reverse=True)
    vs = values[:-1] + ws
    # vs_{t+1} with the true bootstrap at the end of the fragment
    vs_tp1 = jnp.concatenate([vs[1:], values[-1:]], axis=0)
    vs_tp1 = not_done * vs_tp1 + (1.0 - not_done) * values[1:]
    pg_adv = rho_cl * (rewards + gamma * not_term * vs_tp1 - values[:-1])
    return vs, pg_adv, rho_cl


@dataclasses.dataclass
class IMPALALearnerConfig:
    obs_dim: int = 0
    num_actions: int = 0
    hidden: Sequence[int] = (64, 64)
    lr: float = 6e-4
    gamma: float = 0.99
    vtrace_rho_clip: float = 1.0
    vtrace_c_clip: float = 1.0
    vf_coef: float = 0.5
    ent_coef: float = 0.01
    max_grad_norm: float = 40.0
    num_devices: int = 1
    seed: int = 0


class IMPALALearner:
    """Single-jit V-trace update; optional dp-mesh batch sharding."""

    # leading replicated args of the update signature before the batch
    # (APPO adds target_params and sets 3)
    N_REPLICATED_ARGS = 2

    def __init__(self, config: IMPALALearnerConfig):
        from ray_tpu._private.jaxenv import pin_platform_from_env
        pin_platform_from_env()
        self.config = config
        self.module = ActorCriticModule(
            config.obs_dim, config.num_actions, tuple(config.hidden))
        self._tx = optax.chain(
            optax.clip_by_global_norm(config.max_grad_norm),
            optax.adam(config.lr, eps=1e-5))
        self.params = self.module.init(jax.random.PRNGKey(config.seed))
        self.opt_state = self._tx.init(self.params)
        self.version = 0
        self._timer = {"updates": 0, "update_time": 0.0, "transitions": 0}
        self._update_fn = self._jit(self._build_update())

    def _jit(self, update):
        """jit with dp-mesh batch sharding when num_devices > 1; the
        update signature is N_REPLICATED_ARGS replicated pytrees
        followed by the time-major batch."""
        config = self.config
        if config.num_devices <= 1:
            return jax.jit(update)
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        devs = jax.devices()
        if len(devs) < config.num_devices:
            raise ValueError(
                f"num_devices={config.num_devices} > {len(devs)}")
        mesh = Mesh(np.array(devs[:config.num_devices]), ("dp",))
        repl = NamedSharding(mesh, P())

        def shard_for(name):
            return NamedSharding(
                mesh, P(*((None, "dp", None) if name == "obs"
                          else (None, "dp"))))
        return jax.jit(
            update,
            in_shardings=(repl,) * self.N_REPLICATED_ARGS + (
                {k: shard_for(k) for k in
                 ("obs", "actions", "logp", "rewards",
                  "terminateds", "dones", "mask")},),
            out_shardings=(repl, repl, repl))

    def _build_update(self):
        c = self.config
        module = self.module

        def loss_fn(params, batch):
            logits, value = module.forward(params, batch["obs"])
            logits = logits[:-1]                       # (T, N, A)
            logp = Categorical.log_prob(logits, batch["actions"])
            vs, pg_adv, _rho = vtrace_returns(
                jax.lax.stop_gradient(value), batch["rewards"],
                batch["terminateds"], batch["dones"], batch["logp"],
                jax.lax.stop_gradient(logp), c.gamma,
                c.vtrace_rho_clip, c.vtrace_c_clip)
            m = batch["mask"]
            denom = jnp.maximum(jnp.sum(m), 1.0)
            pg_loss = -jnp.sum(logp * pg_adv * m) / denom
            v_loss = 0.5 * jnp.sum(
                jnp.square(vs - value[:-1]) * m) / denom
            ent = jnp.sum(Categorical.entropy(logits) * m) / denom
            total = pg_loss + c.vf_coef * v_loss - c.ent_coef * ent
            return total, {"policy_loss": pg_loss, "vf_loss": v_loss,
                           "entropy": ent,
                           "mean_rho": jnp.sum(_rho * m) / denom}

        def update(params, opt_state, batch):
            (_, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self._tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, metrics

        return update

    # ------------------------------------------------------------- api
    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        t0 = time.perf_counter()
        self.params, self.opt_state, metrics = self._update_fn(
            self.params, self.opt_state, batch)
        metrics = {k: float(v) for k, v in metrics.items()}
        dt = time.perf_counter() - t0
        self.version += 1
        self._timer["updates"] += 1
        self._timer["update_time"] += dt
        self._timer["transitions"] += int(np.prod(batch["rewards"].shape))
        metrics["update_time_s"] = dt
        return metrics

    def sgd_throughput(self) -> Dict[str, float]:
        t = max(self._timer["update_time"], 1e-9)
        return {"learner_transitions_per_s": self._timer["transitions"] / t,
                "updates_per_s": self._timer["updates"] / t}

    def get_weights(self):
        return jax.device_get(self.params)


class IMPALA:
    """Asynchronous trainer: runners sample continuously; each `train()`
    performs `num_updates_per_iteration` V-trace updates off the queue."""

    def __init__(self, config: IMPALAConfig):
        if config.num_env_runners < 1:
            raise ValueError("IMPALA is asynchronous: needs >=1 remote "
                             "env runner (use PPO for local debugging)")
        self.config = config
        self._probe_env()
        self.env_runner_group = EnvRunnerGroup(
            EnvRunnerConfig(
                env=config.env,
                num_envs=config.num_envs_per_env_runner,
                rollout_length=config.rollout_length,
                hidden=tuple(config.hidden),
                seed=config.seed),
            num_env_runners=config.num_env_runners)
        self.learner = self._make_learner()
        self._queue: deque = deque(maxlen=config.sample_queue_size)
        self._mgr = self.env_runner_group.manager
        self._runner_version: Dict[int, int] = {}
        self._resubmits: Dict[int, int] = {}
        self.iteration = 0
        self._total_env_steps = 0
        self._dropped_batches = 0
        self._broadcast_count = 0
        self._last_restore_probe = 0.0
        # prime the pipeline: everyone gets weights and starts sampling
        self.env_runner_group.sync_weights(self.learner.get_weights())
        for aid in self._mgr.healthy_actor_ids():
            self._runner_version[aid] = 0
            self._resubmits[aid] = 0
        self._mgr.foreach_actor_async("sample", tag="s")

    LEARNER_CLS = IMPALALearner
    LEARNER_CONFIG_CLS = IMPALALearnerConfig

    def _make_learner(self) -> "IMPALALearner":
        """Factory hook: learner-config fields mirror algorithm-config
        fields by name (APPO only swaps the two classes)."""
        kw = {f.name: getattr(self.config, f.name)
              for f in dataclasses.fields(self.LEARNER_CONFIG_CLS)
              if hasattr(self.config, f.name)}
        kw.update(obs_dim=self._obs_dim,
                  num_actions=self._num_actions,
                  hidden=tuple(self.config.hidden))
        return self.LEARNER_CLS(self.LEARNER_CONFIG_CLS(**kw))

    def _probe_env(self) -> None:
        import gymnasium as gym
        env = gym.make(self.config.env)
        self._obs_dim = int(np.prod(env.observation_space.shape))
        self._num_actions = int(env.action_space.n)
        env.close()

    # ---------------------------------------------------------- async
    def _pump(self, timeout: float = 0.0) -> None:
        """Collect finished rollouts into the queue, then keep every
        healthy runner saturated: push fresh weights (actor-call
        ordering guarantees they apply before the next rollout) and
        re-submit `sample` to any runner with nothing in flight."""
        import ray_tpu
        # Dead-runner recovery must not depend on the queue running
        # dry (a healthy majority can keep it fed forever): probe
        # unhealthy actors on a 1s cadence from the pump itself.
        if (self._mgr.num_healthy_actors < self._mgr.num_actors
                and time.time() - self._last_restore_probe > 1.0):
            self._last_restore_probe = time.time()
            self._restore_runners()
        results = self._mgr.fetch_ready_async_reqs(
            timeout_seconds=timeout, tags=["s"])
        for r in results:
            if r.ok:
                if len(self._queue) == self._queue.maxlen:
                    self._dropped_batches += 1
                self._queue.append(r.value)
        # drain completed weight-push acks so they don't pin in-flight
        self._mgr.fetch_ready_async_reqs(timeout_seconds=0.0, tags=["w"])
        weights_ref = None
        for aid in self._mgr.healthy_actor_ids():
            if self._mgr.num_in_flight(aid, tag="s") > 0:
                continue
            self._resubmits[aid] = self._resubmits.get(aid, 0) + 1
            if (self._runner_version.get(aid, -1) < self.learner.version
                    and self._resubmits[aid]
                    % self.config.broadcast_interval == 0):
                if weights_ref is None:
                    weights_ref = ray_tpu.put(self.learner.get_weights())
                n = self._mgr.foreach_actor_async(
                    "set_weights", args=(weights_ref,),
                    remote_actor_ids=[aid], tag="w")
                if n:        # skipped at in-flight cap -> retry next pump
                    self._runner_version[aid] = self.learner.version
                    self._broadcast_count += 1
            self._mgr.foreach_actor_async("sample", remote_actor_ids=[aid],
                                          tag="s")

    def _restore_runners(self) -> None:
        restored = self.env_runner_group.probe_unhealthy_env_runners()
        for aid in restored:
            self._runner_version[aid] = -1   # full weight push next pump

    # ------------------------------------------------------------ api
    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        updates = 0
        learner_metrics: Dict[str, float] = {}
        # stall = 120s WITHOUT A SAMPLE, not 120s of train() wall time:
        # reset whenever the pump delivers, so long legitimate
        # iterations never trip it.
        stall_deadline = time.time() + 120.0
        while updates < self.config.num_updates_per_iteration:
            if not self._queue:
                self._pump(timeout=0.02)
                if not self._queue:
                    if time.time() > stall_deadline:
                        raise TimeoutError(
                            "IMPALA: no samples for 120s — all env "
                            "runners dead?")
                    self._restore_runners()
                    continue
                stall_deadline = time.time() + 120.0
            self._pump(timeout=0.0)      # opportunistic, non-blocking
            batch = self._queue.popleft()
            stall_deadline = time.time() + 120.0
            learner_metrics = self.learner.update(batch)
            self._total_env_steps += int(batch["mask"].sum())
            updates += 1
        self.iteration += 1
        metrics = self.env_runner_group.aggregate_metrics()
        metrics.update(learner_metrics)
        metrics.update(self.learner.sgd_throughput())
        metrics.update({
            "training_iteration": self.iteration,
            "num_env_steps_sampled_lifetime": self._total_env_steps,
            "num_learner_updates": self.learner.version,
            "num_weight_broadcasts": self._broadcast_count,
            "sample_queue_len": len(self._queue),
            "dropped_batches_lifetime": self._dropped_batches,
            "time_iteration_s": time.perf_counter() - t0,
        })
        return metrics

    def get_state(self) -> Dict[str, Any]:
        return {"params": jax.device_get(self.learner.params),
                "opt_state": jax.device_get(self.learner.opt_state),
                "iteration": self.iteration,
                "total_env_steps": self._total_env_steps}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.learner.params = jax.device_put(state["params"])
        self.learner.opt_state = jax.device_put(state["opt_state"])
        self.iteration = state.get("iteration", 0)
        self._total_env_steps = state.get("total_env_steps", 0)
        self.env_runner_group.sync_weights(self.learner.get_weights())

    def stop(self) -> None:
        self.env_runner_group.stop()


IMPALAConfig.algo_class = IMPALA
