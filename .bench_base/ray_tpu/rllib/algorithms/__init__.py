from ray_tpu.rllib.algorithms.appo import (APPO, APPOConfig,
                                            APPOLearner)
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig, QModule
from ray_tpu.rllib.algorithms.dreamerv3 import DreamerV3, DreamerV3Config
from ray_tpu.rllib.algorithms.impala import (IMPALA, IMPALAConfig,
                                             IMPALALearner,
                                             IMPALALearnerConfig,
                                             vtrace_returns)
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig, SACModule

__all__ = ["APPO", "APPOConfig", "APPOLearner",
           "PPO", "PPOConfig", "IMPALA", "IMPALAConfig", "IMPALALearner",
           "IMPALALearnerConfig", "vtrace_returns", "DQN", "DQNConfig",
           "QModule", "SAC", "SACConfig", "SACModule",
           "DreamerV3", "DreamerV3Config"]
