"""APPO: asynchronous PPO — IMPALA's pipeline with a clipped surrogate.

Parity: reference rllib/algorithms/appo/appo.py (+ appo_torch_learner):
async env runners feed a queued learner exactly as IMPALA does, but the
policy loss is PPO's clipped surrogate over V-trace advantages, with a
periodically-refreshed TARGET network providing the stable old-policy
for a KL regularizer (the reference's target_network_update_freq +
use_kl_loss path). Everything rides the IMPALA machinery here: same
runner group, sample queue, and single-jit update — only the loss and
the target-params state differ.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.impala import (IMPALA, IMPALAConfig,
                                             IMPALALearner,
                                             IMPALALearnerConfig,
                                             vtrace_returns)
from ray_tpu.rllib.core.rl_module import Categorical


@dataclasses.dataclass
class APPOConfig(IMPALAConfig):
    clip_eps: float = 0.2
    use_kl_loss: bool = True
    kl_coef: float = 0.2
    target_network_update_freq: int = 16    # learner updates per refresh


@dataclasses.dataclass
class APPOLearnerConfig(IMPALALearnerConfig):
    clip_eps: float = 0.2
    use_kl_loss: bool = True
    kl_coef: float = 0.2
    target_network_update_freq: int = 16


class APPOLearner(IMPALALearner):
    """V-trace advantages + clipped surrogate + target-network KL."""

    # (params, target_params, opt_state) precede the batch
    N_REPLICATED_ARGS = 3

    def __init__(self, config: APPOLearnerConfig):
        super().__init__(config)
        self.target_params = jax.tree_util.tree_map(jnp.copy,
                                                    self.params)

    def _build_update(self):
        c = self.config
        module = self.module

        def loss_fn(params, target_params, batch):
            logits, value = module.forward(params, batch["obs"])
            logits = logits[:-1]                       # (T, N, A)
            logp = Categorical.log_prob(logits, batch["actions"])
            vs, pg_adv, _rho = vtrace_returns(
                jax.lax.stop_gradient(value), batch["rewards"],
                batch["terminateds"], batch["dones"], batch["logp"],
                jax.lax.stop_gradient(logp), c.gamma,
                c.vtrace_rho_clip, c.vtrace_c_clip)
            m = batch["mask"]
            denom = jnp.maximum(jnp.sum(m), 1.0)
            # PPO clipped surrogate against the BEHAVIOUR policy's logp
            # (reference appo_torch_learner: ratio to the sampling
            # policy, advantages from v-trace)
            ratio = jnp.exp(logp - batch["logp"])
            adv = pg_adv
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - c.clip_eps, 1 + c.clip_eps) * adv)
            pg_loss = -jnp.sum(surr * m) / denom
            v_loss = 0.5 * jnp.sum(
                jnp.square(vs - value[:-1]) * m) / denom
            ent = jnp.sum(Categorical.entropy(logits) * m) / denom
            total = pg_loss + c.vf_coef * v_loss - c.ent_coef * ent
            kl = jnp.zeros(())
            if c.use_kl_loss:
                t_logits, _ = module.forward(target_params, batch["obs"])
                t_logits = jax.lax.stop_gradient(t_logits[:-1])
                t_logp_all = jax.nn.log_softmax(t_logits, axis=-1)
                logp_all = jax.nn.log_softmax(logits, axis=-1)
                kl_tn = jnp.sum(jnp.exp(t_logp_all)
                                * (t_logp_all - logp_all), axis=-1)
                kl = jnp.sum(kl_tn * m) / denom
                total = total + c.kl_coef * kl
            return total, {"policy_loss": pg_loss, "vf_loss": v_loss,
                           "entropy": ent, "kl_to_target": kl,
                           "mean_rho": jnp.sum(_rho * m) / denom}

        def update(params, target_params, opt_state, batch):
            (_, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, opt_state = self._tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, metrics

        return update

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        t0 = time.perf_counter()
        self.params, self.opt_state, metrics = self._update_fn(
            self.params, self.target_params, self.opt_state, batch)
        metrics = {k: float(v) for k, v in metrics.items()}
        dt = time.perf_counter() - t0
        self.version += 1
        self._timer["updates"] += 1
        self._timer["update_time"] += dt
        self._timer["transitions"] += int(np.prod(batch["rewards"].shape))
        if self.version % self.config.target_network_update_freq == 0:
            self.target_params = jax.tree_util.tree_map(jnp.copy,
                                                        self.params)
        metrics["update_time_s"] = dt
        return metrics


class APPO(IMPALA):
    """Asynchronous PPO on the IMPALA pipeline."""

    LEARNER_CLS = APPOLearner
    LEARNER_CONFIG_CLS = APPOLearnerConfig

    def get_state(self):
        state = super().get_state()
        # the KL target net is part of the learner state (restoring
        # without it would regularize toward a random network)
        state["target_params"] = jax.device_get(
            self.learner.target_params)
        return state

    def set_state(self, state) -> None:
        super().set_state(state)
        if "target_params" in state:
            self.learner.target_params = jax.device_put(
                state["target_params"])
        else:
            self.learner.target_params = jax.tree_util.tree_map(
                jnp.copy, self.learner.params)


APPOConfig.algo_class = APPO
