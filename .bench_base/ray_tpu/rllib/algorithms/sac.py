"""SAC: off-policy maximum-entropy actor-critic for continuous control.

Parity: reference rllib/algorithms/sac/sac.py (+ default_sac_rl_module /
sac_learner) — twin Q critics with target networks, squashed-Gaussian
policy, and entropy-coefficient autotuning toward a target entropy —
re-designed for this stack like DQN: flat-transition env runners feed a
replay buffer and ONE jitted update performs the critic, actor, and
alpha steps plus the polyak target update in a single XLA program.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm_config import AlgorithmConfig

from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer

_LOG_STD_MIN, _LOG_STD_MAX = -20.0, 2.0
_EPS = 1e-6


def _mlp_init(key, dims, out_scale=1.0):
    layers = []
    keys = jax.random.split(key, len(dims) - 1)
    for i, k in enumerate(keys):
        din, dout = dims[i], dims[i + 1]
        scale = out_scale if i == len(keys) - 1 else float(np.sqrt(2.0))
        w = jax.random.orthogonal(k, max(din, dout))[:din, :dout]
        layers.append({"w": (w * scale).astype(jnp.float32),
                       "b": jnp.zeros((dout,), jnp.float32)})
    return layers


def _mlp(layers, x, act=jnp.tanh):
    for layer in layers[:-1]:
        x = act(x @ layer["w"] + layer["b"])
    last = layers[-1]
    return x @ last["w"] + last["b"]


@dataclasses.dataclass(frozen=True)
class SACModule:
    """Squashed-Gaussian policy + twin Q critics (reference
    default_sac_rl_module.py)."""

    obs_dim: int
    act_dim: int
    hidden: Sequence[int] = (256, 256)

    def init(self, key: jax.Array) -> dict:
        kp, k1, k2 = jax.random.split(key, 3)
        h = list(self.hidden)
        return {
            "pi": _mlp_init(kp, [self.obs_dim] + h + [2 * self.act_dim],
                            out_scale=0.01),
            "q1": _mlp_init(k1, [self.obs_dim + self.act_dim] + h + [1]),
            "q2": _mlp_init(k2, [self.obs_dim + self.act_dim] + h + [1]),
        }

    # ------------------------------------------------------------ policy
    def pi_dist(self, params, obs):
        out = _mlp(params["pi"], obs)
        mean, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, _LOG_STD_MIN, _LOG_STD_MAX)
        return mean, log_std

    def sample_action(self, params, obs, key):
        """Reparameterized squashed sample -> (action in [-1,1], logp)."""
        mean, log_std = self.pi_dist(params, obs)
        std = jnp.exp(log_std)
        u = mean + std * jax.random.normal(key, mean.shape)
        a = jnp.tanh(u)
        logp_u = jnp.sum(
            -0.5 * jnp.square((u - mean) / std) - log_std
            - 0.5 * jnp.log(2 * jnp.pi), axis=-1)
        # tanh change of variables (SAC paper appendix C)
        logp = logp_u - jnp.sum(jnp.log(1 - jnp.square(a) + _EPS),
                                axis=-1)
        return a, logp

    # ------------------------------------------------------------ critic
    @staticmethod
    def q(params_q, obs, act):
        return _mlp(params_q, jnp.concatenate([obs, act], -1),
                    act=jax.nn.relu)[..., 0]


class SACEnvRunner:
    """Vectorized continuous sampler emitting flat transitions; actions
    are squashed-Gaussian samples scaled to the env bounds."""

    def __init__(self, config: "SACConfig", worker_index: int = 0):
        from ray_tpu._private.jaxenv import pin_platform_from_env
        pin_platform_from_env()
        import gymnasium as gym
        self.config = config
        seed = config.seed + 1000 * worker_index
        self._envs = gym.make_vec(config.env,
                                  num_envs=config.num_envs_per_env_runner,
                                  vectorization_mode="sync")
        space = self._envs.single_action_space
        if hasattr(space, "n"):
            raise ValueError("SAC needs a continuous (Box) action space")
        self._low = np.asarray(space.low, np.float32)
        self._high = np.asarray(space.high, np.float32)
        self.module = SACModule(
            int(np.prod(self._envs.single_observation_space.shape)),
            int(np.prod(space.shape)), tuple(config.hidden))
        self.params = jax.tree_util.tree_map(
            np.asarray, self.module.init(jax.random.PRNGKey(seed)))
        self._rng = np.random.default_rng(seed + 1)
        self._obs, _ = self._envs.reset(seed=seed)
        self._prev_done = np.zeros(config.num_envs_per_env_runner, bool)
        self._steps = 0
        self._ep_ret = np.zeros(config.num_envs_per_env_runner)
        self._recent: list = []

    def ping(self):
        return "pong"

    def set_weights(self, weights) -> None:
        self.params = jax.tree_util.tree_map(np.asarray, weights)

    def _policy_np(self, obs):
        x = obs
        for layer in self.params["pi"][:-1]:
            x = np.tanh(x @ layer["w"] + layer["b"])
        last = self.params["pi"][-1]
        out = x @ last["w"] + last["b"]
        mean, log_std = np.split(out, 2, axis=-1)
        return mean, np.clip(log_std, _LOG_STD_MIN, _LOG_STD_MAX)

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        c = self.config
        rows = {k: [] for k in ("obs", "actions", "rewards", "new_obs",
                                "terminateds")}
        N = c.num_envs_per_env_runner
        for _ in range(num_steps):
            obs32 = self._obs.astype(np.float32)
            if self._steps < c.random_steps:
                a = self._rng.uniform(-1.0, 1.0,
                                      (N, self.module.act_dim))
            else:
                mean, log_std = self._policy_np(obs32)
                u = mean + np.exp(log_std) * self._rng.standard_normal(
                    mean.shape)
                a = np.tanh(u)
            env_a = (self._low + (a.astype(np.float32) + 1.0)
                     * 0.5 * (self._high - self._low))
            nobs, reward, term, trunc, _ = self._envs.step(env_a)
            done = term | trunc
            valid = ~self._prev_done       # autoreset filler: drop
            rows["obs"].append(obs32[valid])
            rows["actions"].append(a[valid].astype(np.float32))
            rows["rewards"].append(reward[valid].astype(np.float32))
            rows["new_obs"].append(nobs[valid].astype(np.float32))
            rows["terminateds"].append(term[valid].astype(np.float32))
            self._ep_ret[valid] += reward[valid]
            for i in np.nonzero(done & valid)[0]:
                self._recent.append(float(self._ep_ret[i]))
                self._ep_ret[i] = 0.0
            self._recent = self._recent[-100:]
            self._prev_done = done
            self._obs = nobs
            self._steps += N
        return {k: np.concatenate(v) for k, v in rows.items()}

    def get_metrics(self) -> Dict[str, Any]:
        return {"episode_return_mean": (float(np.mean(self._recent))
                                        if self._recent else float("nan")),
                "num_episodes": len(self._recent),
                "num_env_steps_sampled": self._steps}

    def stop(self) -> None:
        self._envs.close()


@dataclasses.dataclass
class SACConfig(AlgorithmConfig):
    env: str = "Pendulum-v1"
    num_env_runners: int = 0               # 0 = local
    num_envs_per_env_runner: int = 8
    rollout_steps_per_iteration: int = 32
    hidden: Sequence[int] = (256, 256)
    actor_lr: float = 3e-4
    critic_lr: float = 3e-4
    alpha_lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.005                     # polyak target rate
    initial_alpha: float = 0.2
    target_entropy: float | None = None    # default: -act_dim
    buffer_size: int = 100_000
    train_batch_size: int = 256
    num_updates_per_iteration: int = 256
    learning_starts: int = 1_000           # env steps before updates
    random_steps: int = 1_000              # uniform exploration warmup
    seed: int = 0

class SAC:
    """Iterative trainer: sample -> buffer -> k SAC updates (critic +
    actor + alpha + polyak in one jit)."""

    def __init__(self, config: SACConfig):
        self.config = config
        c = config
        if c.num_env_runners == 0:
            self._runners = [SACEnvRunner(c)]
            self._remote = False
        else:
            import ray_tpu
            cls = ray_tpu.remote(num_cpus=1)(SACEnvRunner)
            self._runners = [cls.remote(c, worker_index=i + 1)
                             for i in range(c.num_env_runners)]
            self._remote = True
        obs_dim, act_dim = self._probe_dims()
        self.module = SACModule(obs_dim, act_dim, tuple(c.hidden))
        key = jax.random.PRNGKey(c.seed)
        key, init_key = jax.random.split(key)
        self._key = key
        self.params = self.module.init(init_key)
        self.target_q = {"q1": jax.tree_util.tree_map(
                             jnp.copy, self.params["q1"]),
                         "q2": jax.tree_util.tree_map(
                             jnp.copy, self.params["q2"])}
        self.log_alpha = jnp.asarray(
            np.log(c.initial_alpha), jnp.float32)
        self._target_entropy = (c.target_entropy
                                if c.target_entropy is not None
                                else -float(act_dim))
        self._actor_tx = optax.adam(c.actor_lr)
        self._critic_tx = optax.adam(c.critic_lr)
        self._alpha_tx = optax.adam(c.alpha_lr)
        self._actor_opt = self._actor_tx.init(self.params["pi"])
        self._critic_opt = self._critic_tx.init(
            {"q1": self.params["q1"], "q2": self.params["q2"]})
        self._alpha_opt = self._alpha_tx.init(self.log_alpha)
        self.buffer = ReplayBuffer(c.buffer_size, seed=c.seed)
        self._update_fn = jax.jit(self._build_update())
        self._num_updates = 0
        self._total_steps = 0
        self.iteration = 0

    def _probe_dims(self) -> Tuple[int, int]:
        import gymnasium as gym
        env = gym.make(self.config.env)
        dims = (int(np.prod(env.observation_space.shape)),
                int(np.prod(env.action_space.shape)))
        env.close()
        return dims

    def _build_update(self):
        c = self.config
        module = self.module

        def critic_loss_fn(q_params, params, target_q, log_alpha,
                           batch, key):
            next_a, next_logp = module.sample_action(
                params, batch["new_obs"], key)
            tq = jnp.minimum(
                module.q(target_q["q1"], batch["new_obs"], next_a),
                module.q(target_q["q2"], batch["new_obs"], next_a))
            alpha = jnp.exp(log_alpha)
            y = batch["rewards"] + c.gamma * (1 - batch["terminateds"]) \
                * jax.lax.stop_gradient(tq - alpha * next_logp)
            y = jax.lax.stop_gradient(y)
            q1 = module.q(q_params["q1"], batch["obs"], batch["actions"])
            q2 = module.q(q_params["q2"], batch["obs"], batch["actions"])
            return (jnp.mean(jnp.square(q1 - y))
                    + jnp.mean(jnp.square(q2 - y)),
                    0.5 * (jnp.mean(q1) + jnp.mean(q2)))

        def actor_loss_fn(pi_params, params, log_alpha, batch, key):
            p = {**params, "pi": pi_params}
            a, logp = module.sample_action(p, batch["obs"], key)
            q = jnp.minimum(module.q(params["q1"], batch["obs"], a),
                            module.q(params["q2"], batch["obs"], a))
            alpha = jax.lax.stop_gradient(jnp.exp(log_alpha))
            return jnp.mean(alpha * logp - q), jnp.mean(logp)

        def update(params, target_q, log_alpha, opts, batch, key):
            actor_opt, critic_opt, alpha_opt = opts
            k1, k2 = jax.random.split(key)
            # --- critic step
            q_params = {"q1": params["q1"], "q2": params["q2"]}
            (closs, q_mean), cgrads = jax.value_and_grad(
                critic_loss_fn, has_aux=True)(
                    q_params, params, target_q, log_alpha, batch, k1)
            cupd, critic_opt = self._critic_tx.update(cgrads, critic_opt)
            q_params = optax.apply_updates(q_params, cupd)
            params = {**params, **q_params}
            # --- actor step (fresh critics)
            (aloss, logp_mean), agrads = jax.value_and_grad(
                actor_loss_fn, has_aux=True)(
                    params["pi"], params, log_alpha, batch, k2)
            aupd, actor_opt = self._actor_tx.update(agrads, actor_opt)
            params = {**params,
                      "pi": optax.apply_updates(params["pi"], aupd)}
            # --- alpha step (entropy autotune, reference sac_learner)
            alpha_grad = -(jax.lax.stop_gradient(logp_mean)
                           + self._target_entropy)
            alupd, alpha_opt = self._alpha_tx.update(alpha_grad,
                                                     alpha_opt)
            log_alpha = optax.apply_updates(log_alpha, alupd)
            # --- polyak target update
            target_q = jax.tree_util.tree_map(
                lambda t, p: (1 - c.tau) * t + c.tau * p,
                target_q, {"q1": params["q1"], "q2": params["q2"]})
            metrics = {"critic_loss": closs, "actor_loss": aloss,
                       "alpha": jnp.exp(log_alpha), "q_mean": q_mean,
                       "entropy": -logp_mean}
            return (params, target_q, log_alpha,
                    (actor_opt, critic_opt, alpha_opt), metrics)

        return update

    # --------------------------------------------------------------- api
    def train(self) -> Dict[str, Any]:
        import ray_tpu
        c = self.config
        t0 = time.perf_counter()
        weights = jax.device_get(self.params)
        if self._remote:
            ref = ray_tpu.put(weights)
            for r in self._runners:
                r.set_weights.remote(ref)
            batches = ray_tpu.get([
                r.sample.remote(c.rollout_steps_per_iteration)
                for r in self._runners])
        else:
            self._runners[0].set_weights(weights)
            batches = [self._runners[0].sample(
                c.rollout_steps_per_iteration)]
        for b in batches:
            self.buffer.add(b)
            self._total_steps += len(b["rewards"])

        metrics_j: Dict[str, Any] = {}
        if self._total_steps >= c.learning_starts:
            opts = (self._actor_opt, self._critic_opt, self._alpha_opt)
            for _ in range(c.num_updates_per_iteration):
                batch = self.buffer.sample(c.train_batch_size)
                dev = {k: jnp.asarray(v) for k, v in batch.items()
                       if k != "batch_indexes"}
                self._key, sub = jax.random.split(self._key)
                (self.params, self.target_q, self.log_alpha, opts,
                 metrics_j) = self._update_fn(
                     self.params, self.target_q, self.log_alpha, opts,
                     dev, sub)
                self._num_updates += 1
            self._actor_opt, self._critic_opt, self._alpha_opt = opts
        self.iteration += 1
        if self._remote:
            metrics = ray_tpu.get(self._runners[0].get_metrics.remote())
        else:
            metrics = self._runners[0].get_metrics()
        metrics.update({k: float(v) for k, v in metrics_j.items()})
        metrics.update({
            "training_iteration": self.iteration,
            "num_env_steps_sampled_lifetime": self._total_steps,
            "num_updates_lifetime": self._num_updates,
            "buffer_size": len(self.buffer),
            "time_iteration_s": time.perf_counter() - t0,
        })
        return metrics

    def get_state(self) -> Dict[str, Any]:
        return {"params": jax.device_get(self.params),
                "target_q": jax.device_get(self.target_q),
                "log_alpha": float(self.log_alpha),
                "iteration": self.iteration,
                "total_steps": self._total_steps}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = jax.device_put(state["params"])
        self.target_q = jax.device_put(state["target_q"])
        self.log_alpha = jnp.asarray(state["log_alpha"], jnp.float32)
        self.iteration = state.get("iteration", 0)
        self._total_steps = state.get("total_steps", 0)

    def stop(self) -> None:
        import ray_tpu
        for r in self._runners:
            try:
                if self._remote:
                    ray_tpu.kill(r)
                else:
                    r.stop()
            except BaseException:
                pass


SACConfig.algo_class = SAC
