"""Fault-tolerant manager for a fleet of ray_tpu actors.

Parity: reference rllib/utils/actor_manager.py (FaultTolerantActorManager
:198 — foreach_actor :396, foreach_actor_async :464,
fetch_ready_async_reqs :558, probe_unhealthy_actors :641). Small and
load-bearing: both the EnvRunnerGroup and the LearnerGroup drive their
actors through this, so individual actor deaths degrade throughput
instead of killing the algorithm.

Results come back as `RemoteCallResults`, a list of `CallResult`s that
either carry a value (`ok=True`) or the exception that felled the call.
Actors whose calls raise system errors (worker death) are marked
unhealthy and skipped until `probe_unhealthy_actors` restores them.
"""
from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import ray_tpu
from ray_tpu.exceptions import (ActorDiedError, ActorUnavailableError,
                                WorkerDiedError)

logger = logging.getLogger(__name__)

# Exception types that mean "the actor process is gone", as opposed to a
# user-code error that leaves the actor healthy. A get() timeout is NOT
# fatal: a slow-but-healthy actor (e.g. a long sample() under
# timeout_seconds) must keep its health, matching the reference manager.
_SYSTEM_ERRORS = (ActorDiedError, ActorUnavailableError, WorkerDiedError,
                  ConnectionError)


def _is_system_error(e: BaseException) -> bool:
    """Actor-death errors surface wrapped in TaskError at the get()
    site; classify by the CAUSE, not the wrapper (a user-code exception
    also arrives as a TaskError but leaves the actor healthy)."""
    from ray_tpu.exceptions import GetTimeoutError, TaskError
    if isinstance(e, GetTimeoutError):
        return False
    if isinstance(e, TaskError):
        cause = e.cause
        return cause is not None and isinstance(cause, _SYSTEM_ERRORS)
    return isinstance(e, _SYSTEM_ERRORS)


@dataclasses.dataclass
class CallResult:
    actor_id: int
    ok: bool
    value: Any = None
    error: Optional[BaseException] = None

    def get(self):
        if not self.ok:
            raise self.error
        return self.value


class RemoteCallResults(list):
    """List[CallResult] with convenience accessors."""

    def ignore_errors(self) -> List[CallResult]:
        return [r for r in self if r.ok]

    def values(self) -> List[Any]:
        return [r.value for r in self if r.ok]

    @property
    def num_errors(self) -> int:
        return sum(0 if r.ok else 1 for r in self)


@dataclasses.dataclass
class _ActorState:
    actor: Any
    healthy: bool = True
    num_restarts: int = 0


@dataclasses.dataclass
class _InflightReq:
    actor_id: int
    ref: Any
    tag: Optional[str]
    submitted_at: float


class FaultTolerantActorManager:
    """Sync/async RPC fan-out over actors with health tracking.

    `actor_factory`, when given, lets `probe_unhealthy_actors(restore=
    True)` replace dead actors wholesale (the TPU-era analogue of the
    reference's restart-under-same-handle flow: our runtime restarts
    actors via max_restarts; the factory path covers actors created
    without restarts or killed past their budget).
    """

    def __init__(self, actors: Optional[Sequence[Any]] = None,
                 max_remote_requests_in_flight_per_actor: int = 2,
                 actor_factory: Optional[Callable[[int], Any]] = None):
        self._states: Dict[int, _ActorState] = {}
        self._next_id = 0
        self._max_in_flight = max_remote_requests_in_flight_per_actor
        self._in_flight: List[_InflightReq] = []
        self._factory = actor_factory
        for a in actors or []:
            self.add_actor(a)

    # ----------------------------------------------------------- fleet
    def add_actor(self, actor: Any) -> int:
        aid = self._next_id
        self._next_id += 1
        self._states[aid] = _ActorState(actor)
        return aid

    def remove_actor(self, actor_id: int) -> Any:
        st = self._states.pop(actor_id)
        self._in_flight = [r for r in self._in_flight
                           if r.actor_id != actor_id]
        return st.actor

    @property
    def num_actors(self) -> int:
        return len(self._states)

    @property
    def num_healthy_actors(self) -> int:
        return sum(1 for s in self._states.values() if s.healthy)

    def healthy_actor_ids(self) -> List[int]:
        return [a for a, s in self._states.items() if s.healthy]

    def actors(self) -> Dict[int, Any]:
        return {a: s.actor for a, s in self._states.items()}

    def actor(self, actor_id: int) -> Any:
        return self._states[actor_id].actor

    # ------------------------------------------------------------ sync
    def foreach_actor(self, fn_or_name, *, args: Sequence = (),
                      kwargs: Optional[dict] = None,
                      healthy_only: bool = True,
                      remote_actor_ids: Optional[Sequence[int]] = None,
                      timeout_seconds: Optional[float] = None
                      ) -> RemoteCallResults:
        """Call `fn_or_name` on each actor and wait for all results.

        `fn_or_name` is either a method name (str) or a callable applied
        to the actor handle via its `apply` method when present, else
        called as `fn(actor_handle)` driver-side to build the ref.
        """
        ids = self._target_ids(healthy_only, remote_actor_ids)
        refs, ref_ids = [], []
        for aid in ids:
            ref = self._submit(aid, fn_or_name, args, kwargs or {})
            if ref is not None:
                refs.append(ref)
                ref_ids.append(aid)
        return self._collect(ref_ids, refs, timeout_seconds)

    # ----------------------------------------------------------- async
    def foreach_actor_async(self, fn_or_name, *, args: Sequence = (),
                            kwargs: Optional[dict] = None,
                            healthy_only: bool = True,
                            remote_actor_ids: Optional[Sequence[int]] = None,
                            tag: Optional[str] = None) -> int:
        """Fire-and-forget fan-out; results arrive via
        `fetch_ready_async_reqs`. Returns the number of calls actually
        submitted (actors at their in-flight cap are skipped — the
        reference does the same to provide backpressure)."""
        ids = self._target_ids(healthy_only, remote_actor_ids)
        n = 0
        for aid in ids:
            if self._in_flight_count(aid) >= self._max_in_flight:
                continue
            ref = self._submit(aid, fn_or_name, args, kwargs or {})
            if ref is not None:
                self._in_flight.append(
                    _InflightReq(aid, ref, tag, time.monotonic()))
                n += 1
        return n

    def fetch_ready_async_reqs(self, *, timeout_seconds: float = 0.0,
                               tags: Optional[Sequence[str]] = None
                               ) -> RemoteCallResults:
        """Collect whatever async results are ready right now."""
        pending = [r for r in self._in_flight
                   if tags is None or r.tag in tags]
        if not pending:
            return RemoteCallResults()
        ready, _ = ray_tpu.wait(
            [r.ref for r in pending], num_returns=len(pending),
            timeout=timeout_seconds)
        ready_ids = {r.object_id for r in ready}
        done = [r for r in pending if r.ref.object_id in ready_ids]
        results = RemoteCallResults()
        for req in done:
            self._in_flight.remove(req)
            try:
                results.append(CallResult(
                    req.actor_id, True, ray_tpu.get(req.ref, timeout=0.1)))
            except BaseException as e:
                if _is_system_error(e):
                    self._mark_unhealthy(req.actor_id, e)
                results.append(CallResult(req.actor_id, False, error=e))
        return results

    # ---------------------------------------------------------- health
    def probe_unhealthy_actors(self, timeout_seconds: float = 5.0,
                               mark_healthy: bool = True) -> List[int]:
        """Ping unhealthy actors; returns ids of those that came back.

        With an `actor_factory`, dead actors are replaced by fresh ones
        (the whole point: the group keeps its width)."""
        restored = []
        for aid, st in list(self._states.items()):
            if st.healthy:
                continue
            try:
                ray_tpu.get(st.actor.__rtpu_ping__.remote()
                            if hasattr(st.actor, "__rtpu_ping__")
                            else st.actor.ping.remote(),
                            timeout=timeout_seconds)
                if mark_healthy:
                    st.healthy = True
                restored.append(aid)
            except BaseException:
                if self._factory is not None:
                    try:
                        st.actor = self._factory(aid)
                        st.healthy = True
                        st.num_restarts += 1
                        restored.append(aid)
                    except BaseException as e:
                        logger.warning("factory failed for actor %s: %s",
                                       aid, e)
        return restored

    def clear(self) -> None:
        """Kill every managed actor and forget the fleet (reference
        manager's clear()). Groups call this from their stop()."""
        for st in self._states.values():
            try:
                ray_tpu.kill(st.actor)
            except BaseException:
                pass
        self._states.clear()
        self._in_flight.clear()

    def set_actor_state(self, actor_id: int, healthy: bool) -> None:
        self._states[actor_id].healthy = healthy

    def is_actor_healthy(self, actor_id: int) -> bool:
        return self._states[actor_id].healthy

    # -------------------------------------------------------- internal
    def _target_ids(self, healthy_only, remote_actor_ids) -> List[int]:
        ids = (list(remote_actor_ids) if remote_actor_ids is not None
               else list(self._states))
        if healthy_only:
            ids = [a for a in ids if self._states[a].healthy]
        return ids

    def _in_flight_count(self, actor_id: int) -> int:
        return sum(1 for r in self._in_flight if r.actor_id == actor_id)

    def num_in_flight(self, actor_id: Optional[int] = None,
                      tag: Optional[str] = None) -> int:
        """Outstanding async requests, filterable by actor and tag
        (drivers of perpetual-sampling loops use this to keep every
        actor saturated, e.g. IMPALA's pump)."""
        return sum(1 for r in self._in_flight
                   if (actor_id is None or r.actor_id == actor_id)
                   and (tag is None or r.tag == tag))

    def _submit(self, aid: int, fn_or_name, args, kwargs):
        actor = self._states[aid].actor
        try:
            if isinstance(fn_or_name, str):
                return getattr(actor, fn_or_name).remote(*args, **kwargs)
            if hasattr(actor, "apply"):
                return actor.apply.remote(fn_or_name, *args, **kwargs)
            return fn_or_name(actor, *args, **kwargs)
        except BaseException as e:
            if not _is_system_error(e):
                raise
            self._mark_unhealthy(aid, e)
            return None

    def _collect(self, ref_ids, refs, timeout) -> RemoteCallResults:
        results = RemoteCallResults()
        for aid, ref in zip(ref_ids, refs):
            try:
                results.append(CallResult(
                    aid, True, ray_tpu.get(ref, timeout=timeout)))
            except BaseException as e:
                if _is_system_error(e):
                    self._mark_unhealthy(aid, e)
                results.append(CallResult(aid, False, error=e))
        return results

    def _mark_unhealthy(self, aid: int, err: BaseException) -> None:
        if self._states[aid].healthy:
            logger.warning("actor %s marked unhealthy: %r", aid, err)
        self._states[aid].healthy = False
