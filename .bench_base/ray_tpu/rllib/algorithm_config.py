"""Unified AlgorithmConfig: one builder surface for every algorithm.

Parity: reference rllib/algorithms/algorithm_config.py — a single
config class whose fluent groups (`.environment() .env_runners()
.training() .resources() .evaluation() .debugging()`) configure any
algorithm, with unknown options rejected instead of silently ignored,
plus `.to_dict() / .copy() / .build()`. Per-algorithm configs
(PPOConfig, DQNConfig, ...) are dataclasses that inherit this base:
their FIELDS define the option vocabulary, the base supplies the
builder machinery, and ``algo_class`` (assigned next to each
algorithm class) makes ``.build()`` uniform.
"""
from __future__ import annotations

import copy as _copy
from typing import Any, Dict, Optional


class AlgorithmConfig:
    """Fluent builder base shared by all algorithm configs."""

    #: the algorithm class `.build()` instantiates (assigned by each
    #: algorithm module next to the class definition)
    algo_class: Optional[type] = None

    # ------------------------------------------------------- builders
    def _apply(self, kw: Dict[str, Any], group: str) -> "AlgorithmConfig":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(
                    f"unknown {type(self).__name__}.{group}() option "
                    f"{k!r}; valid fields: "
                    f"{sorted(vars(self))}")
            setattr(self, k, v)
        return self

    def environment(self, env: Optional[str] = None,
                    **kw) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        return self._apply(kw, "environment")

    def env_runners(self, **kw) -> "AlgorithmConfig":
        return self._apply(kw, "env_runners")

    def training(self, **kw) -> "AlgorithmConfig":
        return self._apply(kw, "training")

    def resources(self, **kw) -> "AlgorithmConfig":
        return self._apply(kw, "resources")

    def evaluation(self, **kw) -> "AlgorithmConfig":
        return self._apply(kw, "evaluation")

    def debugging(self, *, seed: Optional[int] = None,
                  **kw) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self._apply(kw, "debugging")

    # ------------------------------------------------------ lifecycle
    def to_dict(self) -> Dict[str, Any]:
        return dict(vars(self))

    def copy(self) -> "AlgorithmConfig":
        return _copy.deepcopy(self)

    def build(self):
        if self.algo_class is None:
            raise NotImplementedError(
                f"{type(self).__name__} has no algo_class bound")
        return self.algo_class(self)
