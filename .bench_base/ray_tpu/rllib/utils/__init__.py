from ray_tpu.rllib.utils.replay_buffers import (PrioritizedReplayBuffer,
                                                ReplayBuffer)
from ray_tpu.rllib.utils.schedules import (ConstantSchedule,
                                           LinearSchedule,
                                           PiecewiseSchedule)

__all__ = ["ReplayBuffer", "PrioritizedReplayBuffer", "ConstantSchedule",
           "LinearSchedule", "PiecewiseSchedule"]
