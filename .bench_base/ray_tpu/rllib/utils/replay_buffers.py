"""Replay buffers: uniform ring + proportional prioritized.

Parity: reference rllib/utils/replay_buffers/ (ReplayBuffer,
PrioritizedReplayBuffer with sum-tree proportional sampling +
importance weights). Storage is columnar numpy (transitions as dicts of
arrays), so sampled batches feed jitted updates without a format hop —
the buffer lives host-side, the learner's batch lands on device via
device_put exactly like the data pipeline's batches.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

Batch = Dict[str, np.ndarray]


class ReplayBuffer:
    """Uniform FIFO ring buffer over transition rows."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = int(capacity)
        self._cols: Optional[Dict[str, np.ndarray]] = None
        self._size = 0
        self._next = 0
        self._rng = np.random.default_rng(seed)
        self._added = 0

    def __len__(self) -> int:
        return self._size

    def _ensure(self, batch: Batch) -> None:
        if self._cols is None:
            self._cols = {
                k: np.empty((self.capacity,) + v.shape[1:], v.dtype)
                for k, v in batch.items()}

    def add(self, batch: Batch) -> np.ndarray:
        """Add rows (dict of (n, ...) arrays); returns their slots."""
        n = len(next(iter(batch.values())))
        self._ensure(batch)
        idx = (self._next + np.arange(n)) % self.capacity
        for k, col in self._cols.items():
            col[idx] = batch[k]
        self._next = int((self._next + n) % self.capacity)
        self._size = min(self.capacity, self._size + n)
        self._added += n
        return idx

    def sample(self, batch_size: int) -> Batch:
        if self._size == 0:
            raise ValueError("cannot sample an empty buffer")
        idx = self._rng.integers(0, self._size, batch_size)
        return {k: col[idx] for k, col in self._cols.items()}

    def stats(self) -> Dict[str, int]:
        return {"size": self._size, "capacity": self.capacity,
                "added_lifetime": self._added}


class _SumTree:
    """Flat-array binary sum tree for O(log n) prefix sampling."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        size = 1
        while size < capacity:
            size *= 2
        self._leaf0 = size
        self._tree = np.zeros(2 * size, np.float64)

    def set(self, idx: np.ndarray, values: np.ndarray) -> None:
        pos = np.asarray(idx) + self._leaf0
        self._tree[pos] = values
        pos //= 2
        # bubble sums up; vectorised per level (duplicates collapse via
        # recompute from children rather than += races)
        while np.any(pos >= 1):
            pos = np.unique(pos[pos >= 1])
            self._tree[pos] = (self._tree[2 * pos]
                               + self._tree[2 * pos + 1])
            pos = pos // 2
            if pos.size and pos[0] == 0:
                break

    @property
    def total(self) -> float:
        return float(self._tree[1])

    def prefix_find(self, values: np.ndarray) -> np.ndarray:
        """For each v in [0, total), find the leaf whose cumulative range
        contains it."""
        pos = np.ones(len(values), np.int64)
        v = values.astype(np.float64).copy()
        while pos[0] < self._leaf0:
            left = 2 * pos
            left_sum = self._tree[left]
            go_right = v >= left_sum
            v = np.where(go_right, v - left_sum, v)
            pos = np.where(go_right, left + 1, left)
        return pos - self._leaf0


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritization: P(i) ∝ p_i^alpha, importance weights
    w_i = (N P(i))^-beta / max w (reference
    rllib/utils/replay_buffers/prioritized_replay_buffer.py)."""

    def __init__(self, capacity: int, alpha: float = 0.6,
                 beta: float = 0.4, eps: float = 1e-6, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha, self.beta, self.eps = alpha, beta, eps
        self._tree = _SumTree(capacity)
        self._max_priority = 1.0

    def add(self, batch: Batch,
            priorities: Optional[np.ndarray] = None) -> np.ndarray:
        idx = super().add(batch)
        if priorities is None:
            priorities = np.full(len(idx), self._max_priority)
        self._tree.set(idx, np.power(np.abs(priorities) + self.eps,
                                     self.alpha))
        return idx

    def sample(self, batch_size: int) -> Batch:
        if self._size == 0:
            raise ValueError("cannot sample an empty buffer")
        total = self._tree.total
        targets = self._rng.random(batch_size) * total
        idx = self._tree.prefix_find(targets)
        idx = np.minimum(idx, self._size - 1)
        probs = self._tree._tree[idx + self._tree._leaf0] / max(
            total, 1e-12)
        weights = np.power(self._size * np.maximum(probs, 1e-12),
                           -self.beta)
        weights = (weights / weights.max()).astype(np.float32)
        out = {k: col[idx] for k, col in self._cols.items()}
        out["weights"] = weights
        out["batch_indexes"] = idx.astype(np.int64)
        return out

    def update_priorities(self, idx: np.ndarray,
                          priorities: np.ndarray) -> None:
        self._max_priority = max(self._max_priority,
                                 float(np.max(np.abs(priorities))))
        self._tree.set(np.asarray(idx),
                       np.power(np.abs(priorities) + self.eps,
                                self.alpha))
