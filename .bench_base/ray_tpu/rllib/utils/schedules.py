"""Value schedules (reference rllib/utils/schedules/): epsilon decay,
lr warmup etc. All pure functions of the global timestep."""
from __future__ import annotations

from typing import List, Tuple


class ConstantSchedule:
    def __init__(self, value: float):
        self._v = value

    def value(self, t: int) -> float:
        return self._v

    __call__ = value


class LinearSchedule:
    """Linear interpolation from initial_p to final_p over
    schedule_timesteps, then flat."""

    def __init__(self, schedule_timesteps: int, final_p: float,
                 initial_p: float = 1.0):
        self.T = schedule_timesteps
        self.initial_p = initial_p
        self.final_p = final_p

    def value(self, t: int) -> float:
        frac = min(max(t, 0) / self.T, 1.0)
        return self.initial_p + frac * (self.final_p - self.initial_p)

    __call__ = value


class PiecewiseSchedule:
    """Linear interpolation between (t, value) endpoints; outside the
    range, clamps to the outermost values."""

    def __init__(self, endpoints: List[Tuple[int, float]]):
        if len(endpoints) < 2:
            raise ValueError("need >= 2 endpoints")
        self.endpoints = sorted(endpoints)

    def value(self, t: int) -> float:
        eps = self.endpoints
        if t <= eps[0][0]:
            return eps[0][1]
        if t >= eps[-1][0]:
            return eps[-1][1]
        for (t0, v0), (t1, v1) in zip(eps, eps[1:]):
            if t0 <= t < t1:
                frac = (t - t0) / (t1 - t0)
                return v0 + frac * (v1 - v0)
        return eps[-1][1]

    __call__ = value
