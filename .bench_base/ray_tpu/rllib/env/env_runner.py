"""Env runners: vectorized gymnasium sampling with a JAX policy.

Parity: reference rllib/env/single_agent_env_runner.py:63 (vector env
:86, sample :133) — on CPU, with the policy step jitted once and the
rollout returned as time-major numpy arrays ready for the learner's
single-jit PPO update. Handles gymnasium >=1.0 next-step autoreset by
masking the filler transition that follows each episode end.
"""
from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np

from ray_tpu.rllib.core.rl_module import ActorCriticModule


@dataclasses.dataclass
class EnvRunnerConfig:
    env: str = "CartPole-v1"
    # ConnectorV2 pipelines (rllib/connectors.py): obs transforms run
    # before policy inference (and are what gets STORED, so the learner
    # sees the same inputs); action transforms run before env.step.
    # None = defaults (identity obs; Box-bound clipping for actions).
    env_to_module: Optional[list] = None
    module_to_env: Optional[list] = None
    # Wide-and-short default (32x32 rather than the GPU-classic 8x128):
    # each rollout step costs one jitted-dispatch round-trip, so for
    # cheap CPU envs more parallel envs per step is strictly better.
    num_envs: int = 32
    rollout_length: int = 64
    hidden: Sequence[int] = (64, 64)
    seed: int = 0
    episode_metric_window: int = 100


class SingleAgentEnvRunner:
    """Owns a gym.vector env + policy params; `sample()` one rollout."""

    @staticmethod
    def _f32(obs: np.ndarray) -> np.ndarray:
        """Integer (pixel) observations are scaled to [0,1] HERE, in
        numpy, keyed on the raw env dtype — downstream buffers and
        modules only ever see pre-scaled float32 (the module's own
        dtype-keyed /255 covers direct uint8 callers only)."""
        if np.issubdtype(obs.dtype, np.integer):
            return obs.astype(np.float32) / 255.0
        return obs.astype(np.float32)

    def __init__(self, config: EnvRunnerConfig, worker_index: int = 0):
        from ray_tpu._private.jaxenv import pin_platform_from_env
        pin_platform_from_env()
        import gymnasium as gym

        self.config = config
        self.worker_index = worker_index
        seed = config.seed + 1000 * worker_index
        self._envs = gym.make_vec(
            config.env, num_envs=config.num_envs,
            vectorization_mode="sync")
        act_space = self._envs.single_action_space
        self._continuous = not hasattr(act_space, "n")
        if self._continuous:
            self._act_dim = int(np.prod(act_space.shape))
            self._act_low = np.asarray(act_space.low, np.float32)
            self._act_high = np.asarray(act_space.high, np.float32)
        self._rng = np.random.default_rng(seed + 1)
        self._obs, _ = self._envs.reset(seed=seed)
        self._prev_done = np.zeros(config.num_envs, bool)
        self._ep_return = np.zeros(config.num_envs, np.float64)
        self._ep_len = np.zeros(config.num_envs, np.int64)
        from ray_tpu.rllib.connectors import (ClipActions,
                                               ConnectorPipeline)
        self._env_to_module = ConnectorPipeline(config.env_to_module)
        self._module_to_env = ConnectorPipeline(
            config.module_to_env if config.module_to_env is not None
            else [ClipActions()])
        # probe the pipeline with the real initial obs (counts once in
        # stateful connectors and is reused as the first sample step):
        # the MODULE is sized from the TRANSFORMED obs, which connectors
        # may reshape (FlattenObs, frame stacking, ...)
        self._proc_obs = self._env_to_module(self._f32(self._obs), self)
        obs_dim = int(np.prod(self._proc_obs.shape[1:]))
        if self._continuous:
            self.module = ActorCriticModule(
                obs_dim, self._act_dim, tuple(config.hidden),
                continuous=True)
        else:
            self.module = ActorCriticModule(
                obs_dim, int(act_space.n), tuple(config.hidden))
        self.set_weights(self.module.init(jax.random.PRNGKey(seed)))
        self._recent_returns: deque = deque(
            maxlen=config.episode_metric_window)
        self._recent_lens: deque = deque(
            maxlen=config.episode_metric_window)
        self._total_steps = 0

    # ------------------------------------------------------------ rpc
    def ping(self) -> str:
        return "pong"

    def apply(self, fn, *args, **kwargs):
        return fn(self, *args, **kwargs)

    def get_weights(self):
        return self.params

    def set_weights(self, weights) -> None:
        # Stored as host numpy: sampling inference is numpy (see
        # ActorCriticModule.forward_policy_np for why).
        self.params = jax.tree_util.tree_map(np.asarray, weights)

    # --------------------------------------------------------- sample
    def sample(self, rollout_length: Optional[int] = None
               ) -> Dict[str, np.ndarray]:
        """Collect one time-major rollout batch.

        Returns obs (T+1, N, D) f32, actions (T, N) i32, logp/rewards/
        dones/mask (T, N) f32. mask is 0 on gymnasium next-step
        autoreset filler transitions (the env ignored our action and
        reset instead), which the learner excludes from GAE/losses.
        """
        T = rollout_length or self.config.rollout_length
        N = self.config.num_envs
        # each raw observation is transformed EXACTLY once: the rollout
        # boundary obs is cached so batch k's bootstrap row and batch
        # k+1's first row are the same array (stateful connectors like
        # NormalizeObs must not double-count it), and buffers take the
        # TRANSFORMED shape (connectors may reshape, e.g. FlattenObs).
        if self._proc_obs is None:
            self._proc_obs = self._env_to_module(self._f32(self._obs),
                                                 self)
        proc = self._proc_obs
        obs_buf = np.empty((T + 1, N) + proc.shape[1:], np.float32)
        act_buf = (np.empty((T, N, self._act_dim), np.float32)
                   if self._continuous else np.empty((T, N), np.int32))
        logp_buf = np.empty((T, N), np.float32)
        rew_buf = np.empty((T, N), np.float32)
        term_buf = np.empty((T, N), np.float32)
        done_buf = np.empty((T, N), np.float32)
        mask_buf = np.empty((T, N), np.float32)

        for t in range(T):
            obs_buf[t] = proc
            logits = self.module.forward_policy_np(self.params, proc)
            action, logp = self.module.sample_np(logits, self._rng,
                                                 self.params)
            # learner sees the RAW action (its logp is exact); the env
            # gets the connector-transformed one (clipping by default)
            env_action = self._module_to_env(action, self)
            nobs, reward, term, trunc, _ = self._envs.step(env_action)
            done = np.logical_or(term, trunc)
            act_buf[t] = action
            logp_buf[t] = np.asarray(logp)
            rew_buf[t] = reward
            # terminated zeroes the bootstrap; truncation does NOT — the
            # obs gymnasium returns at the truncating step is the true
            # final observation, so V(obs_{t+1}) is the right bootstrap.
            term_buf[t] = term.astype(np.float32)
            done_buf[t] = done.astype(np.float32)
            # Transition t is filler if the env was resetting (episode
            # ended at t-1): obs_buf[t] is the dead episode's final obs
            # and the env ignored action[t].
            mask_buf[t] = (~self._prev_done).astype(np.float32)
            valid = ~self._prev_done
            self._ep_return[valid] += reward[valid]
            self._ep_len[valid] += 1
            for i in np.nonzero(done & valid)[0]:
                self._recent_returns.append(float(self._ep_return[i]))
                self._recent_lens.append(int(self._ep_len[i]))
                self._ep_return[i] = 0.0
                self._ep_len[i] = 0
            self._prev_done = done
            self._obs = nobs
            proc = self._env_to_module(self._f32(nobs), self)
        obs_buf[T] = proc
        self._proc_obs = proc
        self._total_steps += int(mask_buf.sum())
        return {"obs": obs_buf, "actions": act_buf, "logp": logp_buf,
                "rewards": rew_buf, "terminateds": term_buf,
                "dones": done_buf, "mask": mask_buf}

    # -------------------------------------------------------- metrics
    def get_metrics(self) -> Dict[str, Any]:
        returns = list(self._recent_returns)
        return {
            "episode_return_mean": (float(np.mean(returns))
                                    if returns else float("nan")),
            "episode_len_mean": (float(np.mean(self._recent_lens))
                                 if self._recent_lens else float("nan")),
            "num_episodes": len(returns),
            "num_env_steps_sampled": self._total_steps,
        }

    def get_state(self) -> Dict[str, Any]:
        return {"weights": self.get_weights(),
                "connectors": {
                    "env_to_module": self._env_to_module.get_state(),
                    "module_to_env": self._module_to_env.get_state()}}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.set_weights(state["weights"])
        conn = state.get("connectors") or {}
        self._env_to_module.set_state(conn.get("env_to_module", {}))
        self._module_to_env.set_state(conn.get("module_to_env", {}))

    def stop(self) -> None:
        self._envs.close()
