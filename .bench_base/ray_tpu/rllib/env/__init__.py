from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup
from ray_tpu.rllib.env.multi_agent import (MultiAgentEnv,
                                           MultiAgentEnvRunner,
                                           MultiAgentPPO,
                                           MultiAgentPPOConfig,
                                           PolicySpec)

__all__ = ["SingleAgentEnvRunner", "EnvRunnerGroup", "MultiAgentEnv",
           "MultiAgentEnvRunner", "MultiAgentPPO", "MultiAgentPPOConfig",
           "PolicySpec"]
