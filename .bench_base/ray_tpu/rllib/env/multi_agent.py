"""Multi-agent environments, runner, and PPO trainer.

Parity: reference rllib/env/multi_agent_env.py (dict-keyed MultiAgentEnv
API with "__all__" termination), rllib/env/multi_agent_env_runner.py
(sampling with per-agent -> policy routing), and the multi-policy wiring
of MultiRLModule / policy_mapping_fn — re-designed for this stack:

- a MultiAgentEnv steps ALL live agents each tick with dict obs/action
  payloads (simultaneous-move subset: agents share the episode clock,
  which covers the reference's matrix-game / co-existing-agents tests);
- MultiAgentEnvRunner vectorizes E env copies, routes each (env, agent)
  column to its policy via policy_mapping_fn, and emits ONE time-major
  single-agent-format batch PER POLICY, so the unchanged jitted
  PPOLearner trains each policy;
- MultiAgentPPO runs one PPOLearner per policy over those batches.
"""
from __future__ import annotations

import dataclasses
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu.rllib.core.rl_module import ActorCriticModule


class MultiAgentEnv:
    """Dict-keyed environment (reference rllib/env/multi_agent_env.py).

    Subclasses define `agents` (ids stable for the episode), and
    reset/step with per-agent dicts; step's terminated/truncated dicts
    carry the special "__all__" key ending the episode for everyone.
    """

    agents: Sequence[str] = ()

    def reset(self, *, seed: Optional[int] = None
              ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        raise NotImplementedError

    def step(self, actions: Dict[str, Any]):
        """-> (obs, rewards, terminateds, truncateds, infos) dicts;
        terminateds/truncateds include "__all__"."""
        raise NotImplementedError

    def close(self) -> None:
        pass


@dataclasses.dataclass
class PolicySpec:
    """Per-policy module shape (reference PolicySpec)."""
    obs_dim: int
    num_actions: int
    continuous: bool = False
    hidden: Sequence[int] = (64, 64)


@dataclasses.dataclass
class MultiAgentEnvRunnerConfig:
    env_fn: Callable[[], MultiAgentEnv] = None
    policies: Dict[str, PolicySpec] = None
    policy_mapping_fn: Callable[[str], str] = None
    num_envs: int = 8
    rollout_length: int = 64
    seed: int = 0


class MultiAgentEnvRunner:
    """Vectorized multi-agent sampler: E env copies; each (env, agent)
    pair is one batch column of the agent's policy."""

    def __init__(self, config: MultiAgentEnvRunnerConfig,
                 worker_index: int = 0):
        from ray_tpu._private.jaxenv import pin_platform_from_env
        pin_platform_from_env()
        import jax
        self.config = config
        seed = config.seed + 1000 * worker_index
        self._envs: List[MultiAgentEnv] = [
            config.env_fn() for _ in range(config.num_envs)]
        self._agents = list(self._envs[0].agents)
        self.mapping = {a: config.policy_mapping_fn(a)
                        for a in self._agents}
        unknown = set(self.mapping.values()) - set(config.policies)
        if unknown:
            raise ValueError(f"policy_mapping_fn returned unknown "
                             f"policies {sorted(unknown)}")
        self.modules: Dict[str, ActorCriticModule] = {}
        self.params: Dict[str, Any] = {}
        for pid, spec in config.policies.items():
            self.modules[pid] = ActorCriticModule(
                spec.obs_dim, spec.num_actions, tuple(spec.hidden),
                continuous=spec.continuous)
            self.params[pid] = jax.tree_util.tree_map(
                np.asarray,
                self.modules[pid].init(jax.random.PRNGKey(
                    seed + zlib.crc32(pid.encode()) % 10_000)))
        # column layout per policy: [(env_idx, agent_id), ...]
        self.columns: Dict[str, List[Tuple[int, str]]] = {
            pid: [] for pid in config.policies}
        for e in range(config.num_envs):
            for a in self._agents:
                self.columns[self.mapping[a]].append((e, a))
        self._col_index = {
            pid: {col: i for i, col in enumerate(cols)}
            for pid, cols in self.columns.items()}
        self._rng = np.random.default_rng(seed + 1)
        self._obs: List[Dict[str, Any]] = []
        for i, env in enumerate(self._envs):
            obs, _ = env.reset(seed=seed + i)
            self._obs.append(obs)
        self._ep_ret = {(e, a): 0.0 for e in range(config.num_envs)
                        for a in self._agents}
        # an agent that terminated before "__all__" idles masked-out
        # until its env resets
        self._agent_done = {(e, a): False for e in range(config.num_envs)
                            for a in self._agents}
        self._recent: Dict[str, list] = {a: [] for a in self._agents}
        self._total_steps = 0

    def ping(self) -> str:
        return "pong"

    def set_weights(self, weights: Dict[str, Any]) -> None:
        import jax
        for pid, w in weights.items():
            self.params[pid] = jax.tree_util.tree_map(np.asarray, w)

    # ------------------------------------------------------------ sample
    def sample(self, rollout_length: Optional[int] = None
               ) -> Dict[str, Dict[str, np.ndarray]]:
        """-> {policy_id: single-agent-format time-major batch}."""
        T = rollout_length or self.config.rollout_length
        bufs: Dict[str, Dict[str, np.ndarray]] = {}
        for pid, cols in self.columns.items():
            spec = self.config.policies[pid]
            n = len(cols)
            bufs[pid] = {
                "obs": np.empty((T + 1, n, spec.obs_dim), np.float32),
                "actions": (np.empty((T, n, spec.num_actions), np.float32)
                            if spec.continuous
                            else np.empty((T, n), np.int32)),
                "logp": np.empty((T, n), np.float32),
                "rewards": np.zeros((T, n), np.float32),
                "terminateds": np.zeros((T, n), np.float32),
                "dones": np.zeros((T, n), np.float32),
                "mask": np.ones((T, n), np.float32),
            }

        def stack_obs(pid):
            cols = self.columns[pid]
            return np.stack([
                np.asarray(self._obs[e][a], np.float32).ravel()
                for e, a in cols])

        for t in range(T):
            actions_by_col: Dict[Tuple[int, str], Any] = {}
            for pid, cols in self.columns.items():
                obs = stack_obs(pid)
                bufs[pid]["obs"][t] = obs
                mod = self.modules[pid]
                logits = mod.forward_policy_np(self.params[pid], obs)
                action, logp = mod.sample_np(logits, self._rng,
                                             self.params[pid])
                bufs[pid]["actions"][t] = action
                bufs[pid]["logp"][t] = logp
                for ci, (e, a) in enumerate(cols):
                    actions_by_col[(e, a)] = action[ci]
            for e, env in enumerate(self._envs):
                acts = {a: actions_by_col[(e, a)] for a in self._agents}
                obs, rew, term, trunc, _ = env.step(acts)
                done_all = bool(term.get("__all__", False)
                                or trunc.get("__all__", False))
                for a in self._agents:
                    pid = self.mapping[a]
                    ci = self._col_index[pid][(e, a)]
                    was_done = self._agent_done[(e, a)]
                    r = float(rew.get(a, 0.0))
                    bufs[pid]["rewards"][t, ci] = r
                    term_a = bool(term.get(a, False)) or (
                        bool(term.get("__all__", False)))
                    trunc_a = bool(trunc.get(a, False)) or (
                        bool(trunc.get("__all__", False)))
                    bufs[pid]["terminateds"][t, ci] = float(term_a)
                    bufs[pid]["dones"][t, ci] = float(term_a or trunc_a)
                    if was_done:
                        # idle filler while peers finish: exclude from
                        # losses/GAE and from episode metrics
                        bufs[pid]["mask"][t, ci] = 0.0
                        continue
                    self._ep_ret[(e, a)] += r
                    if term_a or trunc_a:
                        self._recent[a].append(self._ep_ret[(e, a)])
                        self._recent[a] = self._recent[a][-100:]
                        self._ep_ret[(e, a)] = 0.0
                        self._agent_done[(e, a)] = True
                if done_all:
                    obs, _ = env.reset()
                    for a in self._agents:
                        self._agent_done[(e, a)] = False
                self._obs[e] = obs
            self._total_steps += len(self._envs)
        for pid in self.columns:
            bufs[pid]["obs"][T] = stack_obs(pid)
        return bufs

    def get_metrics(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"num_env_steps_sampled": self._total_steps}
        by_policy: Dict[str, list] = {}
        for a, rets in self._recent.items():
            by_policy.setdefault(self.mapping[a], []).extend(rets)
            out[f"episode_return_mean/{a}"] = (
                float(np.mean(rets)) if rets else float("nan"))
        for pid, rets in by_policy.items():
            out[f"episode_return_mean/policy/{pid}"] = (
                float(np.mean(rets)) if rets else float("nan"))
        return out

    def stop(self) -> None:
        for env in self._envs:
            env.close()


# ---------------------------------------------------------------- PPO
@dataclasses.dataclass
class MultiAgentPPOConfig:
    env_fn: Callable[[], MultiAgentEnv] = None
    policies: Dict[str, PolicySpec] = None
    policy_mapping_fn: Callable[[str], str] = None
    num_env_runners: int = 0             # 0 = local
    num_envs_per_env_runner: int = 8
    rollout_length: int = 64
    lr: float = 3e-4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    vf_coef: float = 0.5
    ent_coef: float = 0.01
    num_epochs: int = 4
    num_minibatches: int = 4
    seed: int = 0

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO:
    """One jitted PPOLearner per policy; runner fans samples per policy
    (reference Algorithm + MultiRLModule training path)."""

    def __init__(self, config: MultiAgentPPOConfig):
        from ray_tpu.rllib.core.learner import (PPOLearner,
                                                PPOLearnerConfig)
        self.config = config
        c = config
        runner_cfg = MultiAgentEnvRunnerConfig(
            env_fn=c.env_fn, policies=c.policies,
            policy_mapping_fn=c.policy_mapping_fn,
            num_envs=c.num_envs_per_env_runner,
            rollout_length=c.rollout_length, seed=c.seed)
        if c.num_env_runners == 0:
            self._runners = [MultiAgentEnvRunner(runner_cfg)]
            self._remote = False
        else:
            import ray_tpu
            cls = ray_tpu.remote(num_cpus=1)(MultiAgentEnvRunner)
            self._runners = [cls.remote(runner_cfg, worker_index=i + 1)
                             for i in range(c.num_env_runners)]
            self._remote = True
        self.learners: Dict[str, PPOLearner] = {}
        for pid, spec in c.policies.items():
            self.learners[pid] = PPOLearner(PPOLearnerConfig(
                obs_dim=spec.obs_dim, num_actions=spec.num_actions,
                hidden=tuple(spec.hidden), lr=c.lr, gamma=c.gamma,
                gae_lambda=c.gae_lambda, clip_eps=c.clip_eps,
                vf_coef=c.vf_coef, ent_coef=c.ent_coef,
                num_epochs=c.num_epochs,
                num_minibatches=c.num_minibatches,
                continuous=spec.continuous,
                seed=c.seed + zlib.crc32(pid.encode()) % 10_000))
        self.iteration = 0
        self._sync_weights()

    def _weights(self) -> Dict[str, Any]:
        return {pid: ln.get_weights()
                for pid, ln in self.learners.items()}

    def _sync_weights(self) -> None:
        w = self._weights()
        if self._remote:
            import ray_tpu
            ref = ray_tpu.put(w)
            for r in self._runners:
                r.set_weights.remote(ref)
        else:
            self._runners[0].set_weights(w)

    def train(self) -> Dict[str, Any]:
        import ray_tpu
        t0 = time.perf_counter()
        if self._remote:
            per_runner = ray_tpu.get(
                [r.sample.remote() for r in self._runners])
        else:
            per_runner = [self._runners[0].sample()]
        metrics: Dict[str, Any] = {}
        for pid in self.config.policies:
            batch = {k: np.concatenate([b[pid][k] for b in per_runner],
                                       axis=1)
                     for k in per_runner[0][pid]}
            lm = self.learners[pid].update(batch)
            metrics.update({f"{k}/policy/{pid}": v
                            for k, v in lm.items()})
        self._sync_weights()
        self.iteration += 1
        if self._remote:
            metrics.update(ray_tpu.get(
                self._runners[0].get_metrics.remote()))
        else:
            metrics.update(self._runners[0].get_metrics())
        metrics["training_iteration"] = self.iteration
        metrics["time_iteration_s"] = time.perf_counter() - t0
        return metrics

    def get_state(self) -> Dict[str, Any]:
        return {"learners": {pid: ln.get_state()
                             for pid, ln in self.learners.items()},
                "iteration": self.iteration}

    def set_state(self, state: Dict[str, Any]) -> None:
        for pid, st in state["learners"].items():
            self.learners[pid].set_state(st)
        self.iteration = state.get("iteration", 0)
        self._sync_weights()

    def stop(self) -> None:
        import ray_tpu
        for r in self._runners:
            try:
                if self._remote:
                    ray_tpu.kill(r)
                else:
                    r.stop()
            except BaseException:
                pass
