"""EnvRunnerGroup: a fleet of SingleAgentEnvRunner actors.

Parity: reference rllib/env/env_runner_group.py:70 (sampling fan-out
:185 via FaultTolerantActorManager). num_env_runners=0 keeps a single
local runner in-process (the reference's local-worker debug mode and
the right choice for cheap envs where actor RPC would dominate).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ray_tpu.rllib.env.env_runner import EnvRunnerConfig, SingleAgentEnvRunner


class EnvRunnerGroup:
    def __init__(self, config: EnvRunnerConfig, num_env_runners: int = 0,
                 num_cpus_per_runner: float = 1.0,
                 restart_failed_env_runners: bool = True):
        self.config = config
        self._local: Optional[SingleAgentEnvRunner] = None
        self._manager = None
        if num_env_runners == 0:
            self._local = SingleAgentEnvRunner(config, worker_index=0)
        else:
            import ray_tpu
            from ray_tpu.rllib.actor_manager import FaultTolerantActorManager

            remote_cls = ray_tpu.remote(num_cpus=num_cpus_per_runner)(
                SingleAgentEnvRunner)

            def factory(idx: int):
                return remote_cls.remote(config, worker_index=idx + 1)

            actors = [factory(i) for i in range(num_env_runners)]
            self._manager = FaultTolerantActorManager(
                actors,
                actor_factory=(factory if restart_failed_env_runners
                               else None))

    @property
    def num_healthy_env_runners(self) -> int:
        if self._local is not None:
            return 1
        return self._manager.num_healthy_actors

    @property
    def manager(self):
        return self._manager

    # -------------------------------------------------------- actions
    def sample(self) -> List[Dict[str, np.ndarray]]:
        """One rollout from every healthy runner (synchronous parallel
        sample, reference ppo.py:425 synchronous_parallel_sample)."""
        if self._local is not None:
            return [self._local.sample()]
        results = self._manager.foreach_actor("sample")
        batches = results.values()
        if not batches:
            raise RuntimeError("no healthy env runners produced samples")
        return batches

    def sync_weights(self, weights) -> None:
        if self._local is not None:
            self._local.set_weights(weights)
        else:
            import ray_tpu
            ref = ray_tpu.put(weights)   # ship once, fan out the ref
            self._manager.foreach_actor("set_weights", args=(ref,))

    def aggregate_metrics(self) -> Dict[str, float]:
        if self._local is not None:
            return self._local.get_metrics()
        per = self._manager.foreach_actor("get_metrics").values()
        if not per:
            return {}
        returns = [m["episode_return_mean"] for m in per
                   if m["num_episodes"] > 0]
        lens = [m["episode_len_mean"] for m in per
                if m["num_episodes"] > 0]
        return {
            "episode_return_mean": (float(np.mean(returns)) if returns
                                    else float("nan")),
            "episode_len_mean": (float(np.mean(lens)) if lens
                                 else float("nan")),
            "num_episodes": int(sum(m["num_episodes"] for m in per)),
            "num_env_steps_sampled": int(
                sum(m["num_env_steps_sampled"] for m in per)),
        }

    def probe_unhealthy_env_runners(self) -> List[int]:
        if self._manager is None:
            return []
        return self._manager.probe_unhealthy_actors()

    def stop(self) -> None:
        if self._local is not None:
            self._local.stop()
        elif self._manager is not None:
            import ray_tpu
            self._manager.foreach_actor("stop", timeout_seconds=5.0)
            for actor in self._manager.actors().values():
                try:
                    ray_tpu.kill(actor)
                except BaseException:
                    pass
