"""ConnectorV2: composable transform pipelines on the env↔module edges
AND the learner edge.

Parity: reference rllib/connectors (env_to_module/, module_to_env/,
learner/ — ConnectorV2 pieces composed into ConnectorPipelineV2).
Re-shaped for this stack:
- env-side connectors are callables `(data, runner) -> data` over numpy
  batches, running on the env-runner hot path (obs connectors before
  policy inference, action connectors before env.step);
- learner-side connectors are callables `(batch_dict, learner) ->
  batch_dict` over the full time-major training batch, running in the
  Learner BEFORE the jitted update (reference
  rllib/connectors/learner/general_advantage_estimation.py et al).

Built-ins mirror the reference's defaults: observation flattening,
running-stat normalization (the classic MeanStdFilter), observation
clipping, action clipping for Box spaces; learner-side GAE and
advantage standardization.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np


class Connector:
    """Base transform; subclass or wrap a function with FnConnector."""

    def __call__(self, data: np.ndarray, runner=None) -> np.ndarray:
        raise NotImplementedError

    def get_state(self) -> dict:
        return {}

    def set_state(self, state: dict) -> None:
        pass


class FnConnector(Connector):
    def __init__(self, fn: Callable[[np.ndarray], np.ndarray],
                 name: Optional[str] = None):
        self._fn = fn
        self.name = name or getattr(fn, "__name__", "fn")

    def __call__(self, data, runner=None):
        return self._fn(data)


class FlattenObs(Connector):
    """(N, *obs_shape) -> (N, prod(obs_shape))."""

    def __call__(self, data, runner=None):
        return np.asarray(data).reshape(len(data), -1)


class ClipObs(Connector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def __call__(self, data, runner=None):
        return np.clip(data, self.low, self.high)


class NormalizeObs(Connector):
    """Running mean/std filter (reference MeanStdFilter connector).
    Stats update online during sampling and ride get/set_state so
    restored runners keep their normalization."""

    def __init__(self, eps: float = 1e-8, update: bool = True):
        self.eps = eps
        self.update = update
        self._count = 0.0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def __call__(self, data, runner=None):
        batch = np.asarray(data, np.float64)
        if self._mean is None:
            self._mean = np.zeros(batch.shape[1:], np.float64)
            self._m2 = np.ones(batch.shape[1:], np.float64)
        if self.update and len(batch):
            # Chan's parallel Welford merge: one O(1)-numpy-call update
            # per batch (a per-row Python loop would sit on the sampling
            # hot path)
            n_b = float(len(batch))
            mean_b = batch.mean(axis=0)
            m2_b = ((batch - mean_b) ** 2).sum(axis=0)
            delta = mean_b - self._mean
            total = self._count + n_b
            self._mean = self._mean + delta * (n_b / total)
            self._m2 = (self._m2 + m2_b
                        + (delta ** 2) * (self._count * n_b / total))
            self._count = total
        var = (self._m2 / max(self._count, 1.0)) if self._count else \
            np.ones_like(self._mean)
        return ((batch - self._mean)
                / np.sqrt(var + self.eps)).astype(np.float32)

    def get_state(self) -> dict:
        return {"count": self._count,
                "mean": None if self._mean is None else self._mean.copy(),
                "m2": None if self._m2 is None else self._m2.copy()}

    def set_state(self, state: dict) -> None:
        self._count = state["count"]
        self._mean = state["mean"]
        self._m2 = state["m2"]


class ClipActions(Connector):
    """Clip continuous actions into the env's Box bounds."""

    def __call__(self, data, runner=None):
        if runner is not None and getattr(runner, "_continuous", False):
            return np.clip(data, runner._act_low, runner._act_high)
        return data


class ConnectorPipeline(Connector):
    """Ordered composition with the reference pipeline's edit API."""

    def __init__(self, connectors: Optional[List[Connector]] = None):
        self.connectors: List[Connector] = list(connectors or [])

    def __call__(self, data, runner=None):
        for c in self.connectors:
            data = c(data, runner)
        return data

    def append(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self

    def prepend(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.insert(0, connector)
        return self

    def insert_before(self, cls: type,
                      connector: Connector) -> "ConnectorPipeline":
        for i, c in enumerate(self.connectors):
            if isinstance(c, cls):
                self.connectors.insert(i, connector)
                return self
        raise ValueError(f"no connector of type {cls.__name__}")

    def insert_after(self, cls: type,
                     connector: Connector) -> "ConnectorPipeline":
        for i, c in enumerate(self.connectors):
            if isinstance(c, cls):
                self.connectors.insert(i + 1, connector)
                return self
        raise ValueError(f"no connector of type {cls.__name__}")

    def get_state(self) -> dict:
        return {i: c.get_state() for i, c in enumerate(self.connectors)}

    def set_state(self, state: dict) -> None:
        for i, c in enumerate(self.connectors):
            if i in state:
                c.set_state(state[i])


# ----------------------------------------------------------------------
# Learner connectors: batch-level transforms before the jitted update
# (reference rllib/connectors/learner/).
# ----------------------------------------------------------------------
class LearnerConnector:
    """Transforms the full time-major training batch dict. Receives the
    Learner so connectors can query the module (value predictions)."""

    def __call__(self, batch: dict, learner=None) -> dict:
        raise NotImplementedError

    def get_state(self) -> dict:
        return {}

    def set_state(self, state: dict) -> None:
        pass


class LearnerConnectorPipeline(LearnerConnector):
    """Ordered composition with the same edit API as the env-side
    pipeline."""

    def __init__(self, connectors=None):
        self.connectors: List[LearnerConnector] = list(connectors or [])

    def __call__(self, batch: dict, learner=None) -> dict:
        for c in self.connectors:
            batch = c(batch, learner)
        return batch

    def append(self, c):
        self.connectors.append(c)
        return self

    def prepend(self, c):
        self.connectors.insert(0, c)
        return self

    def get_state(self) -> dict:
        return {i: c.get_state() for i, c in enumerate(self.connectors)}

    def set_state(self, state: dict) -> None:
        for i, c in enumerate(self.connectors):
            if i in state:
                c.set_state(state[i])


class GeneralAdvantageEstimation(LearnerConnector):
    """GAE as a learner connector (reference rllib/connectors/learner/
    general_advantage_estimation.py): queries the learner module's
    value function, then adds ``advantages`` and ``value_targets`` to
    the batch. Semantics mirror the in-jit path: ``terminateds`` cuts
    the bootstrap, ``dones`` (incl. truncation) cuts only the advantage
    chain — truncation still bootstraps off V(final obs)."""

    def __init__(self, gamma: Optional[float] = None,
                 lambda_: Optional[float] = None):
        # None = inherit from the learner's config at call time, so the
        # connector can never silently diverge from the algorithm's
        # gamma/gae_lambda (the reference constructs this connector
        # FROM the algorithm config for the same reason)
        self.gamma = gamma
        self.lambda_ = lambda_

    def __call__(self, batch: dict, learner=None) -> dict:
        cfg = getattr(learner, "config", None)
        gamma = (self.gamma if self.gamma is not None
                 else getattr(cfg, "gamma", 0.99))
        lambda_ = (self.lambda_ if self.lambda_ is not None
                   else getattr(cfg, "gae_lambda", 0.95))
        values = learner.compute_values(batch["obs"])     # (T+1, N)
        rewards = np.asarray(batch["rewards"], np.float32)
        terms = np.asarray(batch["terminateds"], np.float32)
        dones = np.asarray(batch["dones"], np.float32)
        T = rewards.shape[0]
        adv = np.zeros_like(rewards)
        carry = np.zeros_like(rewards[0])
        for t in range(T - 1, -1, -1):
            delta = (rewards[t]
                     + gamma * values[t + 1] * (1.0 - terms[t])
                     - values[t])
            carry = (delta
                     + gamma * lambda_ * (1.0 - dones[t])
                     * carry)
            adv[t] = carry
        batch = dict(batch)
        batch["advantages"] = adv
        batch["value_targets"] = adv + values[:-1]
        return batch


class StandardizeAdvantages(LearnerConnector):
    """Zero-mean/unit-variance advantages over VALID transitions only
    (mask-aware), matching the in-jit normalization."""

    def __init__(self, eps: float = 1e-8):
        self.eps = eps

    def __call__(self, batch: dict, learner=None) -> dict:
        adv = np.asarray(batch["advantages"], np.float32)
        mask = np.asarray(batch.get("mask",
                                    np.ones_like(adv)), np.float32)
        denom = max(float(mask.sum()), 1.0)
        mu = float((adv * mask).sum()) / denom
        var = float((np.square(adv - mu) * mask).sum()) / denom
        batch = dict(batch)
        batch["advantages"] = ((adv - mu)
                               / np.sqrt(var + self.eps)).astype(
                                   np.float32)
        return batch
