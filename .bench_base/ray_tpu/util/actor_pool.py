"""ActorPool: load-balanced task fan-out over a fixed set of actors.

Parity: reference python/ray/util/actor_pool.py (ActorPool — map,
map_unordered, submit, get_next, get_next_unordered, has_next,
push/pop_idle). Submission past pool width queues host-side and
dispatches as actors free up (claimed results recycle their actor).
"""
from __future__ import annotations

from collections import deque
from typing import Any, Callable, Iterable, Iterator, List, Optional

import ray_tpu


class ActorPool:
    def __init__(self, actors: Iterable[Any]):
        self._idle: List[Any] = list(actors)
        if not self._idle:
            raise ValueError("ActorPool needs at least one actor")
        self._future_to_actor: dict[str, Any] = {}   # oid -> (actor, ref)
        self._index_to_future: dict[int, Any] = {}
        self._pending: deque = deque()               # (fn, value)
        self._claimed_early: dict[str, Any] = {}     # done, actor recycled
        self._next_task_index = 0
        self._next_return_index = 0

    # ------------------------------------------------------------- map
    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]) -> Iterator[Any]:
        """Ordered map: fn(actor, value) -> ObjectRef per value; results
        yielded in input order with pool-width parallelism."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # ---------------------------------------------------------- submit
    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        if self._idle:
            self._dispatch(fn, value)
        else:
            self._pending.append((fn, value))

    def _dispatch(self, fn, value) -> None:
        actor = self._idle.pop()
        ref = fn(actor, value)
        self._future_to_actor[ref.object_id] = (actor, ref)
        self._index_to_future[self._next_task_index] = ref
        self._next_task_index += 1

    def _recycle(self, actor) -> None:
        self._idle.append(actor)
        while self._pending and self._idle:
            fn, value = self._pending.popleft()
            self._dispatch(fn, value)

    # ----------------------------------------------------------- fetch
    def has_next(self) -> bool:
        return bool(self._future_to_actor or self._pending
                    or self._claimed_early)

    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in SUBMISSION order."""
        if not self.has_next():
            raise StopIteration("no pending results")
        while True:
            # slots claimed by get_next_unordered are gone: skip them
            while (self._next_return_index < self._next_task_index
                   and self._next_return_index
                   not in self._index_to_future):
                self._next_return_index += 1
            if self._next_return_index in self._index_to_future:
                break
            # next task not dispatched yet (queued behind busy actors):
            # drain one completion to free an actor
            self._drain_one(timeout)
        ref = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        value = ray_tpu.get(ref, timeout=timeout)
        entry = self._future_to_actor.pop(ref.object_id, None)
        if entry is not None:
            self._recycle(entry[0])
        else:
            self._claimed_early.pop(ref.object_id, None)
        return value

    def _drain_one(self, timeout: Optional[float]) -> None:
        refs = [ref for _, ref in self._future_to_actor.values()]
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no result within timeout")
        actor, ref = self._future_to_actor[ready[0].object_id]
        # don't claim the result; just free capacity for queued submits
        del self._future_to_actor[ref.object_id]
        self._claimed_early[ref.object_id] = ref
        self._recycle(actor)

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Next result in COMPLETION order."""
        if not self.has_next():
            raise StopIteration("no pending results")
        if self._claimed_early:
            oid, ref = next(iter(self._claimed_early.items()))
            del self._claimed_early[oid]
        else:
            refs = [ref for _, ref in self._future_to_actor.values()]
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=timeout)
            if not ready:
                raise TimeoutError("no result within timeout")
            ref = ready[0]
            actor, _ = self._future_to_actor.pop(ref.object_id)
            self._recycle(actor)
        # drop its ordered slot and advance past claimed gaps
        for idx, f in list(self._index_to_future.items()):
            if f.object_id == ref.object_id:
                del self._index_to_future[idx]
                break
        return ray_tpu.get(ref)

    # ------------------------------------------------------- idle mgmt
    def push(self, actor: Any) -> None:
        """Add an idle actor to the pool (reference push)."""
        self._recycle(actor)

    def pop_idle(self) -> Optional[Any]:
        return self._idle.pop() if self._idle else None

    @property
    def num_idle(self) -> int:
        return len(self._idle)

    @property
    def num_pending(self) -> int:
        return len(self._future_to_actor) + len(self._pending)
