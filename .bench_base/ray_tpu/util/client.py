"""Remote-driver connectivity (the Ray Client analogue).

Parity: reference python/ray/util/client/ (ray.init("ray://host:port")
proxying the full API over gRPC) — re-designed for this stack: the
head's listener already speaks a complete driver-equivalent protocol to
its workers (submit/get/put/wait/actor/kv/state ops), so a remote
client IS a WorkerContext over TCP: same wire messages, no proxy
server, no separate pickler. Usage::

    import ray_tpu
    ray_tpu.init(address="10.0.0.5:6379")   # head started with
                                            # bind_host="0.0.0.0"
    # full API: remote/get/put/wait/actors/PGs/kv/state
"""
from __future__ import annotations

import uuid
from typing import Optional

from ray_tpu._private import context as _context
from ray_tpu._private import protocol
from ray_tpu._private.worker_main import WorkerContext


class ClientContext(WorkerContext):
    """A driver living in another process/host, speaking the worker
    wire protocol to the head. `is_driver` stays False so function
    pickles ship inline with the first submission (the head's function
    store dedups by content hash)."""

    def __init__(self, conn: protocol.Connection, client_id: str,
                 address: str):
        super().__init__(conn, client_id)
        self.address = address

    def is_connected(self) -> bool:
        return not self.conn.closed

    def disconnect(self) -> None:
        try:
            self.conn.close()
        finally:
            if _context.maybe_ctx() is self:
                _context.set_ctx(None)


def connect(address: str) -> ClientContext:
    """Connect this process to a remote head as a driver. The head must
    listen on a reachable interface (init(bind_host=...) /
    RAY_TPU_BIND_HOST)."""
    existing = _context.maybe_ctx()
    if existing is not None:
        raise RuntimeError(
            "already initialized in this process; call shutdown()/"
            "disconnect() first")
    host, port = address.rsplit(":", 1)
    client_id = "client_" + uuid.uuid4().hex[:8]
    conn = protocol.connect((host, int(port)), lambda c, m: None,
                            name=f"client-{client_id}")
    ctx = ClientContext(conn, client_id, address)
    _context.set_ctx(ctx)
    return ctx


def disconnect() -> None:
    ctx = _context.maybe_ctx()
    if isinstance(ctx, ClientContext):
        ctx.disconnect()
