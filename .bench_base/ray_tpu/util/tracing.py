"""Tracing/profiling hooks (SURVEY §5.1).

Parity: reference util/tracing (opt-in opentelemetry wrapping) + the
nsight runtime-env plugin + `ray timeline`. The TPU-native profiler IS
jax.profiler (XLA/TPU traces viewable in TensorBoard/Perfetto); this
module gives it the framework spelling and keeps the task-level Chrome
trace next to it:

    with ray_tpu.util.tracing.profile("/tmp/tb"):   # device+host trace
        train_step(...)

    with ray_tpu.util.tracing.annotate("sample"):    # named span
        ...

    ray_tpu.util.tracing.task_timeline("out.json")   # task events
"""
from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def profile(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler trace (XLA ops, TPU activity, host) under
    `log_dir` for TensorBoard/XProf."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named span inside a profile() capture (TraceAnnotation); no-op
    cost when no trace is active."""
    import jax
    with jax.profiler.TraceAnnotation(name):
        yield


def annotate_fn(name: Optional[str] = None):
    """Decorator flavor of `annotate` (reference tracing_helper's
    function wrapping)."""
    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with annotate(name or fn.__qualname__):
                return fn(*args, **kwargs)
        return wrapped
    return deco


def task_timeline(filename: Optional[str] = None) -> list:
    """Chrome-trace of runtime task events (`ray timeline` parity);
    see util/metrics.timeline."""
    from ray_tpu.util.metrics import timeline
    return timeline(filename)
