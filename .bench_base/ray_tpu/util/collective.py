"""Host-side (CPU) collective group across actors/driver.

Parity: reference ray.util.collective (util/collective/collective.py —
init_collective_group:120, allreduce:258, broadcast:373, allgather:423,
reducescatter:472, send:531, recv:594) with its gloo CPU backend
(collective_group/gloo_collective_group.py). On TPU, ACCELERATOR
collectives belong to XLA over ICI (parallel/collectives.py); this
module is the control/host plane those collectives don't cover:
rendezvous, small-tensor CPU reductions, and p2p between actor
processes.

Transport: one named coordinator actor per group (its own process; all
participants rendezvous on the name), payloads ride the shm object
plane. Each participant keeps a local operation sequence number, so the
k-th collective call on every rank lands in the same round — the same
implicit-ordering contract gloo/NCCL groups rely on.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

_GROUPS: Dict[str, "_GroupHandle"] = {}
_DEFAULT_TIMEOUT_S = 60.0


class _Coordinator:
    """Rendezvous + reduction actor (one per group)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._cv = threading.Condition()
        self._rounds: Dict[Any, dict] = {}
        self._mail: Dict[Any, Any] = {}     # p2p mailbox

    def ping(self):
        return "pong"

    # ---------------------------------------------------- collectives
    def collect(self, key, rank: int, payload, kind: str, op: str,
                src_rank: int, timeout: float):
        with self._cv:
            rnd = self._rounds.setdefault(key, {"data": {}, "claimed": 0})
            if rank in rnd["data"]:
                raise RuntimeError(
                    f"rank {rank} contributed twice to round {key!r} — "
                    f"collective calls out of sync")
            rnd["data"][rank] = payload
            if len(rnd["data"]) == self.world_size:
                rnd["result"] = self._finish(rnd["data"], kind, op,
                                             src_rank)
                self._cv.notify_all()
            elif not self._cv.wait_for(lambda: "result" in rnd,
                                       timeout=timeout):
                # withdraw our contribution so a retry of this key isn't
                # poisoned ("contributed twice") and abandoned rounds
                # don't accumulate
                rnd["data"].pop(rank, None)
                if not rnd["data"]:
                    self._rounds.pop(key, None)
                raise TimeoutError(
                    f"collective round {key!r}: only "
                    f"{len(rnd['data']) + 1}/{self.world_size} ranks "
                    f"arrived within {timeout}s")
            result = rnd["result"]
            rnd["claimed"] += 1
            if rnd["claimed"] == self.world_size:
                del self._rounds[key]
        if kind == "reducescatter":
            return np.array_split(result, self.world_size)[rank]
        return result

    def _finish(self, data: Dict[int, Any], kind: str, op: str,
                src_rank: int):
        if kind == "broadcast":
            return data[src_rank]
        if kind == "allgather":
            return [data[r] for r in range(self.world_size)]
        if kind == "barrier":
            return True
        arrays = [np.asarray(data[r]) for r in range(self.world_size)]
        if op == "sum":
            out = arrays[0].copy()
            for a in arrays[1:]:
                out = out + a
        elif op == "max":
            out = np.maximum.reduce(arrays)
        elif op == "min":
            out = np.minimum.reduce(arrays)
        elif op == "prod":
            out = np.multiply.reduce(arrays)
        elif op == "mean":
            out = sum(arrays[1:], arrays[0].copy()) / len(arrays)
        else:
            raise ValueError(f"unknown reduce op {op!r}")
        return out

    # ------------------------------------------------------------ p2p
    def put_mail(self, key, payload) -> None:
        with self._cv:
            if key in self._mail:
                raise RuntimeError(f"duplicate send for {key!r}")
            self._mail[key] = payload
            self._cv.notify_all()

    def take_mail(self, key, timeout: float):
        with self._cv:
            if not self._cv.wait_for(lambda: key in self._mail,
                                     timeout=timeout):
                raise TimeoutError(f"recv {key!r}: no matching send "
                                   f"within {timeout}s")
            return self._mail.pop(key)


class _GroupHandle:
    def __init__(self, name: str, world_size: int, rank: int, actor):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.actor = actor
        self.seq = 0                      # per-rank op counter
        self.p2p_seq: Dict[tuple, int] = {}


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> None:
    """Join (rank of world_size) a named collective group. Every
    participant — driver or actor — calls this once before using the
    verbs below (reference collective.py:120)."""
    import time as _time

    import ray_tpu
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} outside world of {world_size}")
    if group_name in _GROUPS:
        raise RuntimeError(f"group {group_name!r} already initialized "
                           f"in this process")
    name = f"_rtpu_collective::{group_name}"
    # Rank 0 creates the coordinator; everyone else looks it up (retry —
    # a concurrent get_if_exists from every rank would race the
    # check-then-create window across processes).
    coord = None
    if rank == 0:
        coord = ray_tpu.remote(
            max_concurrency=max(2, world_size * 2))(_Coordinator).options(
            name=name, get_if_exists=True).remote(world_size)
    else:
        deadline = _time.time() + _DEFAULT_TIMEOUT_S
        while coord is None:
            try:
                coord = ray_tpu.get_actor(name)
            except ValueError:
                if _time.time() > deadline:
                    raise TimeoutError(
                        f"rank {rank}: coordinator for group "
                        f"{group_name!r} never appeared — did rank 0 "
                        f"call init_collective_group?")
                _time.sleep(0.1)
    ray_tpu.get(coord.ping.remote())      # rendezvous / liveness
    _GROUPS[group_name] = _GroupHandle(group_name, world_size, rank,
                                       coord)


def destroy_collective_group(group_name: str = "default") -> None:
    import ray_tpu
    h = _GROUPS.pop(group_name, None)
    if h is not None and h.rank == 0:
        try:
            ray_tpu.kill(h.actor)
        except BaseException:
            pass


def _group(group_name: str) -> _GroupHandle:
    h = _GROUPS.get(group_name)
    if h is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized in this "
            f"process; call init_collective_group first")
    return h


def _round(h: _GroupHandle, kind: str, payload, op: str = "sum",
           src_rank: int = 0, timeout: float = _DEFAULT_TIMEOUT_S):
    import ray_tpu
    key = (kind, h.seq)
    h.seq += 1
    return ray_tpu.get(
        h.actor.collect.remote(key, h.rank, payload, kind, op, src_rank,
                               timeout),
        timeout=timeout + 10.0)


# ------------------------------------------------------------- verbs
def allreduce(array, op: str = "sum", group_name: str = "default",
              timeout: float = _DEFAULT_TIMEOUT_S) -> np.ndarray:
    return _round(_group(group_name), "allreduce", np.asarray(array),
                  op=op, timeout=timeout)


def allgather(array, group_name: str = "default",
              timeout: float = _DEFAULT_TIMEOUT_S) -> List[np.ndarray]:
    return _round(_group(group_name), "allgather", np.asarray(array),
                  timeout=timeout)


def broadcast(array, src_rank: int = 0, group_name: str = "default",
              timeout: float = _DEFAULT_TIMEOUT_S) -> np.ndarray:
    return _round(_group(group_name), "broadcast", np.asarray(array),
                  src_rank=src_rank, timeout=timeout)


def reducescatter(array, op: str = "sum", group_name: str = "default",
                  timeout: float = _DEFAULT_TIMEOUT_S) -> np.ndarray:
    """Reduce across ranks, then return this rank's 1/world_size shard
    (split along axis 0, numpy array_split semantics)."""
    return _round(_group(group_name), "reducescatter", np.asarray(array),
                  op=op, timeout=timeout)


def barrier(group_name: str = "default",
            timeout: float = _DEFAULT_TIMEOUT_S) -> None:
    _round(_group(group_name), "barrier", None, timeout=timeout)


def send(array, dst_rank: int, group_name: str = "default",
         tag: int = 0) -> None:
    import ray_tpu
    h = _group(group_name)
    pk = (h.rank, dst_rank, tag)
    seq = h.p2p_seq.get(pk, 0)
    h.p2p_seq[pk] = seq + 1
    ray_tpu.get(h.actor.put_mail.remote((*pk, seq), np.asarray(array)))


def recv(src_rank: int, group_name: str = "default", tag: int = 0,
         timeout: float = _DEFAULT_TIMEOUT_S) -> np.ndarray:
    import ray_tpu
    h = _group(group_name)
    pk = (src_rank, h.rank, tag)
    seq = h.p2p_seq.get(pk, 0)
    h.p2p_seq[pk] = seq + 1
    return ray_tpu.get(
        h.actor.take_mail.remote((*pk, seq), timeout),
        timeout=timeout + 10.0)
