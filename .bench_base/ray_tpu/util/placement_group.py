"""Public placement-group API.

Parity: reference python/ray/util/placement_group.py (placement_group,
remove_placement_group, placement_group_table, PlacementGroup handle
with ready()/wait()) over the TPU-era 2-phase reserve/commit in
_private/cluster.py. STRICT_* groups that can never fit the cluster
raise PlacementGroupUnschedulableError immediately instead of pending
forever (VERDICT r1: options must not be silently ignored).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private import context as _context

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    """Handle to a created (or pending) placement group."""

    def __init__(self, pg_id: str, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self._bundles = [dict(b) for b in bundles]

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return [dict(b) for b in self._bundles]

    @property
    def bundle_count(self) -> int:
        return len(self._bundles)

    def ready(self):
        """ObjectRef that resolves when the PG is reserved (reference
        PlacementGroup.ready()); prefer `wait()` in new code."""
        import ray_tpu

        pg_id = self.id

        @ray_tpu.remote(num_cpus=0)
        def _pg_ready():
            return pg_id
        return _pg_ready.options(placement_group=self).remote()

    def wait(self, timeout_seconds: Optional[float] = 30.0) -> bool:
        rt = _context.get_ctx()
        return rt.cluster.wait_pg(self.id, timeout_seconds)

    def __repr__(self) -> str:
        return f"PlacementGroup(id={self.id}, bundles={self._bundles})"


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    """Reserve `bundles` across the cluster with `strategy`.

    Returns a handle immediately; reservation may still be pending (use
    `.wait()`). Raises PlacementGroupUnschedulableError when the demand
    exceeds what the cluster could EVER satisfy."""
    if strategy not in VALID_STRATEGIES:
        raise ValueError(
            f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}")
    rt = _context.get_ctx()
    rec = rt.cluster.create_pg(bundles, strategy, name=name)
    return PlacementGroup(rec.pg_id, rec.bundles)


def remove_placement_group(pg: PlacementGroup) -> None:
    rt = _context.get_ctx()
    rt.cluster.remove_pg(pg.id if isinstance(pg, PlacementGroup) else pg)


def placement_group_table(pg: Optional[PlacementGroup] = None):
    rt = _context.get_ctx()
    table = rt.cluster.pg_table()
    if pg is None:
        return {e["placement_group_id"]: e for e in table}
    for e in table:
        if e["placement_group_id"] == pg.id:
            return e
    return None


def get_placement_group(pg_id: str) -> Optional[PlacementGroup]:
    rt = _context.get_ctx()
    rec = rt.cluster.get_pg(pg_id)
    if rec is None:
        return None
    return PlacementGroup(rec.pg_id, rec.bundles)
