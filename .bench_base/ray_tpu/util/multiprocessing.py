"""multiprocessing.Pool API over ray_tpu actors.

Parity: reference python/ray/util/multiprocessing/pool.py — drop-in
`Pool` whose processes are cluster actors, so existing
multiprocessing code scales past one host unchanged::

    from ray_tpu.util.multiprocessing import Pool
    with Pool(processes=4, initializer=setup) as p:
        results = p.map(work, items)

Supports map/starmap/imap/imap_unordered/apply and their _async
variants, chunking, initializers, and context-manager lifecycle.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Callable, Iterable, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu._private.pickle_utils import dumps_by_value


class _PoolWorker:
    def __init__(self, initializer_bytes: Optional[bytes],
                 initargs: tuple):
        if initializer_bytes is not None:
            cloudpickle.loads(initializer_bytes)(*initargs)

    def run_chunk(self, fn_bytes: bytes, chunk: list, star: bool) -> list:
        fn = cloudpickle.loads(fn_bytes)
        if star:
            return [fn(*args) for args in chunk]
        return [fn(x) for x in chunk]

    def run_one(self, fn_bytes: bytes, args: tuple, kwargs: dict):
        return cloudpickle.loads(fn_bytes)(*args, **kwargs)


class AsyncResult:
    """multiprocessing.pool.AsyncResult-shaped handle."""

    def __init__(self, refs: List[Any], combine: Callable[[list], Any],
                 callback: Optional[Callable] = None,
                 error_callback: Optional[Callable] = None):
        self._refs = refs
        self._combine = combine
        self._value: Any = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()

        def waiter():
            try:
                self._value = combine(
                    [ray_tpu.get(r) for r in refs])
                if callback is not None:
                    callback(self._value)
            except BaseException as e:  # noqa: BLE001
                self._error = e
                if error_callback is not None:
                    error_callback(e)
            finally:
                self._done.set()

        threading.Thread(target=waiter, daemon=True,
                         name="pool-async-result").start()

    def ready(self) -> bool:
        return self._done.is_set()

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        return self._error is None

    def wait(self, timeout: Optional[float] = None) -> None:
        self._done.wait(timeout)

    def get(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("result not ready within timeout")
        if self._error is not None:
            raise self._error
        return self._value


class Pool:
    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = (),
                 ray_remote_args: Optional[dict] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        if processes is None:
            processes = max(
                1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        if processes < 1:
            raise ValueError("processes must be >= 1")
        self._size = processes
        init_bytes = (dumps_by_value(initializer)
                      if initializer is not None else None)
        Actor = ray_tpu.remote(**(ray_remote_args or {"num_cpus": 1}))(
            _PoolWorker)
        self._actors = [Actor.remote(init_bytes, tuple(initargs))
                        for _ in range(processes)]
        self._closed = False

    # ------------------------------------------------------------- map
    def _chunks(self, iterable: Iterable,
                chunksize: Optional[int]) -> List[list]:
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._size * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)], len(items)

    def _map_refs(self, fn, iterable, chunksize, star: bool):
        self._check_open()
        chunks, _n = self._chunks(iterable, chunksize)
        fn_bytes = dumps_by_value(fn)
        # round-robin: chunk k -> actor k % size (ordered actor queues
        # pipeline the backlog per actor)
        return [
            self._actors[i % self._size].run_chunk.remote(fn_bytes, c,
                                                          star)
            for i, c in enumerate(chunks)]

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> list:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable, chunksize=None, callback=None,
                  error_callback=None) -> AsyncResult:
        refs = self._map_refs(fn, iterable, chunksize, star=False)
        return AsyncResult(refs,
                           lambda parts: list(
                               itertools.chain.from_iterable(parts)),
                           callback, error_callback)

    def starmap(self, fn: Callable, iterable: Iterable,
                chunksize: Optional[int] = None) -> list:
        return self.starmap_async(fn, iterable, chunksize).get()

    def starmap_async(self, fn, iterable, chunksize=None, callback=None,
                      error_callback=None) -> AsyncResult:
        refs = self._map_refs(fn, iterable, chunksize, star=True)
        return AsyncResult(refs,
                           lambda parts: list(
                               itertools.chain.from_iterable(parts)),
                           callback, error_callback)

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: Optional[int] = None):
        refs = self._map_refs(fn, iterable, chunksize, star=False)
        for r in refs:
            yield from ray_tpu.get(r)

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: Optional[int] = None):
        refs = self._map_refs(fn, iterable, chunksize, star=False)
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            for r in ready:
                yield from ray_tpu.get(r)

    # ----------------------------------------------------------- apply
    def apply(self, fn: Callable, args: tuple = (),
              kwds: Optional[dict] = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: Optional[dict] = None, callback=None,
                    error_callback=None) -> AsyncResult:
        self._check_open()
        ref = self._actors[0].run_one.remote(
            dumps_by_value(fn), tuple(args), dict(kwds or {}))
        return AsyncResult([ref], lambda parts: parts[0], callback,
                           error_callback)

    # ------------------------------------------------------- lifecycle
    def _check_open(self) -> None:
        if self._closed:
            raise ValueError("Pool not running")

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except BaseException:
                pass
        self._actors = []

    def join(self) -> None:
        """Wait for in-flight work (close+join returns results like the
        stdlib contract), then release the actors."""
        if not self._closed:
            raise ValueError("Pool is still running")
        for a in self._actors:
            try:
                # ordered actor queues: a no-op completes only after
                # every previously submitted chunk
                ray_tpu.get(a.run_one.remote(
                    cloudpickle.dumps(lambda: None), (), {}))
            except BaseException:
                pass
        self.terminate()

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()
