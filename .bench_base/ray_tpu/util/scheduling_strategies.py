"""Scheduling strategies (reference python/ray/util/scheduling_strategies.py).

Consumed by api._apply_scheduling via duck-typed class names, so these
plain dataclasses are the full contract.
"""
from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: object
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclasses.dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str
    soft: bool = False


@dataclasses.dataclass
class SpreadSchedulingStrategy:
    """Best-effort spread across nodes (reference \"SPREAD\")."""


# ---- node-label scheduling (reference NodeLabelSchedulingStrategy +
# label match expressions, python/ray/util/scheduling_strategies.py) ----
class In:
    def __init__(self, *values: str):
        self.values = [str(v) for v in values]

    def spec(self) -> tuple:
        return ("in", self.values)


class NotIn:
    def __init__(self, *values: str):
        self.values = [str(v) for v in values]

    def spec(self) -> tuple:
        return ("not_in", self.values)


class Exists:
    def spec(self) -> tuple:
        return ("exists",)


class DoesNotExist:
    def spec(self) -> tuple:
        return ("absent",)


@dataclasses.dataclass
class NodeLabelSchedulingStrategy:
    """Schedule onto nodes by label: `hard` constraints filter candidate
    nodes; `soft` constraints express preference among the survivors.
    Values may be match operators (In/NotIn/Exists/DoesNotExist) or a
    plain string (sugar for In(value))."""
    hard: Optional[dict] = None
    soft: Optional[dict] = None

    def normalized(self) -> tuple:
        return (_normalize(self.hard), _normalize(self.soft))


def _normalize(constraints: Optional[dict]) -> dict:
    out = {}
    for key, op in (constraints or {}).items():
        if isinstance(op, str):
            op = In(op)
        if not hasattr(op, "spec"):
            raise ValueError(
                f"label constraint for {key!r} must be a string or one "
                f"of In/NotIn/Exists/DoesNotExist, got {op!r}")
        out[str(key)] = op.spec()
    return out


def labels_match(labels: dict, constraints: dict) -> bool:
    """Evaluate normalized constraints against a node's label dict."""
    for key, op in constraints.items():
        val = labels.get(key)
        kind = op[0]
        if kind == "in":
            if val is None or val not in op[1]:
                return False
        elif kind == "not_in":
            if val is not None and val in op[1]:
                return False
        elif kind == "exists":
            if val is None:
                return False
        elif kind == "absent":
            if val is not None:
                return False
        else:
            raise ValueError(f"unknown label operator {kind!r}")
    return True


DEFAULT = "DEFAULT"
