"""Public TPU pod-slice scheduling helpers.

Parity: reference python/ray/util/accelerators/tpu.py:7-29 plus the
pod-slice bundle recipe of _private/accelerators/tpu.py:334-397: a pod
slice schedules as one STRICT_SPREAD placement group with a per-host
bundle {TPU: chips_per_host, <pod_name>: 1}, the head bundle adding
{TPU-<gen>-head: 1}, giving "one actor per pod host, addressed as a
unit" — the SPMD-slice primitive the Train worker group rides on.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private.accelerators.tpu import (chips_per_host,
                                               head_resource_name,
                                               num_hosts)


def slice_bundles(accelerator_type: str,
                  pod_name: Optional[str] = None,
                  cpus_per_host: float = 1.0) -> List[Dict[str, float]]:
    """One bundle per slice host; bundle 0 carries the head resource."""
    hosts = num_hosts(accelerator_type)
    per_host = chips_per_host(accelerator_type)
    bundles: List[Dict[str, float]] = []
    for i in range(hosts):
        b: Dict[str, float] = {"CPU": cpus_per_host,
                               "TPU": float(per_host)}
        if pod_name:
            b[pod_name] = 1.0
        if i == 0:
            b[head_resource_name(accelerator_type)] = 1.0
        bundles.append(b)
    return bundles


def slice_placement_group(accelerator_type: str,
                          pod_name: Optional[str] = None,
                          cpus_per_host: float = 1.0):
    """Reserve a whole pod slice: STRICT_SPREAD so each bundle lands on
    a distinct host. Raises PlacementGroupUnschedulableError when the
    cluster cannot ever hold the slice."""
    from ray_tpu.util.placement_group import placement_group
    return placement_group(
        slice_bundles(accelerator_type, pod_name, cpus_per_host),
        strategy="STRICT_SPREAD",
        name=f"tpu_slice_{accelerator_type}")
