from ray_tpu.util.accelerators.tpu import (slice_bundles,
                                           slice_placement_group)

__all__ = ["slice_bundles", "slice_placement_group"]
