"""State API: list/get/summarize cluster entities, with filters.

Parity: reference python/ray/util/state/api.py (`ray list actors/tasks/
nodes/objects/placement-groups` with `--filter key=value`, `ray get`,
`ray summary tasks/actors/objects`) — served straight from the
controller tables; also exposed as a CLI:
``python -m ray_tpu.util.state list actors --filter state=ALIVE``.

Filters are (key, op, value) triples with ops ``=``, ``!=``, ``<``,
``<=``, ``>``, ``>=`` and ``contains`` (reference StateApiClient filter
predicates), applied to the listed records.
"""
from __future__ import annotations

import operator
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private import context as _context

Filter = Tuple[str, str, Any]

_OPS = {"=": operator.eq, "==": operator.eq, "!=": operator.ne,
        "<": operator.lt, "<=": operator.le, ">": operator.gt,
        ">=": operator.ge,
        "contains": lambda a, b: b in str(a)}


def _op(op: str, **kw) -> Any:
    return _context.get_ctx().state_op(op, **kw)


def _apply_filters(rows: List[Dict],
                   filters: Optional[Sequence[Filter]]) -> List[Dict]:
    if not filters:
        return rows
    preds = []
    for key, fop, value in filters:
        if fop not in _OPS:
            raise ValueError(f"unknown filter op {fop!r}; "
                             f"one of {sorted(_OPS)}")
        preds.append((key, _OPS[fop], value))
    out = []
    for r in rows:
        ok = True
        for key, fn, value in preds:
            have = r.get(key)
            try:
                # numeric filter values compare numerically even though
                # CLI-provided values arrive as strings
                if isinstance(have, (int, float)) and \
                        not isinstance(value, (int, float)):
                    value_c = type(have)(value)
                else:
                    value_c = value
                if not fn(have, value_c):
                    ok = False
                    break
            except (TypeError, ValueError):
                ok = False
                break
        if ok:
            out.append(r)
    return out


def list_actors(filters: Optional[Sequence[Filter]] = None) -> List[Dict]:
    return _apply_filters(_op("list_actors"), filters)


def list_tasks(filters: Optional[Sequence[Filter]] = None,
               limit: int = 1000) -> List[Dict]:
    return _apply_filters(_op("list_tasks", limit=limit), filters)


def list_nodes(filters: Optional[Sequence[Filter]] = None) -> List[Dict]:
    return _apply_filters(_op("list_nodes"), filters)


def list_placement_groups(
        filters: Optional[Sequence[Filter]] = None) -> List[Dict]:
    return _apply_filters(_op("list_placement_groups"), filters)


def list_workers(filters: Optional[Sequence[Filter]] = None) -> List[Dict]:
    """Worker-manager table: every pooled worker process across the
    cluster (reference `ray list workers` / GcsWorkerManager)."""
    return _apply_filters(_op("list_workers"), filters)


def usage_stats() -> Dict[str, Any]:
    """Cluster usage rollup: uptime, node/worker counts, task + actor
    state summaries, resources, object store (reference usage-stats
    aggregation, shaped for the dashboard)."""
    return _op("usage_stats")


def _get_by_id(rows: List[Dict], key: str, value: str) -> Optional[Dict]:
    for r in rows:
        if r.get(key) == value:
            return r
    return None


def get_actor(actor_id: str) -> Optional[Dict]:
    return _get_by_id(_op("list_actors"), "actor_id", actor_id)


def get_task(task_id: str) -> Optional[Dict]:
    return _get_by_id(_op("list_tasks", limit=100000), "task_id", task_id)


def get_node(node_id: str) -> Optional[Dict]:
    return _get_by_id(_op("list_nodes"), "node_id", node_id)


def get_placement_group(pg_id: str) -> Optional[Dict]:
    return _get_by_id(_op("list_placement_groups"), "id", pg_id) or \
        _get_by_id(_op("list_placement_groups"), "pg_id", pg_id)


def summarize_tasks() -> Dict[str, int]:
    return _op("summarize_tasks")


def summarize_actors() -> Dict[str, int]:
    """Actor count per state (reference `ray summary actors`)."""
    counts: Dict[str, int] = {}
    for a in _op("list_actors"):
        counts[a.get("state", "UNKNOWN")] = counts.get(
            a.get("state", "UNKNOWN"), 0) + 1
    return counts


def summarize_objects() -> Dict[str, Any]:
    """Object-store rollup (reference `ray summary objects`)."""
    return _op("object_store_stats")


def object_store_stats() -> Dict:
    return _op("object_store_stats")


def cluster_resources() -> Dict[str, float]:
    return _op("cluster_resources")


def available_resources() -> Dict[str, float]:
    return _op("available_resources")


_LISTERS = {
    "actors": list_actors,
    "tasks": list_tasks,
    "nodes": list_nodes,
    "placement-groups": list_placement_groups,
    "workers": list_workers,
}


def _main() -> None:     # pragma: no cover - thin CLI shim over the API
    import argparse
    import json

    parser = argparse.ArgumentParser(
        prog="ray_tpu.util.state",
        description="Inspect a ray_tpu runtime (from the driver process)")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_list = sub.add_parser("list")
    p_list.add_argument("entity", choices=sorted(_LISTERS))
    p_list.add_argument("--filter", action="append", default=[],
                        help="key=value / key!=value / key>=value / "
                             "'key contains value'")
    sub.add_parser("summary")
    sub.add_parser("resources")
    args = parser.parse_args()

    import ray_tpu
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    if args.cmd == "list":
        filters = []
        for f in args.filter:
            for op_tok in (" contains ", "!=", ">=", "<=", "=", ">",
                           "<"):
                if op_tok in f:
                    k, v = f.split(op_tok, 1)
                    filters.append((k.strip(), op_tok.strip(), v.strip()))
                    break
            else:
                raise SystemExit(f"bad --filter {f!r}")
        print(json.dumps(_LISTERS[args.entity](filters=filters or None),
                         indent=1, default=str))
    elif args.cmd == "summary":
        print(json.dumps(summarize_tasks(), indent=1))
    else:
        print(json.dumps({"total": cluster_resources(),
                          "available": available_resources()}, indent=1))


if __name__ == "__main__":
    _main()
