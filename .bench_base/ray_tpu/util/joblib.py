"""joblib parallel backend on ray_tpu tasks.

Parity: reference python/ray/util/joblib/ (register_ray + the
ray backend) — after `register_ray()`, scikit-learn / joblib code runs
its batches as cluster tasks::

    from ray_tpu.util.joblib import register_ray
    import joblib
    register_ray()
    with joblib.parallel_backend("ray_tpu"):
        results = joblib.Parallel()(joblib.delayed(f)(x) for x in xs)
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Optional

import cloudpickle

import ray_tpu
from ray_tpu._private.pickle_utils import dumps_by_value


class _JoblibFuture:
    """joblib expects apply_async to return something with get()."""

    def __init__(self, ref, callback: Optional[Callable]):
        self._ref = ref
        self._result: Any = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()

        def waiter():
            try:
                self._result = ray_tpu.get(ref)
                if callback is not None:
                    callback(self._result)
            except BaseException as e:  # noqa: BLE001
                self._error = e
            finally:
                self._done.set()

        threading.Thread(target=waiter, daemon=True,
                         name="joblib-future").start()

    def get(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("joblib task not done within timeout")
        if self._error is not None:
            raise self._error
        return self._result


def _run_batch(batch_bytes: bytes):
    return cloudpickle.loads(batch_bytes)()


def register_ray() -> None:
    """Register the 'ray_tpu' joblib parallel backend."""
    from joblib.parallel import (ParallelBackendBase,
                                 register_parallel_backend)

    class RayTpuBackend(ParallelBackendBase):
        supports_timeout = True
        # joblib >= 1.3 probes this to decide nesting behavior
        uses_threads = False
        supports_sharedmem = False

        def configure(self, n_jobs: int = 1, parallel=None,
                      **backend_args) -> int:
            if not ray_tpu.is_initialized():
                ray_tpu.init(ignore_reinit_error=True)
            self.parallel = parallel
            self._remote = ray_tpu.remote(num_cpus=1)(_run_batch)
            return self.effective_n_jobs(n_jobs)

        def effective_n_jobs(self, n_jobs: int) -> int:
            if n_jobs == 1 or n_jobs is None:
                return 1
            cpus = int(ray_tpu.cluster_resources().get("CPU", 1)) \
                if ray_tpu.is_initialized() else 1
            return cpus if n_jobs < 0 else min(n_jobs, max(cpus, 1))

        def apply_async(self, func, callback=None) -> _JoblibFuture:
            # func is joblib's BatchedCalls (library code); the USER
            # functions hide inside func.items — their modules must
            # ship by value for driver-only code
            inner = [call[0] for call in getattr(func, "items", [])]
            ref = self._remote.remote(
                dumps_by_value(func, roots=tuple(inner)))
            return _JoblibFuture(ref, callback)

        def abort_everything(self, ensure_ready: bool = True) -> None:
            pass

    register_parallel_backend("ray_tpu", RayTpuBackend)
