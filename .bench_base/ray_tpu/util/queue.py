"""Distributed FIFO queue backed by an actor.

Parity: reference python/ray/util/queue.py (Queue over an asyncio
_QueueActor: put/get with block+timeout, qsize/empty/full,
put_nowait/get_nowait, shutdown). Blocking semantics live inside the
actor via a threading.Condition + max_concurrency, so producers and
consumers in different processes coordinate without driver polling.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self._maxsize = maxsize
        self._q: deque = deque()
        self._cv = threading.Condition()

    def ping(self):
        return "pong"

    def qsize(self) -> int:
        with self._cv:
            return len(self._q)

    # NOTE on blocking: actor-side waits are capped at a SHORT slice
    # (clients loop until their own deadline). An unbounded wait would
    # park one of the actor's max_concurrency threads per blocked
    # producer/consumer — enough blocked producers would starve every
    # consumer RPC and deadlock the queue.
    _SLICE_S = 0.2

    def put(self, item: Any, block: bool, timeout: Optional[float]) -> bool:
        with self._cv:
            if self._maxsize > 0 and len(self._q) >= self._maxsize:
                if not block:
                    return False
                slice_s = self._SLICE_S if timeout is None else min(
                    self._SLICE_S, timeout)
                if not self._cv.wait_for(
                        lambda: len(self._q) < self._maxsize,
                        timeout=slice_s):
                    return False
            self._q.append(item)
            self._cv.notify_all()
            return True

    def get(self, block: bool, timeout: Optional[float]):
        with self._cv:
            if not self._q:
                if not block:
                    return False, None
                slice_s = self._SLICE_S if timeout is None else min(
                    self._SLICE_S, timeout)
                if not self._cv.wait_for(lambda: bool(self._q),
                                         timeout=slice_s):
                    return False, None
            item = self._q.popleft()
            self._cv.notify_all()
            return True, item

    def get_batch(self, max_items: int):
        with self._cv:
            out = []
            while self._q and len(out) < max_items:
                out.append(self._q.popleft())
            self._cv.notify_all()
            return out


class Queue:
    """Cross-process FIFO; share the Queue object with tasks/actors
    (it pickles as a handle to the same queue actor)."""

    def __init__(self, maxsize: int = 0,
                 actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("max_concurrency", 8)
        self._actor = ray_tpu.remote(**opts)(_QueueActor).remote(maxsize)
        ray_tpu.get(self._actor.ping.remote())
        self._maxsize = maxsize

    # picklable: workers reconstruct around the same actor handle
    def __reduce__(self):
        q = object.__new__(Queue)
        return (_rebuild_queue, (self._actor, self._maxsize))

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        import time
        deadline = None if timeout is None else time.time() + timeout
        while True:
            left = None if deadline is None else deadline - time.time()
            ok = ray_tpu.get(self._actor.put.remote(item, block, left))
            if ok:
                return
            if not block or (deadline is not None
                             and time.time() >= deadline):
                raise Full("queue full")

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        import time
        deadline = None if timeout is None else time.time() + timeout
        while True:
            left = None if deadline is None else deadline - time.time()
            ok, item = ray_tpu.get(self._actor.get.remote(block, left))
            if ok:
                return item
            if not block or (deadline is not None
                             and time.time() >= deadline):
                raise Empty("queue empty")

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_nowait_batch(self, max_items: int) -> List[Any]:
        return ray_tpu.get(self._actor.get_batch.remote(max_items))

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self._maxsize > 0 and self.qsize() >= self._maxsize

    def shutdown(self) -> None:
        try:
            ray_tpu.kill(self._actor)
        except BaseException:
            pass


def _rebuild_queue(actor, maxsize):
    q = object.__new__(Queue)
    q._actor = actor
    q._maxsize = maxsize
    return q
