"""Exception hierarchy for ray_tpu.

Parity map (reference: python/ray/exceptions.py): RayError -> RayTpuError,
RayTaskError -> TaskError, RayActorError -> ActorError, GetTimeoutError kept,
ObjectLostError kept, WorkerCrashedError -> WorkerDiedError.
"""
from __future__ import annotations

import traceback as _tb


class RayTpuError(Exception):
    """Base class for all ray_tpu errors."""


class TaskError(RayTpuError):
    """A task raised an exception during remote execution.

    Raised at the `get()` site of the caller, mirroring the reference's
    owner-side error propagation (core_worker task retries exhausted ->
    error object stored; see reference src/ray/core_worker/task_manager.cc).
    """

    def __init__(self, cause: BaseException | None, traceback_str: str = "",
                 task_name: str = ""):
        self.cause = cause
        self.traceback_str = traceback_str
        self.task_name = task_name
        super().__init__(str(self))

    def __str__(self) -> str:
        head = f"Task {self.task_name!r} failed" if self.task_name else "Task failed"
        if self.traceback_str:
            return f"{head}:\n{self.traceback_str}"
        return f"{head}: {self.cause!r}"


class ActorError(RayTpuError):
    """An actor died before or during execution of a submitted method."""

    def __init__(self, actor_id: str = "", message: str = ""):
        self.actor_id = actor_id
        super().__init__(message or f"Actor {actor_id} died")


class ActorDiedError(ActorError):
    pass


class ActorUnavailableError(ActorError):
    pass


class WorkerDiedError(RayTpuError):
    """The worker process executing a task died (crash/OOM/kill)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """`get()` timed out."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled before/while running."""

    def __init__(self, task_id: str = ""):
        self.task_id = task_id
        super().__init__(f"Task {task_id} was cancelled")


class ObjectLostError(RayTpuError):
    """Object is unreachable (evicted and not reconstructable)."""


class RuntimeNotInitializedError(RayTpuError):
    def __init__(self):
        super().__init__(
            "ray_tpu has not been initialized; call ray_tpu.init() first.")


class PlacementGroupUnschedulableError(RayTpuError):
    """Placement group cannot fit on the cluster."""


def format_exception(exc: BaseException) -> str:
    return "".join(_tb.format_exception(type(exc), exc, exc.__traceback__))
