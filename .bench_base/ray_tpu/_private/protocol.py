"""Wire protocol for the ray_tpu runtime.

Design: a single full-duplex, length-prefixed-frame protocol over TCP
(localhost) or later unix sockets. Either endpoint may send *requests*
(carry a fresh ``rid``) and *replies* (echo the ``rid``). A ``Connection``
owns a reader thread that routes replies to waiting futures and hands
requests to a handler callback, so both sides can issue RPCs concurrently
(a worker blocked in a nested ``get()`` keeps receiving pushed tasks).

This replaces the reference's per-service gRPC stack (reference
src/ray/rpc/: gcs_server/, node_manager/, worker/) with one multiplexed
channel per process pair — appropriate because our control plane is
centralized in the driver process for the single-node runtime, and the
bulk data plane is shared memory, not the socket.

Frame bodies are versioned protobuf Envelopes (`ray_tpu/protos/
wire.proto` via `_private/wire.py`): control data is schema'd and
language-neutral; Python-only payloads ride an explicit `pickled`
bytes leaf. A peer with an incompatible wire MAJOR version is refused
at the first frame, before any pickled leaf is decoded.
"""
from __future__ import annotations

import itertools
import socket
import struct
import threading
from typing import Any, Callable, Optional

from ray_tpu._private.wire import WireVersionError, dumps, loads

_LEN = struct.Struct("<Q")

# Message types (flat namespace; direction noted).
REGISTER = "register"            # worker -> driver
TASK = "task"                    # driver -> worker: run a normal task
ACTOR_CREATE = "actor_create"    # driver -> worker: instantiate actor
ACTOR_TASK = "actor_task"        # driver -> worker: run actor method
TASK_DONE = "task_done"          # worker -> driver (reply to TASK/ACTOR_*)
GET_OBJECT = "get_object"        # worker -> driver
PUT_OBJECT = "put_object"        # worker -> driver
WAIT = "wait"                    # worker -> driver
SUBMIT = "submit"                # worker -> driver: nested task submission
SUBMIT_ACTOR = "submit_actor"    # worker -> driver: nested actor creation
SUBMIT_ACTOR_TASK = "submit_actor_task"  # worker -> driver
KV_OP = "kv_op"                  # worker -> driver: internal KV get/put/del
DECREF = "decref"                # worker -> driver: ref-count release
ADDREF = "addref"                # worker -> driver
SHUTDOWN = "shutdown"            # driver -> worker
CANCEL_TASK = "cancel_task"      # driver -> worker: interrupt a running task
UNQUEUE_TASK = "unqueue_task"    # driver -> worker: drop a pipelined task
                                 #   that has not started (reply ok)
PING = "ping"                    # either
REPLY = "reply"                  # either (generic reply)
STATE_OP = "state_op"            # worker -> driver: state/metrics queries

# ---- multi-host: node agent <-> head (reference raylet <-> GCS,
# gcs_node_manager.h:62 HandleRegisterNode; ray_syncer.h:88 resource
# gossip; object_manager.cc node-to-node transfer) ----
NODE_REGISTER = "node_register"        # agent -> head (reply: node_id)
NODE_HEARTBEAT = "node_heartbeat"      # agent -> head: resource view
NODE_ENQUEUE = "node_enqueue"          # head -> agent: spec to queue
NODE_CANCEL_PENDING = "node_cancel_pending"  # head -> agent (reply found)
NODE_CANCEL_RUNNING = "node_cancel_running"  # head -> agent
NODE_KILL_WORKER = "node_kill_worker"  # head -> agent
NODE_SEND_ACTOR_TASK = "node_send_actor_task"  # head -> agent (reply ok)
NODE_RESERVE_BUNDLE = "node_reserve_bundle"    # head -> agent (reply ok)
NODE_RELEASE_BUNDLE = "node_release_bundle"    # head -> agent
NODE_EVENT = "node_event"              # agent -> head: dispatch/lost/
                                       #   object_at location registers/...
NODE_TASK_DONE = "node_task_done"      # agent -> head: control + results
NODE_DELETE_OBJECT = "node_delete_object"      # head -> agent
NODE_SHUTDOWN = "node_shutdown"        # head -> agent
OBJECT_LOOKUP = "object_lookup"        # agent -> head (reply: stored |
                                       #   location | timeout)
PULL_OBJECT = "pull_object"            # any -> holder (reply: pull meta)
PULL_CHUNK = "pull_chunk"              # any -> holder (reply: data)


class ConnectionClosed(Exception):
    pass


def _auth_token() -> Optional[bytes]:
    """Shared listener secret (RAY_TPU_AUTH_TOKEN). When set, every
    accepted connection must present it in a RAW first frame, verified
    with a constant-time compare BEFORE any frame is unpickled — the
    wire is pickle, so an unauthenticated peer would otherwise get
    arbitrary code execution (reference scopes this via gRPC + tokened
    client/job servers, python/ray/util/client/server/)."""
    from ray_tpu._private.config import CONFIG
    tok = CONFIG.auth_token
    return tok.encode() if tok else None


class Connection:
    """Full-duplex framed-message channel with request/reply correlation."""

    def __init__(self, sock: socket.socket,
                 handler: Callable[["Connection", dict], None],
                 on_close: Optional[Callable[["Connection"], None]] = None,
                 name: str = "", server: bool = False):
        self._sock = sock
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Bound sends only (recv stays blocking: connections idle for
        # minutes legitimately): waiter-registry replies run inline on
        # sealing threads, so a wedged peer (full TCP buffer) must
        # surface as a ConnectionClosed after this budget instead of
        # hanging the sender forever — peer-death recovery then runs.
        try:
            self._sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                struct.pack("ll", 30, 0))
        except OSError:
            pass
        self._handler = handler
        self._on_close = on_close
        self.name = name
        self._send_lock = threading.Lock()
        self._rid_counter = itertools.count(1)
        self._pending: dict[int, _Future] = {}
        self._pending_lock = threading.Lock()
        self._closed = threading.Event()
        self._server = server
        self.meta: dict = {}  # endpoint-attached metadata (worker id, etc.)
        self._reader = threading.Thread(
            target=self._read_loop, name=f"ray-tpu-conn-{name}", daemon=True)

    def start(self) -> None:
        self._reader.start()

    def send_auth(self) -> None:
        """Client side: present the shared secret as the raw first
        frame (no-op when auth is disabled)."""
        token = _auth_token()
        if token is None:
            return
        with self._send_lock:
            try:
                self._sock.sendall(_LEN.pack(len(token)) + token)
            except OSError as e:
                self.close()
                raise ConnectionClosed(str(e)) from e

    def _check_auth(self) -> bool:
        """Server side (reader thread): verify the raw first frame
        before ANY unpickling. Closes and returns False on mismatch."""
        token = _auth_token()
        if token is None:
            return True
        try:
            # hard deadline: a peer that connects and sends nothing
            # must not pin this thread + fd forever (slowloris)
            self._sock.settimeout(10.0)
            header = self._read_exact(_LEN.size)
            (length,) = _LEN.unpack(header)
            if length > 4096:           # token frames are tiny
                raise ConnectionClosed("oversized auth frame")
            presented = self._read_exact(length)
            self._sock.settimeout(None)
        except (ConnectionClosed, OSError):
            self.close()        # malformed/short/slow: drop the socket
            return False
        import hmac
        if not hmac.compare_digest(presented, token):
            import sys as _sys
            _sys.stderr.write(
                f"ray_tpu: rejected unauthenticated connection "
                f"({self.name})\n")
            self.close()
            return False
        return True

    # ---- sending ----
    def send(self, msg: dict) -> None:
        data = dumps(msg)
        header = _LEN.pack(len(data))
        with self._send_lock:
            try:
                self._sock.sendall(header + data)
            except OSError as e:
                # A failed sendall may have written a PARTIAL frame
                # (e.g. the SO_SNDTIMEO budget expired mid-write); the
                # stream is desynced, so the connection must die — a
                # later send would be parsed as garbage by the peer.
                self.close()
                raise ConnectionClosed(str(e)) from e

    def request(self, msg: dict, timeout: Optional[float] = None) -> dict:
        """Send a request and block for the matching reply."""
        fut = self.request_async(msg)
        return fut.result(timeout)

    def request_async(self, msg: dict) -> "_Future":
        rid = next(self._rid_counter)
        msg["rid"] = rid
        fut = _Future()
        with self._pending_lock:
            self._pending[rid] = fut
        try:
            self.send(msg)
        except ConnectionClosed:
            with self._pending_lock:
                self._pending.pop(rid, None)
            raise
        return fut

    def reply(self, request_msg: dict, **fields) -> None:
        self.send({"type": REPLY, "rid": request_msg["rid"], **fields})

    # ---- receiving ----
    def _read_exact(self, n: int) -> bytes:
        chunks = []
        remaining = n
        while remaining:
            chunk = self._sock.recv(min(remaining, 1 << 20))
            if not chunk:
                raise ConnectionClosed("peer closed")
            chunks.append(chunk)
            remaining -= len(chunk)
        return b"".join(chunks)

    def _read_loop(self) -> None:
        try:
            if self._server and not self._check_auth():
                return
            while True:
                header = self._read_exact(_LEN.size)
                (length,) = _LEN.unpack(header)
                msg = loads(self._read_exact(length))
                if msg.get("type") == REPLY:
                    with self._pending_lock:
                        fut = self._pending.pop(msg["rid"], None)
                    if fut is not None:
                        fut.set(msg)
                else:
                    self._handler(self, msg)
        except (ConnectionClosed, OSError):
            pass
        except WireVersionError as e:
            import sys as _sys
            _sys.stderr.write(
                f"ray_tpu: refusing connection ({self.name}): {e}\n")
        except Exception:  # handler bug; don't kill silently
            import traceback
            traceback.print_exc()
        finally:
            self.close()     # reader exit = stream dead; release the fd
            self._closed.set()
            with self._pending_lock:
                pending, self._pending = self._pending, {}
            for fut in pending.values():
                fut.set_error(ConnectionClosed("connection lost"))
            if self._on_close is not None:
                try:
                    self._on_close(self)
                except Exception:
                    pass

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class _Future:
    """Minimal thread-safe future for reply correlation."""

    def __init__(self):
        self._event = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None
        self._callbacks: list[Callable[["_Future"], None]] = []
        self._cb_lock = threading.Lock()

    def add_done_callback(self, fn: Callable[["_Future"], None]) -> None:
        """Run `fn(self)` when the reply lands (on the reader thread) —
        relays pipe replies onward without parking a thread. Runs
        immediately if already done."""
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def _fire(self) -> None:
        with self._cb_lock:
            cbs, self._callbacks = self._callbacks, []
        for fn in cbs:
            try:
                fn(self)
            except Exception:
                pass

    def set(self, value: Any) -> None:
        self._value = value
        self._event.set()
        self._fire()

    def set_error(self, err: BaseException) -> None:
        self._error = err
        self._event.set()
        self._fire()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError("rpc timed out")
        if self._error is not None:
            raise self._error
        return self._value


def connect(addr: tuple[str, int],
            handler: Callable[[Connection, dict], None],
            on_close: Optional[Callable[[Connection], None]] = None,
            name: str = "") -> Connection:
    sock = socket.create_connection(addr)
    conn = Connection(sock, handler, on_close, name=name)
    conn.send_auth()             # no-op unless RAY_TPU_AUTH_TOKEN is set
    conn.start()
    return conn
