"""Versioned wire codec: message dict <-> protobuf Envelope.

The schema is `ray_tpu/protos/wire.proto` (checked-in generated module
`wire_pb2.py`) — the language-neutral contract for every control-plane
frame, replacing the previous raw-pickle wire (reference parity:
src/ray/protobuf/*.proto define the reference's wire; its TaskSpec
likewise carries pickled function descriptors in `bytes` fields).

Encoding rules (exact round-trip or escape hatch, never lossy):
  * None/bool/int(:int64)/float/str/bytes/list/dict-with-str-keys whose
    size fits the structural bounds encode as typed `Value` nodes.
  * Everything else — task/actor specs, closures, exceptions, tuples,
    subclasses (IntEnum!), oversized collections — rides the `pickled`
    leaf: PLAIN pickle on the fast path (importable object graphs),
    with a tripwire falling back to cloudpickle for anything that
    needs by-value pickling (__main__ / <locals> classes, functions,
    instances — see _FastPickler). Type checks are `type() is`, not
    isinstance, so subclass identity is never silently widened.
  * Bulk collections (> _MAX_ITEMS entries, or nesting deeper than
    _MAX_DEPTH) are pickled wholesale: the structural encoding is for
    control data; the data plane stays a single opaque leaf (state-API
    replies with 100k task events must not pay a Python-loop tax).

Versioning: Envelope.version = MAJOR*100 + MINOR. A frame whose MAJOR
differs from ours raises WireVersionError — the connection is refused
before any field (in particular any pickled leaf) is decoded. MINOR
skew is compatible (proto3 skips unknown fields).

Encoding policy: messages on the language-neutral node plane (agent <->
head registration/heartbeats/events, the object-location + pull
protocol, refcounts, ping) encode field-by-field — a non-Python agent
can speak them. Python-plane messages (task dispatch, replies, nested
submission: their payloads are cloudpickled specs/closures regardless)
put the whole field dict in the flat `py_body` bytes field, keeping
the hot path within ~30% of raw pickle while every frame still carries
the versioned envelope. Structural encode/decode costs ~5µs/leaf in
Python; spending that on a task-plane frame that is ~90% pickled spec
bytes anyway buys nothing.
"""
from __future__ import annotations

import io
import pickle
from typing import Any

import cloudpickle

from ray_tpu._private import wire_pb2 as pb

WIRE_MAJOR = 1
WIRE_MINOR = 0
WIRE_VERSION = WIRE_MAJOR * 100 + WIRE_MINOR

_MAX_ITEMS = 64      # larger lists/dicts -> one pickled leaf
_MAX_DEPTH = 6
_INT64_MIN = -(1 << 63)
_INT64_MAX = (1 << 63) - 1


class WireVersionError(Exception):
    """Peer speaks an incompatible wire major version."""


# Message types that encode field-by-field (the language-neutral set:
# everything a non-Python node agent / object-transfer peer needs).
# Kept in sync with protocol.py constants; anything else rides `__py__`.
STRUCTURAL_TYPES = frozenset({
    "register", "ping", "decref", "addref",
    "node_register", "node_heartbeat", "node_event",
    "node_kill_worker", "node_delete_object", "node_shutdown",
    "object_lookup", "pull_object", "pull_chunk",
})


class _NeedCloudpickle(Exception):
    """Raised mid-pickle when an object graph needs cloudpickle."""


class _FastPickler(pickle.Pickler):
    """Plain pickle with a tripwire: most control-plane messages are
    specs/dicts of importable types, which plain pickle serializes in
    ~1/6 the time of cloudpickle's reducer machinery. But plain pickle
    saves __main__ / <locals> objects BY REFERENCE — "successfully"
    producing bytes the receiving process cannot load. CPython calls
    reducer_override for every non-primitive object being saved
    (classes, functions, AND instances / global-name-pickled objects
    like a __main__ TypeVar), so any graph that needs cloudpickle's
    by-value pickling trips the wire and the whole message falls back
    to cloudpickle."""

    def reducer_override(self, obj):
        mod = getattr(obj, "__module__", None)
        if mod == "__main__" or "<locals>" in getattr(
                obj, "__qualname__", ""):
            raise _NeedCloudpickle
        if mod is None and (isinstance(obj, type) or callable(obj)):
            raise _NeedCloudpickle
        return NotImplemented


def _pickle(obj: Any) -> bytes:
    buf = io.BytesIO()
    try:
        _FastPickler(buf, protocol=pickle.HIGHEST_PROTOCOL).dump(obj)
        return buf.getvalue()
    except (_NeedCloudpickle, TypeError, AttributeError,
            pickle.PicklingError):
        buf = io.BytesIO()
        cloudpickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
        return buf.getvalue()


def _encode_value(obj: Any, v: pb.Value, depth: int) -> None:
    t = type(obj)
    if obj is None:
        v.null = True
    elif t is bool:
        v.b = obj
    elif t is int and _INT64_MIN <= obj <= _INT64_MAX:
        v.i = obj
    elif t is float:
        v.d = obj
    elif t is str:
        v.s = obj
    elif t is bytes:
        v.data = obj
    elif t is list and len(obj) <= _MAX_ITEMS and depth < _MAX_DEPTH:
        lv = v.list
        lv.SetInParent()                 # presence even when empty
        for item in obj:
            _encode_value(item, lv.items.add(), depth + 1)
    elif (t is dict and len(obj) <= _MAX_ITEMS and depth < _MAX_DEPTH
          and all(type(k) is str for k in obj)):
        sv = v.struct
        sv.SetInParent()                 # presence even when empty
        for k, item in obj.items():
            _encode_value(item, sv.fields[k], depth + 1)
    else:
        v.pickled = _pickle(obj)


def _decode_value(v: pb.Value) -> Any:
    kind = v.WhichOneof("kind")
    if kind == "null":
        return None
    if kind == "b":
        return v.b
    if kind == "i":
        return v.i
    if kind == "d":
        return v.d
    if kind == "s":
        return v.s
    if kind == "data":
        return v.data
    if kind == "list":
        return [_decode_value(item) for item in v.list.items]
    if kind == "struct":
        return {k: _decode_value(item)
                for k, item in v.struct.fields.items()}
    if kind == "pickled":
        return pickle.loads(v.pickled)
    return None                          # unset Value (future kinds)


def dumps(msg: dict) -> bytes:
    """Encode a message dict as a versioned Envelope frame body."""
    mtype = msg.get("type", "")
    env = pb.Envelope(version=WIRE_VERSION, type=mtype,
                      rid=msg.get("rid", 0))
    if mtype in STRUCTURAL_TYPES:
        fields = env.fields
        fields.SetInParent()
        for k, val in msg.items():
            if k == "type" or k == "rid":
                continue
            _encode_value(val, fields.fields[k], 0)
    else:
        rest = {k: v for k, v in msg.items()
                if k != "type" and k != "rid"}
        if rest:
            env.py_body = _pickle(rest)
    return env.SerializeToString()


def loads(data: bytes) -> dict:
    """Decode an Envelope frame body; refuses foreign major versions
    before touching any pickled leaf."""
    env = pb.Envelope.FromString(data)
    if env.version // 100 != WIRE_MAJOR:
        raise WireVersionError(
            f"peer wire version {env.version} is incompatible with "
            f"ours ({WIRE_VERSION}): major "
            f"{env.version // 100} != {WIRE_MAJOR}")
    if env.py_body:
        msg = pickle.loads(env.py_body)
    else:
        msg = {k: _decode_value(v)
               for k, v in env.fields.fields.items()}
    msg["type"] = env.type
    if env.rid:
        msg["rid"] = env.rid
    return msg
