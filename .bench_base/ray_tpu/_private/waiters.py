"""Event-driven waiter registry: blocked gets/waits without threads.

Replaces the thread-per-blocked-request model (one Python thread parked
in ``store.get_stored(timeout=...)`` per outstanding worker ``get``/
``wait``) with a registry serviced on object-seal events: the store
fires ``on_seal(object_id)`` when an object lands, and the registry
resolves every waiter watching that id on the sealing thread. A single
timer thread sweeps deadlines. This is the reference's model — raylet
``WaitManager`` (reference src/ray/raylet/wait_manager.cc) and the
core-worker memory store's ``GetAsync`` callbacks are both
notification-driven, not thread-parked — and is what lets one node hold
thousands of blocked workers (BASELINE.md: 1M queued tasks) without a
thread explosion.

Two waiter kinds:
- get: one object id; resolved with the StoredObject (or a location
  miss -> timeout reply).
- wait: N ids, ``num_returns`` threshold; re-evaluated whenever any
  watched id seals; resolved with the ready list.

The registry is presence-agnostic: ``present_fn(oid)`` decides what
"ready" means (the single-host runtime uses store residency; the
multi-host runtime ORs in remote-location knowledge), and
``resolve_fn(waiter)`` builds + sends the reply, so the same registry
serves both topologies.
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass(eq=False)          # identity hash: waiters live in sets
class GetWaiter:
    oid: str
    reply: Callable[["GetWaiter", bool], None]   # (waiter, timed_out)
    deadline: Optional[float]
    on_done: Optional[Callable[[], None]] = None  # unblock bookkeeping
    seq: int = 0
    resolved: bool = False


@dataclass(eq=False)
class WaitWaiter:
    ids: list[str]
    num_returns: int
    reply: Callable[["WaitWaiter", list[str]], None]  # (waiter, ready)
    deadline: Optional[float]
    on_done: Optional[Callable[[], None]] = None
    seq: int = 0
    resolved: bool = False


class WaiterRegistry:
    def __init__(self, present_fn: Callable[[str], bool]):
        self._present = present_fn
        from ray_tpu._private.debug_sync import make_lock
        self._lock = make_lock("waiters")
        self._by_oid: dict[str, set] = {}
        self._heap: list[tuple[float, int, object]] = []
        self._seq = itertools.count()
        self._cv = threading.Condition(self._lock)
        self._running = True
        self._timer = threading.Thread(target=self._timer_loop,
                                       name="ray-tpu-waiters", daemon=True)
        self._timer.start()

    # ------------------------------------------------------------ add
    def add_get(self, oid: str, reply, timeout: Optional[float],
                on_done=None) -> None:
        """Register a get waiter; resolves immediately if present."""
        w = GetWaiter(oid=oid, reply=reply,
                      deadline=(None if timeout is None
                                else time.monotonic() + timeout),
                      on_done=on_done, seq=next(self._seq))
        fire = None        # resolved immediately: reply OUTSIDE the lock
        with self._cv:
            if not self._running:
                fire = lambda: reply(w, True)  # noqa: E731
            else:
                # register-then-check closes the probe/seal race: a seal
                # between our presence check and registration would be
                # lost the other way around.
                self._by_oid.setdefault(oid, set()).add(w)
                if self._present(oid):
                    self._unlink_locked(w)
                    fire = lambda: reply(w, False)  # noqa: E731
                elif w.deadline is not None:
                    heapq.heappush(self._heap, (w.deadline, w.seq, w))
                    self._cv.notify()
        if fire is not None:
            self._finish(w, fire)

    def add_wait(self, ids: list[str], num_returns: int, reply,
                 timeout: Optional[float], on_done=None) -> None:
        w = WaitWaiter(ids=list(ids), num_returns=num_returns, reply=reply,
                       deadline=(None if timeout is None
                                 else time.monotonic() + timeout),
                       on_done=on_done, seq=next(self._seq))
        fire = None
        with self._cv:
            if not self._running:
                fire = lambda: reply(w, [])  # noqa: E731
            else:
                for oid in w.ids:
                    self._by_oid.setdefault(oid, set()).add(w)
                ready = [o for o in w.ids if self._present(o)]
                if len(ready) >= num_returns or num_returns <= 0:
                    self._unlink_locked(w)
                    fire = lambda: reply(w, ready)  # noqa: E731
                elif w.deadline is not None:
                    heapq.heappush(self._heap, (w.deadline, w.seq, w))
                    self._cv.notify()
        if fire is not None:
            self._finish(w, fire)

    # --------------------------------------------------------- notify
    def notify(self, oid: str) -> None:
        """An object sealed (or its remote location registered):
        resolve every waiter whose condition is now met. Runs on the
        sealing thread; replies are socket sends."""
        # Lock-free fast path: most seals have no waiters. Safe against
        # a concurrent registration because add_get/add_wait re-check
        # presence under their own lock AFTER inserting the waiter, and
        # the store already sealed the object before calling us.
        if oid not in self._by_oid:
            return
        done: list[tuple[object, Callable]] = []
        with self._lock:
            waiters = self._by_oid.get(oid)
            if not waiters:
                return
            for w in list(waiters):
                if w.resolved:
                    continue
                if isinstance(w, GetWaiter):
                    self._unlink_locked(w)
                    done.append((w, (lambda w=w: w.reply(w, False))))
                else:
                    ready = [o for o in w.ids if self._present(o)]
                    if len(ready) >= w.num_returns:
                        self._unlink_locked(w)
                        done.append(
                            (w, (lambda w=w, r=ready: w.reply(w, r))))
        for w, fn in done:
            self._finish(w, fn)

    # ---------------------------------------------------------- timer
    def _timer_loop(self) -> None:
        while True:
            expired: list[tuple[object, Callable]] = []
            with self._cv:
                if not self._running:
                    return
                now = time.monotonic()
                while self._heap and self._heap[0][0] <= now:
                    _, _, w = heapq.heappop(self._heap)
                    if w.resolved:
                        continue
                    self._unlink_locked(w)
                    if isinstance(w, GetWaiter):
                        expired.append((w, (lambda w=w: w.reply(w, True))))
                    else:
                        ready = [o for o in w.ids if self._present(o)]
                        expired.append(
                            (w, (lambda w=w, r=ready: w.reply(w, r))))
                timeout = (self._heap[0][0] - now) if self._heap else None
                if not expired:
                    self._cv.wait(timeout=timeout)
            for w, fn in expired:
                self._finish(w, fn)

    # -------------------------------------------------------- helpers
    def _unlink_locked(self, w) -> None:
        w.resolved = True
        ids = [w.oid] if isinstance(w, GetWaiter) else w.ids
        for oid in ids:
            s = self._by_oid.get(oid)
            if s is not None:
                s.discard(w)
                if not s:
                    self._by_oid.pop(oid, None)

    def _finish(self, w, fn: Callable) -> None:
        try:
            fn()
        except Exception:
            pass
        if w.on_done is not None:
            try:
                w.on_done()
            except Exception:
                pass

    def stats(self) -> dict:
        with self._lock:
            return {"watched_ids": len(self._by_oid),
                    "pending_timeouts": len(self._heap)}

    def shutdown(self) -> None:
        with self._cv:
            self._running = False
            self._cv.notify_all()
