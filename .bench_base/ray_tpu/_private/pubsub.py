"""Pubsub: cursor-based channels on the control plane.

Parity: reference src/ray/pubsub (long-poll publisher/subscriber used
for actor/node/error channels) — re-shaped for this topology: the
driver-resident `Publisher` keeps a bounded ring per channel; consumers
poll with a cursor (workers via the STATE_OP RPC, driver-side readers
directly), which gives the same at-least-once-in-order contract the
reference's long-poll delivers without a push socket per subscriber.

Wired publications: node lifecycle (cluster) and actor lifecycle
(controller) — the channels the reference's GCS publishes.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

# Well-known channels (reference rpc::ChannelType)
NODE_CHANNEL = "node"
ACTOR_CHANNEL = "actor"
ERROR_CHANNEL = "error"


class StaleCursorError(Exception):
    """The cursor predates the retained window: messages were evicted
    and are unrecoverable (the caller must resync its view). The
    ``resync`` attribute carries the current head seq to restart from."""

    def __init__(self, msg: str, resync: int = 0):
        super().__init__(msg)
        self.resync = resync


class Publisher:
    def __init__(self, maxlen_per_channel: int = 1000):
        self._lock = threading.Condition()
        self._maxlen = maxlen_per_channel
        # channel -> (next_seq, ring of (seq, ts, message))
        self._channels: Dict[str, Tuple[int, deque]] = {}
        # Async long-poll waiters: (channel, cursor, deadline, cb).
        # publish() resolves matching waiters inline; a lazy timer
        # thread expires the rest — so a remote subscriber's long-poll
        # parks HERE instead of blocking its connection reader thread
        # (the reference's long-poll is equally push-resolved).
        self._waiters: List[tuple] = []
        self._timer_started = False
        self._stopped = False

    def publish(self, channel: str, message: Any) -> int:
        fire: List[tuple] = []
        with self._lock:
            seq, ring = self._channels.get(channel, (0, None))
            if ring is None:
                ring = deque(maxlen=self._maxlen)
            ring.append((seq, time.time(), message))
            self._channels[channel] = (seq + 1, ring)
            if self._waiters:
                keep = []
                for w in self._waiters:
                    ch, cursor, _deadline, cb = w
                    if ch == channel:
                        msgs = [m for s, _, m in ring if s >= cursor]
                        fire.append((cb, msgs, seq + 1))
                    else:
                        keep.append(w)
                self._waiters = keep
            self._lock.notify_all()
        for cb, msgs, cur in fire:       # outside the lock: cb sends
            try:
                cb(msgs, cur)
            except Exception:
                pass
        return seq

    def add_waiter(self, channel: str, cursor: int, timeout: float,
                   cb) -> None:
        """Async long-poll: cb(messages, next_cursor) fires when a
        message lands on `channel` (or immediately if one is already
        past `cursor`), or with ([], cursor) at the timeout. Raises
        StaleCursorError synchronously like poll() — the at-least-once
        contract must not silently skip evicted messages."""
        with self._lock:
            seq, ring = self._channels.get(channel, (0, None))
            if ring and cursor < ring[0][0]:
                raise StaleCursorError(
                    f"channel {channel!r}: cursor {cursor} predates "
                    f"oldest retained seq {ring[0][0]}", resync=seq)
            msgs = ([m for s, _, m in ring if s >= cursor]
                    if ring is not None else [])
            if msgs:
                now_cur = seq
            else:
                self._waiters.append(
                    (channel, cursor, time.time() + timeout, cb))
                if not self._timer_started:
                    self._timer_started = True
                    threading.Thread(target=self._expire_loop,
                                     name="rtpu-pubsub-expire",
                                     daemon=True).start()
                return
        try:
            cb(msgs, now_cur)
        except Exception:
            pass

    def close(self) -> None:
        """Fail outstanding waiters and stop the expire thread."""
        with self._lock:
            self._stopped = True
            waiters, self._waiters = self._waiters, []
        for _ch, cursor, _dl, cb in waiters:
            try:
                cb([], cursor)
            except Exception:
                pass

    def _expire_loop(self) -> None:
        idle_ticks = 0
        while True:
            time.sleep(0.5)
            now = time.time()
            expired: List[tuple] = []
            with self._lock:
                if getattr(self, "_stopped", False):
                    return
                if not self._waiters:
                    idle_ticks += 1
                    if idle_ticks >= 20:
                        # 10s with nothing to expire: park; a future
                        # add_waiter restarts the thread (under lock)
                        self._timer_started = False
                        return
                    continue
                idle_ticks = 0
                keep = []
                for w in self._waiters:
                    if w[2] <= now:
                        expired.append(w)
                    else:
                        keep.append(w)
                self._waiters = keep
            for _ch, cursor, _dl, cb in expired:
                try:
                    cb([], cursor)
                except Exception:
                    pass

    def poll(self, channel: str, cursor: int = 0,
             timeout: Optional[float] = None
             ) -> Tuple[List[Any], int]:
        """Messages with seq >= cursor and the next cursor. With a
        timeout, blocks until at least one message lands (long-poll)."""
        deadline = None if timeout is None else time.time() + timeout

        def fetch():
            seq, ring = self._channels.get(channel, (0, None))
            if ring is None:
                return [], 0
            if ring and cursor < ring[0][0]:
                # at-least-once contract: never silently skip evicted
                # messages — the subscriber fell too far behind
                raise StaleCursorError(
                    f"channel {channel!r}: cursor {cursor} predates "
                    f"oldest retained seq {ring[0][0]}", resync=seq)
            msgs = [(s, m) for s, _, m in ring if s >= cursor]
            return msgs, seq

        with self._lock:
            msgs, next_cursor = fetch()
            while not msgs and deadline is not None:
                left = deadline - time.time()
                if left <= 0:
                    break
                self._lock.wait(timeout=min(left, 0.25))
                msgs, next_cursor = fetch()
            return [m for _, m in msgs], max(next_cursor, cursor)

    def current_seq(self, channel: str) -> int:
        """Next sequence number for `channel` (resync point)."""
        with self._lock:
            return self._channels.get(channel, (0, None))[0]

    def channels(self) -> List[str]:
        with self._lock:
            return sorted(self._channels)
