"""Process-global runtime context.

Every process participating in a ray_tpu cluster — the driver or a spawned
worker — installs exactly one ``BaseContext`` implementation here. The public
API (``ray_tpu.get/put/remote/...``) routes through it, so user code behaves
identically whether it runs in the driver or inside a remote task/actor
(mirroring the reference where both driver and workers embed the same core
worker library, reference src/ray/core_worker/core_worker.h:271).
"""
from __future__ import annotations

from typing import Any, Optional

from ray_tpu.exceptions import RuntimeNotInitializedError

_ctx: Optional["BaseContext"] = None


class BaseContext:
    """Interface both the driver runtime and worker context implement."""

    is_driver: bool = False

    # object plane
    def put(self, value: Any) -> "ObjectRef": raise NotImplementedError
    def get_objects(self, object_ids: list[str],
                    timeout: Optional[float]) -> list[Any]:
        raise NotImplementedError
    def wait(self, object_ids: list[str], num_returns: int,
             timeout: Optional[float]) -> tuple[list[str], list[str]]:
        raise NotImplementedError
    def addref(self, object_id: str) -> None: pass
    def decref(self, object_id: str) -> None: pass

    # task plane
    def submit_task(self, spec) -> list[str]: raise NotImplementedError
    def create_actor(self, spec) -> str: raise NotImplementedError
    def submit_actor_task(self, actor_id: str, spec) -> list[str]:
        raise NotImplementedError
    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        raise NotImplementedError
    def cancel_task(self, object_id: str, force: bool = False) -> None:
        raise NotImplementedError

    # control plane
    def kv_op(self, op: str, key: str, value: Any = None,
              namespace: str = "default") -> Any:
        raise NotImplementedError
    def get_actor_handle(self, name: str, namespace: str = "default"):
        raise NotImplementedError
    def state_op(self, op: str, **kwargs) -> Any:
        raise NotImplementedError

    def node_resources(self) -> dict:
        raise NotImplementedError


_ctx_epoch = 0


def set_ctx(ctx: Optional[BaseContext]) -> None:
    global _ctx, _ctx_epoch
    if ctx is not None and not hasattr(ctx, "ctx_epoch"):
        # monotonic context identity: id() of a new Runtime can collide
        # with a freed one's address, so per-runtime caches (prepared
        # runtime envs, function registration) key on this instead
        _ctx_epoch += 1
        ctx.ctx_epoch = _ctx_epoch
    _ctx = ctx


def get_ctx() -> BaseContext:
    if _ctx is None:
        raise RuntimeNotInitializedError()
    return _ctx


def maybe_ctx() -> Optional[BaseContext]:
    return _ctx


def is_initialized() -> bool:
    return _ctx is not None
