"""JAX platform pinning for worker processes.

A site hook in this environment re-registers experimental TPU platforms
and rewrites `jax_platforms` at import time, overriding the
JAX_PLATFORMS env var a parent process fanned out to its workers (the
driver pins CPU in tests so the single TPU chip isn't fought over).
Framework actors that initialize JAX call `pin_platform_from_env()`
first, restoring the env var's authority before any backend spins up.
"""
from __future__ import annotations

import os


def pin_platform_from_env() -> None:
    platforms = os.environ.get("JAX_PLATFORMS")
    if not platforms:
        return
    try:
        import jax
        jax.config.update("jax_platforms", platforms)
    except Exception:
        # backend already initialized (config is then immutable) or jax
        # missing — either way the caller's import proceeds as-is.
        pass
