"""Pickle helpers shared by the user-facing shims.

cloudpickle serializes module-level functions/classes BY REFERENCE when
their module is importable in the current process — but worker
processes cannot import driver-only modules (scripts, test files,
notebooks' helper modules). `dumps_by_value` captures such objects by
VALUE instead, leaving true library code (stdlib/site-packages/ray_tpu)
by reference.
"""
from __future__ import annotations

import os
import sys
from typing import Any

import cloudpickle


def _is_library_module(mod) -> bool:
    f = getattr(mod, "__file__", None)
    if not f:
        return True                    # builtins / frozen
    f = f.replace(os.sep, "/")
    return (f.startswith(sys.prefix.replace(os.sep, "/"))
            or "site-packages" in f
            or "/ray_tpu/" in f)


def dumps_by_value(obj: Any, roots: tuple = ()) -> bytes:
    """Serialize `obj`, forcing driver-local modules by value. `roots`
    names additional objects whose defining modules must also ship by
    value (e.g. the user functions inside a joblib BatchedCalls
    wrapper, which itself lives in library code)."""
    mods = []
    for o in (obj, *roots):
        mod = sys.modules.get(getattr(o, "__module__", None) or "")
        if (mod is not None and mod.__name__ != "__main__"
                and not _is_library_module(mod)
                and mod not in mods):
            mods.append(mod)
    for m in mods:
        cloudpickle.register_pickle_by_value(m)
    try:
        return cloudpickle.dumps(obj)
    finally:
        for m in mods:
            cloudpickle.unregister_pickle_by_value(m)
