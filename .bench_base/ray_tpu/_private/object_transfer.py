"""Cross-host object transfer: chunked pull protocol.

The reference moves objects node-to-node with a chunked push/pull plane
(reference src/ray/object_manager/object_manager.cc, pull_manager.cc,
object_buffer_pool.cc chunking). Here the equivalent is a pull-only
protocol riding the framed-message channel:

    PULL_OBJECT {object_id}            -> {found, pull_id, nchunks, size}
    PULL_CHUNK  {pull_id, index}       -> {data: bytes}   (x nchunks)

The holder serializes the StoredObject — materializing any POSIX-shm
segments into inline bytes, since shm names are host-local — and serves
it in fixed-size chunks so one giant object never occupies a connection
for a single monolithic frame (and the puller can bound memory).
Sessions expire after a TTL to survive pullers that die mid-pull.
"""
from __future__ import annotations

import io
import pickle
import threading
import time
import uuid
from typing import Optional

from ray_tpu._private import protocol
from ray_tpu._private.object_store import StoredObject, _map_segment

CHUNK_BYTES = 4 * 1024 * 1024
_SESSION_TTL_S = 120.0


def materialize(obj: StoredObject) -> StoredObject:
    """Copy of `obj` with every shm-backed buffer pulled inline — the
    only form that can cross a host boundary."""
    if not obj.shm_names:
        return obj
    inline: list[bytes] = []
    ii = si = 0
    order: list[str] = []
    for kind in obj.buffer_order:
        if kind == "i":
            inline.append(obj.inline_buffers[ii]); ii += 1
        else:
            mv = _map_segment(obj.shm_names[si], obj.shm_sizes[si])
            inline.append(mv.tobytes())
            del mv
            si += 1
        order.append("i")
    return StoredObject(obj.object_id, obj.payload, inline, [], [],
                        order, obj.is_error,
                        contained_ids=list(obj.contained_ids))


def _encode(obj: StoredObject) -> bytes:
    return pickle.dumps(materialize(obj), protocol=pickle.HIGHEST_PROTOCOL)


def _decode(data: bytes) -> StoredObject:
    return pickle.loads(data)


class PullServer:
    """Serves PULL_OBJECT / PULL_CHUNK against a LocalStore. Mixed into
    any endpoint that holds objects (head runtime, node agent).

    `executor` (when given) takes the slow path — spill restore from
    disk + blob encode — off the connection reader thread, so a
    multi-GB restore can never stall heartbeat processing on a shared
    control connection."""

    def __init__(self, store, executor=None):
        self._store = store
        self._executor = executor
        self._sessions: dict[str, tuple[bytes, float]] = {}
        self._slock = threading.Lock()

    def handle_pull(self, conn: protocol.Connection, msg: dict) -> None:
        """Runs on the connection reader thread: answer only the cheap
        not-found case inline; ALL serving (the _encode of a possibly
        multi-GB object, and any spill restore) goes to the executor so
        the reader thread never stalls heartbeats/control traffic."""
        oid = msg["object_id"]
        stored = self._store.get_stored(oid, timeout=0, restore=False)
        if stored is None and not self._store.contains(oid):
            stored = self._store.get_stored(oid, timeout=0)
            if stored is None:
                conn.reply(msg, found=False)
                return
        if self._executor is not None:
            self._executor.submit(self._pull_slow, conn, msg, oid)
        elif stored is not None:
            self._serve(conn, msg, stored)
        else:
            self._pull_slow(conn, msg, oid)

    def _pull_slow(self, conn: protocol.Connection, msg: dict,
                   oid: str) -> None:
        try:
            stored = self._store.get_stored(oid, timeout=10)
            if stored is None:
                conn.reply(msg, found=False)
            else:
                self._serve(conn, msg, stored)
        except protocol.ConnectionClosed:
            pass

    def _serve(self, conn: protocol.Connection, msg: dict,
               stored) -> None:
        blob = _encode(stored)
        pull_id = uuid.uuid4().hex[:12]
        now = time.monotonic()
        with self._slock:
            self._sessions[pull_id] = (blob, now)
            # TTL sweep inline (sessions are few; no timer thread)
            dead = [k for k, (_, t) in self._sessions.items()
                    if now - t > _SESSION_TTL_S]
            for k in dead:
                self._sessions.pop(k, None)
        nchunks = max(1, (len(blob) + CHUNK_BYTES - 1) // CHUNK_BYTES)
        conn.reply(msg, found=True, pull_id=pull_id, nchunks=nchunks,
                   size=len(blob))

    def handle_chunk(self, conn: protocol.Connection, msg: dict) -> None:
        pull_id, index = msg["pull_id"], msg["index"]
        with self._slock:
            entry = self._sessions.get(pull_id)
            if entry is not None:
                blob = entry[0]
                self._sessions[pull_id] = (blob, time.monotonic())
        if entry is None:
            conn.reply(msg, data=None)
            return
        start = index * CHUNK_BYTES
        data = blob[start:start + CHUNK_BYTES]
        last = start + CHUNK_BYTES >= len(blob)
        if last:
            with self._slock:
                self._sessions.pop(pull_id, None)
        conn.reply(msg, data=data)


def pull_object(conn: protocol.Connection, object_id: str,
                timeout: Optional[float] = 60.0) -> Optional[StoredObject]:
    """Client side: chunked fetch of one object over `conn`."""
    deadline = None if timeout is None else time.monotonic() + timeout

    def remaining() -> Optional[float]:
        if deadline is None:
            return None
        return max(0.1, deadline - time.monotonic())

    meta = conn.request({"type": protocol.PULL_OBJECT,
                         "object_id": object_id}, timeout=remaining())
    if not meta.get("found"):
        return None
    parts: list[bytes] = []
    for i in range(meta["nchunks"]):
        rep = conn.request({"type": protocol.PULL_CHUNK,
                            "pull_id": meta["pull_id"], "index": i},
                           timeout=remaining())
        data = rep.get("data")
        if data is None:
            return None                  # session expired / holder lost it
        parts.append(data)
    return _decode(b"".join(parts))
