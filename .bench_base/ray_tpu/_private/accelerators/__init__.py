from ray_tpu._private.accelerators.tpu import (TPUAcceleratorManager,
                                               detect_num_tpu_chips)

__all__ = ["TPUAcceleratorManager", "detect_num_tpu_chips"]
