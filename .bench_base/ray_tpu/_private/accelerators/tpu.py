"""TPU accelerator manager: detection, visibility, pod-slice resources.

Parity: reference python/ray/_private/accelerators/tpu.py —
- chip detection via /dev/accel* then /dev/vfio (:98-117),
- chip-subset visibility env vars TPU_VISIBLE_CHIPS /
  TPU_CHIPS_PER_HOST_BOUNDS (:154-195),
- pod-slice scheduling resources (:334-397): every worker of a pod
  slice advertises {<pod_name>: 1} and worker 0 additionally advertises
  {TPU-<generation>-head: 1}, so "one actor per pod host, addressed as
  a unit" is a plain resource request (SURVEY.md §7 step 3's SPMD-slice
  bundle primitive).

Environment detection is env-var based (TPU_NAME / TPU_WORKER_ID /
TPU_ACCELERATOR_TYPE as set by GKE and the TPU VM runtime); the
reference's GCE metadata-server probing is intentionally not replicated
(zero-egress design: the runtime env always carries these vars).
"""
from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional

# chips per host by generation: v2/v3/v4/v5p hosts carry 4 chips;
# v5litepod (v5e) and v6e hosts carry up to 8.
_CHIPS_PER_HOST = {"v2": 4, "v3": 4, "v4": 4, "v5p": 4, "v5e": 8,
                   "v5litepod": 8, "v6e": 8}
# generations whose accelerator_type suffix counts TensorCores (2/chip)
# rather than chips.
_SUFFIX_IS_CORES = {"v2", "v3", "v4", "v5p"}


def detect_num_tpu_chips() -> int:
    """Chips visible on this host (env override > /dev probing)."""
    env = os.environ.get("RAY_TPU_CHIPS")
    if env is not None:
        return int(env)
    visible = os.environ.get("TPU_VISIBLE_CHIPS")
    if visible:
        return len([c for c in visible.split(",") if c.strip()])
    accel = glob.glob("/dev/accel*")
    if accel:
        return len(accel)
    vfio = glob.glob("/dev/vfio/[0-9]*")
    if vfio:
        return len(vfio)
    return 0


def parse_accelerator_type(accelerator_type: str) -> tuple:
    """'v4-32' -> ('v4', 32). Raises on malformed input."""
    parts = accelerator_type.lower().split("-")
    if len(parts) != 2 or not parts[1].isdigit():
        raise ValueError(
            f"malformed TPU accelerator type {accelerator_type!r}; "
            f"expected e.g. 'v4-32', 'v5e-16'")
    gen, size = parts[0], int(parts[1])
    if gen not in _CHIPS_PER_HOST:
        raise ValueError(f"unknown TPU generation {gen!r} "
                         f"(known: {sorted(_CHIPS_PER_HOST)})")
    return gen, size


def chips_per_host(accelerator_type: str) -> int:
    gen, size = parse_accelerator_type(accelerator_type)
    per_host = _CHIPS_PER_HOST[gen]
    total = num_chips(accelerator_type)
    return min(per_host, total)


def num_chips(accelerator_type: str) -> int:
    gen, size = parse_accelerator_type(accelerator_type)
    return size // 2 if gen in _SUFFIX_IS_CORES else size


def num_hosts(accelerator_type: str) -> int:
    """Hosts in the pod slice (>=1)."""
    chips = num_chips(accelerator_type)
    gen, _ = parse_accelerator_type(accelerator_type)
    return max(1, -(-chips // _CHIPS_PER_HOST[gen]))


def head_resource_name(accelerator_type: str) -> str:
    gen, _ = parse_accelerator_type(accelerator_type)
    return f"TPU-{gen}-head"


class TPUAcceleratorManager:
    """AcceleratorManager-shape API (reference accelerator.py ABC)."""

    RESOURCE_NAME = "TPU"

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        return detect_num_tpu_chips()

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        return (os.environ.get("TPU_ACCELERATOR_TYPE")
                or os.environ.get("RAY_TPU_ACCELERATOR_TYPE"))

    @staticmethod
    def get_current_pod_name() -> Optional[str]:
        return (os.environ.get("TPU_NAME")
                or os.environ.get("RAY_TPU_POD_NAME"))

    @staticmethod
    def get_current_pod_worker_id() -> int:
        return int(os.environ.get("TPU_WORKER_ID", "0"))

    @staticmethod
    def set_visible_accelerators(chip_ids: List[int]) -> None:
        """Restrict this process to a chip subset (reference :154-195)."""
        os.environ["TPU_VISIBLE_CHIPS"] = ",".join(map(str, chip_ids))
        n = len(chip_ids)
        bounds = {1: "1,1,1", 2: "1,2,1", 4: "2,2,1", 8: "2,4,1"}
        if n in bounds:
            os.environ["TPU_CHIPS_PER_HOST_BOUNDS"] = bounds[n]

    @classmethod
    def get_current_node_additional_resources(cls) -> Dict[str, float]:
        """Pod-slice resources this node should advertise
        (reference tpu.py:334-397): {pod_name: 1} on every slice host,
        plus {TPU-<gen>-head: 1} on worker 0."""
        pod = cls.get_current_pod_name()
        if not pod:
            return {}
        out: Dict[str, float] = {pod: 1.0}
        accel = cls.get_current_node_accelerator_type()
        if accel and cls.get_current_pod_worker_id() == 0:
            out[head_resource_name(accel)] = 1.0
        return out
