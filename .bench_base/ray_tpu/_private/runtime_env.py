"""Runtime environments: pip venvs, py_modules, env hashing.

Parity: reference python/ray/_private/runtime_env/{pip.py, py_modules.py}
+ the runtime-env-keyed worker reuse of raylet worker_pool.cc — re-shaped
for this stack: there is no separate agent process; the FIRST worker that
needs an env materializes it into a per-host cache keyed by content hash
(guarded by a lock file against concurrent workers), and later workers —
or the same pooled worker running another task with the same env — reuse
it via sys.path injection. The scheduler prefers idle workers whose last
applied env hash matches the task's, so repeated working_dir/pip churn on
pooled workers disappears.

- pip: {"pip": [pkgs...]} or {"pip": {"packages": [...], "pip_install_
  options": [...]}} — a venv with --system-site-packages at
  ~/.ray_tpu/runtime_envs/pip/<hash>/, its site-packages prepended to
  sys.path (the reference execs the worker inside the venv; path
  injection gives the same import resolution without a re-exec).
- py_modules: list of local dirs/files, packed driver-side into zips
  stored in the cluster KV under their content hash; workers extract to
  ~/.ray_tpu/runtime_envs/py_modules/<hash>/ and prepend to sys.path, so
  driver-local packages import on workers that share no filesystem.
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import shutil
import subprocess
import sys
import tempfile
import zipfile
from typing import Any, Dict, List, Optional

_CACHE_ROOT = os.path.join(
    os.path.expanduser(os.environ.get("RAY_TPU_RUNTIME_ENV_DIR",
                                      "~/.ray_tpu/runtime_envs")))


def env_hash(renv: Optional[dict]) -> Optional[str]:
    """Stable identity of a runtime env (worker-reuse key)."""
    if not renv:
        return None
    return hashlib.sha1(
        json.dumps(renv, sort_keys=True, default=str).encode()
    ).hexdigest()[:12]


# ------------------------------------------------------------ py_modules
def pack_py_module(path: str) -> bytes:
    """Zip one module dir (or single .py file) deterministically —
    fixed entry timestamps so equal content yields an equal hash (a
    time-varying hash would defeat the KV dedup, the worker cache, AND
    env-keyed worker reuse)."""
    path = os.path.abspath(path)
    buf = io.BytesIO()

    def add(zf, arcname, data):
        info = zipfile.ZipInfo(arcname, date_time=(1980, 1, 1, 0, 0, 0))
        info.compress_type = zipfile.ZIP_DEFLATED
        zf.writestr(info, data)

    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        if os.path.isfile(path):
            add(zf, os.path.basename(path), open(path, "rb").read())
        else:
            base = os.path.basename(path.rstrip("/"))
            for root, dirs, files in os.walk(path):
                dirs.sort()
                for f in sorted(files):
                    if f.endswith(".pyc") or "__pycache__" in root:
                        continue
                    full = os.path.join(root, f)
                    rel = os.path.join(base, os.path.relpath(full, path))
                    add(zf, rel, open(full, "rb").read())
    return buf.getvalue()


def upload_py_modules(renv: dict, kv_put) -> dict:
    """Driver-side (submission): replace local paths with KV refs.
    Already-uploaded specs (dicts with 'hash') pass through."""
    mods = renv.get("py_modules")
    if not mods:
        return renv
    out = []
    for m in mods:
        if isinstance(m, dict) and "hash" in m:
            out.append(m)
            continue
        if not isinstance(m, str) or not os.path.exists(m):
            raise ValueError(f"py_modules entry {m!r} is not a local "
                             f"path (or a prior upload ref)")
        data = pack_py_module(m)
        h = hashlib.sha1(data).hexdigest()[:16]
        kv_put(f"pymod:{h}", data)
        out.append({"hash": h,
                    "name": os.path.basename(m.rstrip("/"))})
    new = dict(renv)
    new["py_modules"] = out
    return new


def ensure_py_modules(mods: List[dict], kv_get) -> List[str]:
    """Worker-side: materialize each module zip from KV into the host
    cache; returns sys.path entries."""
    paths = []
    for m in mods:
        h = m["hash"]
        dest = os.path.join(_CACHE_ROOT, "py_modules", h)
        marker = os.path.join(dest, ".ready")
        if not os.path.exists(marker):
            _locked_build(dest, lambda d: _extract_zip(
                kv_get(f"pymod:{h}"), d))
        paths.append(dest)
    return paths


def _extract_zip(data: bytes, dest: str) -> None:
    if data is None:
        raise RuntimeError("py_module content missing from cluster KV")
    with zipfile.ZipFile(io.BytesIO(data)) as zf:
        zf.extractall(dest)


# ------------------------------------------------------------------ pip
def normalize_pip(spec: Any) -> dict:
    if isinstance(spec, list):
        return {"packages": list(spec), "pip_install_options": []}
    if isinstance(spec, dict):
        return {"packages": list(spec.get("packages", [])),
                "pip_install_options": list(
                    spec.get("pip_install_options", []))}
    raise TypeError("pip spec must be a list of packages or a dict")


def ensure_pip_env(spec: dict) -> str:
    """Create (once per host per hash) a venv with the requested
    packages; returns its site-packages dir for sys.path injection."""
    h = hashlib.sha1(json.dumps(spec, sort_keys=True).encode()
                     ).hexdigest()[:16]
    dest = os.path.join(_CACHE_ROOT, "pip", h)

    def build(tmp: str) -> None:
        subprocess.run(
            [sys.executable, "-m", "venv", "--system-site-packages",
             tmp], check=True, capture_output=True)
        vpy = os.path.join(tmp, "bin", "python")
        cmd = [vpy, "-m", "pip", "install", "--no-input",
               *spec["pip_install_options"], *spec["packages"]]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"pip install failed ({' '.join(cmd)}):\n"
                f"{proc.stdout}\n{proc.stderr}")

    if not os.path.exists(os.path.join(dest, ".ready")):
        _locked_build(dest, build)
    return _site_packages_of(dest)


def _site_packages_of(venv_dir: str) -> str:
    lib = os.path.join(venv_dir, "lib")
    for entry in sorted(os.listdir(lib)):
        sp = os.path.join(lib, entry, "site-packages")
        if os.path.isdir(sp):
            return sp
    raise RuntimeError(f"no site-packages under {venv_dir}")


# ------------------------------------------------------------------- uv
def ensure_uv_env(spec: Any) -> str:
    """Like ensure_pip_env but resolved/installed by the `uv` binary
    (reference _private/runtime_env/uv.py): ~10-100x faster resolver
    for big dependency sets. Gated: raises a clear error when uv is
    not installed on this host. RAY_TPU_UV_BIN overrides discovery
    (tests point it at a stub)."""
    uv = os.environ.get("RAY_TPU_UV_BIN") or shutil.which("uv")
    if not uv:
        raise RuntimeError(
            "runtime_env {'uv': ...} requires the `uv` binary on the "
            "worker host (not found on PATH); install uv or use "
            "{'pip': ...}")
    if isinstance(spec, list):
        spec = {"packages": list(spec), "uv_pip_install_options": []}
    h = hashlib.sha1(json.dumps(spec, sort_keys=True).encode()
                     ).hexdigest()[:16]
    dest = os.path.join(_CACHE_ROOT, "uv", h)

    def build(tmp: str) -> None:
        for cmd in (
                [uv, "venv", "--system-site-packages", tmp],
                [uv, "pip", "install", "--python",
                 os.path.join(tmp, "bin", "python"),
                 *spec.get("uv_pip_install_options", []),
                 *spec["packages"]]):
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(f"uv failed ({' '.join(cmd)}):\n"
                                   f"{proc.stdout}\n{proc.stderr}")

    if not os.path.exists(os.path.join(dest, ".ready")):
        _locked_build(dest, build)
    return _site_packages_of(dest)


# ---------------------------------------------------------------- conda
def ensure_conda_env(spec: Any) -> str:
    """Named-environment support (reference _private/runtime_env/
    conda.py): {'conda': 'env-name'} injects that existing env's
    site-packages. Creating envs from a dependency dict is out of
    scope for a TPU-image deployment (images are baked); gated with a
    clear error either way when conda is absent."""
    conda = os.environ.get("RAY_TPU_CONDA_BIN") or shutil.which("conda")
    if not conda:
        raise RuntimeError(
            "runtime_env {'conda': ...} requires the `conda` binary on "
            "the worker host (not found on PATH)")
    if not isinstance(spec, str):
        raise RuntimeError(
            "only named conda envs are supported ({'conda': 'name'}); "
            "bake dependency-dict envs into the image instead")
    proc = subprocess.run([conda, "info", "--json"],
                          capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"conda info failed: {proc.stderr}")
    info = json.loads(proc.stdout)
    for env_dir in info.get("envs", []):
        if os.path.basename(env_dir) == spec:
            return _site_packages_of(env_dir)
    raise RuntimeError(f"conda env {spec!r} not found on this host "
                       f"(envs: {info.get('envs', [])})")


# ------------------------------------------------------------ container
def has_container(renv: Optional[dict]) -> bool:
    return bool(renv and (renv.get("container")
                          or renv.get("image_uri")))


def container_command(renv: dict, inner_cmd: List[str]) -> List[str]:
    """Wrap a worker spawn command to run inside the env's container
    image (reference _private/runtime_env/image_uri.py: the worker
    process itself starts inside the container; an already-running
    worker cannot enter one). Engine discovery: RAY_TPU_CONTAINER_
    RUNTIME (tests point it at a stub), else podman, else docker.
    The image must bundle a compatible python + ray_tpu."""
    spec = renv.get("container") or {}
    if isinstance(spec, str):
        spec = {"image": spec}
    image = spec.get("image") or renv.get("image_uri")
    if not image:
        raise RuntimeError("container runtime_env needs an 'image'")
    engine = (os.environ.get("RAY_TPU_CONTAINER_RUNTIME")
              or shutil.which("podman") or shutil.which("docker"))
    if not engine:
        raise RuntimeError(
            f"runtime_env container image {image!r} requires podman or "
            f"docker on the worker host (neither found)")
    cmd = [engine, "run", "--rm", "--network", "host",
           "-v", f"{_CACHE_ROOT}:{_CACHE_ROOT}"]
    for env_key in ("RAY_TPU_WORKER_ID", "RAY_TPU_NODE_ID",
                    "RAY_TPU_SESSION", "RAY_TPU_AUTH_TOKEN"):
        cmd += ["-e", env_key]
    cmd += list(spec.get("run_options", []))
    cmd.append(image)
    return cmd + inner_cmd


# ------------------------------------------------------------- build lock
def _locked_build(dest: str, build_fn) -> None:
    """Build into a temp dir then atomically rename, serialized by a
    lock file so concurrent workers build once (reference pip.py uses
    the same create-lock pattern per node)."""
    import fcntl
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    lock_path = dest + ".lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if os.path.exists(os.path.join(dest, ".ready")):
                return
            tmp = tempfile.mkdtemp(dir=os.path.dirname(dest),
                                   prefix=".build_")
            try:
                build_fn(tmp)
                with open(os.path.join(tmp, ".ready"), "w") as f:
                    f.write("ok")
                if os.path.exists(dest):
                    shutil.rmtree(dest, ignore_errors=True)
                os.rename(tmp, dest)
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)
