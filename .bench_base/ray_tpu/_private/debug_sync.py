"""Lock-order-inversion detection for the pure-Python runtime.

SURVEY §5.2 parity: the reference runs its C++ core under
TSan/deadlock sanitizers in CI (reference .bazelrc tsan configs,
BUILD sanitizer toggles). A pure-Python runtime has no TSan, but the
failure mode those configs exist to catch — two threads taking the
same pair of locks in opposite orders — is detectable the same way
TSan's deadlock detector does it: record the acquisition graph and
flag the first edge that closes a cycle, at the moment it is taken,
whether or not the schedule actually deadlocks this run.

Enable with ``RAY_TPU_DEBUG_LOCKS=1``: runtime subsystems create their
mutexes via :func:`make_lock`, which returns an :class:`OrderedLock`
recording, per thread, the stack of held locks and, globally, every
held->acquiring edge with the stack trace that created it. A cycle
raises :class:`LockOrderInversion` (fail-fast in tests) or, with
``RAY_TPU_DEBUG_LOCKS=warn``, writes the report to stderr and
continues. Disabled (the default), make_lock returns a plain
``threading.Lock`` — zero overhead in production.
"""
from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, Optional, Set

__all__ = ["make_lock", "LockOrderInversion", "lock_report",
           "reset_lock_graph", "enabled"]


class LockOrderInversion(RuntimeError):
    """Two lock sites acquired in inconsistent order across threads."""


def enabled() -> str:
    """"" (off), "raise", or "warn"."""
    v = os.environ.get("RAY_TPU_DEBUG_LOCKS", "").strip().lower()
    if v in ("", "0", "false"):
        return ""
    return "warn" if v == "warn" else "raise"


class _LockGraph:
    """Global acquisition-order graph: edge A->B means some thread
    acquired B while holding A. A path B~>A existing when edge A->B is
    added is an inversion."""

    def __init__(self):
        self._mu = threading.Lock()
        self._edges: Dict[str, Set[str]] = {}
        self._sites: Dict[tuple, str] = {}   # (a, b) -> formatted stack
        self._inversions: list[dict] = []

    def add_edge(self, held: str, acquiring: str, mode: str) -> None:
        with self._mu:
            if acquiring in self._edges.get(held, ()):
                return                      # known-good edge
            # cycle check BEFORE recording: path acquiring ~> held?
            if self._path_exists(acquiring, held):
                prior = self._sites.get((acquiring, held)) or next(
                    (s for (a, _b), s in self._sites.items()
                     if a == acquiring), "<site unknown>")
                here = "".join(traceback.format_stack(limit=8)[:-1])
                report = {
                    "cycle": f"{held} -> {acquiring} -> ... -> {held}",
                    "this_order": f"{held} held while acquiring "
                                  f"{acquiring}",
                    "this_site": here,
                    "reverse_site": prior,
                }
                self._inversions.append(report)
                msg = (f"lock-order inversion: {report['cycle']}\n"
                       f"--- this acquisition ({report['this_order']}) "
                       f"---\n{here}\n--- reverse-order site ---\n"
                       f"{prior}")
                if mode == "raise":
                    raise LockOrderInversion(msg)
                import sys
                sys.stderr.write("ray_tpu DEBUG_LOCKS: " + msg + "\n")
                return
            self._edges.setdefault(held, set()).add(acquiring)
            self._sites[(held, acquiring)] = "".join(
                traceback.format_stack(limit=8)[:-1])

    def _path_exists(self, src: str, dst: str) -> bool:
        seen = set()
        stack = [src]
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(self._edges.get(n, ()))
        return False

    def report(self) -> dict:
        with self._mu:
            return {"locks": sorted({a for a in self._edges}
                                    | {b for bs in self._edges.values()
                                       for b in bs}),
                    "edges": {a: sorted(bs)
                              for a, bs in self._edges.items()},
                    "inversions": list(self._inversions)}

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._sites.clear()
            self._inversions.clear()


_GRAPH = _LockGraph()
_HELD = threading.local()            # per-thread list of held lock names


def lock_report() -> dict:
    """Acquisition graph + inversions observed so far."""
    return _GRAPH.report()


def reset_lock_graph() -> None:
    _GRAPH.reset()


def _held_stack() -> list:
    held = getattr(_HELD, "stack", None)
    if held is None:
        held = _HELD.stack = []
    return held


def _depths() -> dict:
    d = getattr(_HELD, "depths", None)
    if d is None:
        d = _HELD.depths = {}
    return d


class OrderedLock:
    """Drop-in for ``threading.Lock``/``RLock`` that feeds the order
    graph. Implements the private Condition protocol
    (``_release_save``/``_acquire_restore``/``_is_owned``) so it can
    back a ``threading.Condition``; the held-stack stays accurate
    across ``wait()``."""

    def __init__(self, name: str, mode: str, reentrant: bool = False):
        self._name = name
        self._mode = mode
        self._lock = threading.RLock() if reentrant else threading.Lock()

    # -- lock protocol --
    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        held = _held_stack()
        depths = _depths()
        first = depths.get(self._name, 0) == 0
        if first and held:
            _GRAPH.add_edge(held[-1], self._name, self._mode)
        got = self._lock.acquire(blocking, timeout)
        if got:
            depths[self._name] = depths.get(self._name, 0) + 1
            if first:
                held.append(self._name)
        return got

    def release(self) -> None:
        depths = _depths()
        left = depths.get(self._name, 1) - 1
        if left <= 0:
            depths.pop(self._name, None)
            held = _held_stack()
            if held and held[-1] == self._name:
                held.pop()
            elif self._name in held:     # out-of-order release
                held.remove(self._name)
        else:
            depths[self._name] = left
        self._lock.release()

    def locked(self) -> bool:
        try:
            return self._lock.locked()
        except AttributeError:           # RLock pre-3.12 fallback
            if self._lock.acquire(False):
                self._lock.release()
                return False
            return True

    # -- Condition protocol --
    def _is_owned(self) -> bool:
        inner = getattr(self._lock, "_is_owned", None)
        if inner is not None:
            return inner()
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    def _release_save(self):
        depths = _depths()
        d = depths.pop(self._name, 1)
        held = _held_stack()
        if self._name in held:
            held.remove(self._name)
        inner = getattr(self._lock, "_release_save", None)
        if inner is not None:
            return ("r", inner(), d)
        self._lock.release()
        return ("p", None, d)

    def _acquire_restore(self, state) -> None:
        kind, inner_state, d = state
        # no edge recording: a condvar re-acquire resumes logical
        # ownership, it is not a fresh lock-ordering decision
        if kind == "r":
            self._lock._acquire_restore(inner_state)
        else:
            self._lock.acquire()
        _depths()[self._name] = d
        _held_stack().append(self._name)

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"OrderedLock({self._name!r})"


def make_lock(name: str, reentrant: bool = False) -> object:
    """A mutex for a named runtime subsystem: plain
    ``threading.Lock``/``RLock`` normally, an order-tracking
    :class:`OrderedLock` under ``RAY_TPU_DEBUG_LOCKS``."""
    mode = enabled()
    if not mode:
        return threading.RLock() if reentrant else threading.Lock()
    return OrderedLock(name, mode, reentrant=reentrant)
