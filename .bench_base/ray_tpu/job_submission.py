"""Job submission: run driver scripts as managed subprocesses.

Parity: reference dashboard/modules/job (JobSubmissionClient + JobManager
driving a supervisor that spawns the entrypoint with its runtime_env,
tracking status and capturing logs). Re-shaped for this stack: jobs are
subprocesses of the submitting driver's host (the single-head topology),
with env fanout, captured logs, status polling, and stop.
"""
from __future__ import annotations

import dataclasses
import os
import subprocess
import tempfile
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"


@dataclasses.dataclass
class JobInfo:
    job_id: str
    entrypoint: str
    status: str = PENDING
    return_code: Optional[int] = None
    submitted_at: float = dataclasses.field(default_factory=time.time)
    ended_at: Optional[float] = None
    log_path: str = ""
    metadata: Dict[str, str] = dataclasses.field(default_factory=dict)


class JobSubmissionClient:
    """Submit/inspect/stop jobs (reference JobSubmissionClient API:
    submit_job, get_job_status, get_job_logs, list_jobs, stop_job)."""

    def __init__(self, log_dir: Optional[str] = None):
        self._log_dir = log_dir or os.path.join(
            tempfile.gettempdir(), f"rtpu_jobs_{os.getpid()}")
        os.makedirs(self._log_dir, exist_ok=True)
        self._jobs: Dict[str, JobInfo] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[Dict[str, str]] = None,
                   job_id: Optional[str] = None) -> str:
        from ray_tpu.api import validate_runtime_env
        renv = validate_runtime_env(runtime_env) or {}
        job_id = job_id or "job_" + uuid.uuid4().hex[:10]
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"job {job_id!r} already exists")
        log_path = os.path.join(self._log_dir, f"{job_id}.log")
        env = dict(os.environ)
        env.update(renv.get("env_vars") or {})
        env["RAY_TPU_JOB_ID"] = job_id
        cwd = renv.get("working_dir") or None
        # pip / py_modules for a job (a subprocess on THIS host) become
        # PYTHONPATH entries: the venv's site-packages materializes via
        # the per-host cache; py_modules local paths ride directly
        # (never silently ignore a validated option)
        extra_paths = []
        if renv.get("pip"):
            from ray_tpu._private.runtime_env import ensure_pip_env
            extra_paths.append(ensure_pip_env(renv["pip"]))
        for m in renv.get("py_modules") or []:
            if isinstance(m, str):
                extra_paths.append(os.path.dirname(os.path.abspath(m))
                                   if os.path.isfile(m)
                                   else os.path.dirname(
                                       os.path.abspath(m.rstrip("/"))))
            else:
                raise ValueError(
                    "job py_modules entries must be local paths")
        if extra_paths:
            env["PYTHONPATH"] = os.pathsep.join(
                extra_paths + [env.get("PYTHONPATH", "")]).rstrip(
                    os.pathsep)
        info = JobInfo(job_id=job_id, entrypoint=entrypoint,
                       log_path=log_path, metadata=dict(metadata or {}))
        log_f = open(log_path, "wb")
        proc = subprocess.Popen(
            entrypoint, shell=True, stdout=log_f, stderr=log_f,
            env=env, cwd=cwd)
        log_f.close()
        info.status = RUNNING
        with self._lock:
            self._jobs[job_id] = info
            self._procs[job_id] = proc
        threading.Thread(target=self._reap, args=(job_id,),
                         daemon=True).start()
        return job_id

    def _reap(self, job_id: str) -> None:
        proc = self._procs[job_id]
        rc = proc.wait()
        with self._lock:
            info = self._jobs[job_id]
            if info.status == RUNNING:
                info.status = SUCCEEDED if rc == 0 else FAILED
            info.return_code = rc
            info.ended_at = time.time()

    def get_job_status(self, job_id: str) -> str:
        return self._info(job_id).status

    def get_job_info(self, job_id: str) -> JobInfo:
        return self._info(job_id)

    def get_job_logs(self, job_id: str) -> str:
        info = self._info(job_id)
        try:
            with open(info.log_path, "r", errors="replace") as f:
                return f.read()
        except FileNotFoundError:
            return ""

    def list_jobs(self) -> List[JobInfo]:
        with self._lock:
            return list(self._jobs.values())

    def list_log_files(self) -> List[Dict[str, Any]]:
        """Log files in this client's log dir (dashboard /api/logs)."""
        out = []
        for info in self.list_jobs():
            try:
                size = os.path.getsize(info.log_path)
            except OSError:
                size = 0
            out.append({"job_id": info.job_id, "path": info.log_path,
                        "size_bytes": size, "status": info.status})
        return out

    def tail_logs(self, job_id: str, lines: int = 200) -> List[str]:
        """Last N lines of a job's log (dashboard /api/logs/<job>)."""
        text = self.get_job_logs(job_id)
        return text.splitlines()[-max(1, lines):]

    def stop_job(self, job_id: str) -> bool:
        info = self._info(job_id)
        proc = self._procs.get(job_id)
        if proc is None or proc.poll() is not None:
            return False
        with self._lock:
            info.status = STOPPED
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        return True

    def wait_until_finished(self, job_id: str,
                            timeout: float = 300.0) -> str:
        deadline = time.time() + timeout
        status = self.get_job_status(job_id)
        while True:
            if status in (SUCCEEDED, FAILED, STOPPED):
                return status
            if time.time() >= deadline:
                raise TimeoutError(f"job {job_id} still {status} after "
                                   f"{timeout}s")
            time.sleep(0.2)
            status = self.get_job_status(job_id)

    def _info(self, job_id: str) -> JobInfo:
        with self._lock:
            info = self._jobs.get(job_id)
        if info is None:
            raise ValueError(f"no job {job_id!r}")
        return info


_DEFAULT_CLIENT = None


def default_client() -> "JobSubmissionClient":
    """Process-wide client (the dashboard's job/log endpoints use it, so
    jobs submitted through it are the ones observability surfaces)."""
    global _DEFAULT_CLIENT
    if _DEFAULT_CLIENT is None:
        _DEFAULT_CLIENT = JobSubmissionClient()
    return _DEFAULT_CLIENT
