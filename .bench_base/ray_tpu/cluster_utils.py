"""In-process multi-node cluster harness.

Parity: reference python/ray/cluster_utils.py:135 (Cluster/add_node) —
multiple per-node schedulers (each owning real worker subprocesses) run
inside the driver process, so scheduling, spillback, placement groups,
and node-failure recovery are exercised without real multi-host
infrastructure. `kill_node` simulates abrupt node death that the health
monitor must detect, mirroring the reference's killer-actor fault
pattern (_private/test_utils.py:1433).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private import context as _context


class Cluster:
    """Drives the ClusterTaskManager of the active runtime."""

    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None):
        import ray_tpu
        args = dict(head_node_args or {})
        self._rt = ray_tpu.init(**args) if initialize_head else (
            _context.get_ctx())

    @property
    def _cluster(self):
        return self._rt.cluster

    def add_node(self, num_cpus: float = 1.0,
                 num_tpus: float = 0.0,
                 resources: Optional[Dict[str, float]] = None,
                 max_workers: Optional[int] = None,
                 labels: Optional[Dict[str, str]] = None) -> str:
        """Add a simulated node; returns its node_id."""
        res = {"CPU": float(num_cpus)}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        if resources:
            res.update({k: float(v) for k, v in resources.items()})
        rec = self._cluster.add_node(res, max_workers=max_workers,
                                     labels=labels)
        return rec.node_id

    def remove_node(self, node_id: str) -> None:
        """Graceful removal: drain + recover the node's work."""
        self._cluster.remove_node(node_id, graceful=True)

    def kill_node(self, node_id: str) -> None:
        """Abrupt death: workers SIGKILLed, heartbeat stops; the health
        monitor detects and recovers (reference RayletKiller pattern)."""
        self._cluster.remove_node(node_id, graceful=False)

    def list_nodes(self) -> List[dict]:
        return self._rt.controller.list_nodes()

    def alive_node_ids(self) -> List[str]:
        return [n.node_id for n in self._cluster.alive_nodes()]

    def wait_for_nodes(self, n: int, timeout: float = 10.0) -> bool:
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self._cluster.alive_nodes()) >= n:
                return True
            time.sleep(0.05)
        return False


class NodeAgentProcess:
    """A REAL node-agent subprocess joined to the active head over TCP —
    the honest multi-host topology (vs Cluster's in-process nodes).
    Reference analogue: `ray start --address=<head>` spawning a raylet
    that registers with the remote GCS (gcs_node_manager.h:62)."""

    def __init__(self, head_address: Optional[tuple] = None,
                 num_cpus: float = 2.0, num_tpus: float = 0.0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 max_workers: Optional[int] = None,
                 node_id: Optional[str] = None):
        import json
        import os
        import subprocess
        import sys
        import uuid
        if head_address is None:
            head_address = _context.get_ctx().address
        self.node_id = node_id or ("node_" + uuid.uuid4().hex[:8])
        args = [sys.executable, "-m", "ray_tpu._private.node_agent",
                "--head", f"{head_address[0]}:{head_address[1]}",
                "--num-cpus", str(num_cpus), "--num-tpus", str(num_tpus),
                "--bind", "127.0.0.1", "--advertise", "127.0.0.1",
                "--node-id", self.node_id]
        if resources:
            args += ["--resources", json.dumps(resources)]
        if labels:
            args += ["--labels", json.dumps(labels)]
        if max_workers is not None:
            args += ["--max-workers", str(max_workers)]
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        self.proc = subprocess.Popen(args, env=env)

    def kill(self) -> None:
        """Abrupt agent death (SIGKILL): the head's failure detection
        must notice via connection loss / heartbeat staleness."""
        try:
            self.proc.kill()
        except Exception:
            pass

    def terminate(self) -> None:
        try:
            self.proc.terminate()
        except Exception:
            pass

    def wait(self, timeout: Optional[float] = 10.0) -> None:
        try:
            self.proc.wait(timeout=timeout)
        except Exception:
            self.kill()
