"""Mixture-of-Experts FFN: top-k routing + capacity-based dispatch.

Expert parallelism the TPU way (SURVEY.md §2.4 EP row — absent from the
reference in-tree, delivered here natively): expert weights carry the
"experts" logical axis (→ ep mesh axis), dispatch/combine are dense
einsums whose sharding constraints make XLA insert the token all-to-all
over ICI — no ragged buffers, no host-side routing. GShard-style
capacity discipline: each expert processes at most
`ceil(tokens·top_k/num_experts · capacity_factor)` tokens; overflow
tokens fall through the residual connection (standard drop semantics).

Parity property used by tests: with every expert initialised to the
same weights, normalised top-k routing makes the MoE block exactly
equal to its dense FFN (Σ w_k · F(x) = F(x)), so correctness reduces to
dense-FFN parity plus sharding-invariance on an ep>1 mesh.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_jitter: float = 0.0    # train-time router noise (0 = off)


def expert_capacity(num_tokens: int, num_experts: int, top_k: int,
                    capacity_factor: float) -> int:
    return max(1, int(math.ceil(
        num_tokens * top_k / num_experts * capacity_factor)))


def moe_ffn(x: jax.Array,
            router_w: jax.Array,
            gate_w: jax.Array, up_w: jax.Array, down_w: jax.Array,
            *, top_k: int, capacity_factor: float,
            constrain=None,
            rngs: Optional[jax.Array] = None,
            router_jitter: float = 0.0
            ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Apply the MoE FFN block.

    x: (b, s, d). router_w: (d, E). gate/up_w: (E, d, f); down_w:
    (E, f, d). `constrain(arr, logical_axes)` applies sharding
    constraints (models pass their mesh-bound constrainer). Returns
    (output (b, s, d), aux metrics incl. load-balance loss).
    """
    b, s, d = x.shape
    E = router_w.shape[-1]
    T = b * s
    C = expert_capacity(T, E, top_k, capacity_factor)
    cdtype = x.dtype

    xf = x.reshape(T, d)
    logits = (xf @ router_w.astype(cdtype)).astype(jnp.float32)  # (T, E)
    if router_jitter and rngs is not None:
        logits = logits + router_jitter * jax.random.normal(
            rngs, logits.shape)
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_e = lax.top_k(probs, top_k)                    # (T, k)
    top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)    # renorm

    # Position of each (token, k) assignment within its expert's queue:
    # flatten assignments k-major so k=0 choices win capacity ties.
    assign = jax.nn.one_hot(top_e, E, dtype=jnp.int32)        # (T, k, E)
    flat = assign.transpose(1, 0, 2).reshape(top_k * T, E)    # (kT, E)
    pos_flat = jnp.cumsum(flat, axis=0) - flat                # (kT, E)
    pos = pos_flat.reshape(top_k, T, E).transpose(1, 0, 2)    # (T, k, E)
    within = (pos * assign).sum(-1)                           # (T, k)
    keep = within < C                                         # capacity

    # dispatch (T, k, E, C) one-hot -> collapsed over k to (T, E, C)
    disp = (assign[..., None]
            * jax.nn.one_hot(within, C, dtype=jnp.int32)[:, :, None, :]
            * keep[:, :, None, None].astype(jnp.int32))       # (T,k,E,C)
    combine = (disp.astype(jnp.float32)
               * top_p[:, :, None, None]).sum(1)              # (T, E, C)
    dispatch = disp.sum(1).astype(cdtype)                     # (T, E, C)

    # expert inputs: the big resharding einsum — tokens (dp-sharded)
    # -> expert-major (ep-sharded): XLA inserts the all-to-all here.
    ein = jnp.einsum("tec,td->ecd", dispatch, xf)             # (E, C, d)
    if constrain is not None:
        ein = constrain(ein, ("experts", "expert_capacity", "embed"))
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", ein,
                               gate_w.astype(cdtype)))
    h = h * jnp.einsum("ecd,edf->ecf", ein, up_w.astype(cdtype))
    if constrain is not None:
        h = constrain(h, ("experts", "expert_capacity", "mlp"))
    eout = jnp.einsum("ecf,efd->ecd", h, down_w.astype(cdtype))
    if constrain is not None:
        eout = constrain(eout, ("experts", "expert_capacity", "embed"))

    y = jnp.einsum("tec,ecd->td", combine.astype(cdtype), eout)
    y = y.reshape(b, s, d)

    # Aux: switch-style load-balance loss + routing stats.
    frac_tokens = jnp.mean(assign[:, 0, :].astype(jnp.float32), axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    lb_loss = E * jnp.sum(frac_tokens * frac_probs)
    dropped = 1.0 - (jnp.sum(dispatch) / (T * top_k))
    return y, {"moe_load_balance_loss": lb_loss,
               "moe_dropped_fraction": dropped.astype(jnp.float32)}


MOE_PARAM_AXES = {
    "router": ("embed", None),
    "moe_gate": ("experts", "embed", "mlp"),
    "moe_up": ("experts", "embed", "mlp"),
    "moe_down": ("experts", "mlp", "embed"),
}
