"""Llama-family decoder: GQA + RoPE + SwiGLU on ray_tpu.ops kernels.

Pure-pytree parameters (no module framework): `init` builds the tree,
`param_logical_axes` mirrors it with logical axis names consumed by
ray_tpu.parallel.sharding, `apply`/`loss` are jit-friendly functions.
Layers are stacked on a leading "layers" axis and executed with
`lax.scan` so XLA compiles one layer body regardless of depth; with
`config.remat` the body is wrapped in `jax.checkpoint` trading FLOPs
for HBM (SURVEY.md §7 hardware notes).
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from ray_tpu.models.config import TransformerConfig
from ray_tpu.ops.attention import flash_attention
from ray_tpu.ops.losses import softmax_cross_entropy
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.ring_attention import ring_attention_sharded
from ray_tpu.parallel.sharding import with_logical_constraint

Params = Dict[str, Any]

# Activation logical axes (all optional constraints; params use the
# rules in parallel.sharding directly).
_ACT_RULES_EXTRA = {"act_embed": None, "expert_capacity": None}


def _rules():
    from ray_tpu.parallel.sharding import LOGICAL_AXIS_RULES
    rules = dict(LOGICAL_AXIS_RULES)
    rules.update(_ACT_RULES_EXTRA)
    return rules


class Transformer:
    """Functional model bundle for one TransformerConfig."""

    def __init__(self, config: TransformerConfig,
                 mesh: Optional[Mesh] = None):
        self.config = config
        self.mesh = mesh

    def _platform(self):
        """Platform the forward will actually run on: the mesh's devices
        when bound to a mesh (may differ from the default backend — e.g.
        a virtual CPU mesh on a TPU host), else the default backend."""
        if self.mesh is None:
            return None
        from ray_tpu.ops.dispatch import mesh_platform
        return mesh_platform(self.mesh)

    # ------------------------------------------------------------ init
    def init(self, key: jax.Array) -> Params:
        c = self.config
        pd = c.parameter_dtype
        e, f, hd = c.d_model, c.d_ff, c.head_dim
        qd, kvd = c.n_heads * hd, c.kv_heads * hd
        k = iter(jax.random.split(key, 16))
        std = 0.02
        out_std = std / math.sqrt(2 * c.n_layers)

        def w(key, shape, scale):
            return (jax.random.normal(key, shape, jnp.float32)
                    * scale).astype(pd)

        L = c.n_layers
        layers: Params = {
            "attn_norm": jnp.zeros((L, e), pd),
            "wq": w(next(k), (L, e, qd), std),
            "wk": w(next(k), (L, e, kvd), std),
            "wv": w(next(k), (L, e, kvd), std),
            "wo": w(next(k), (L, qd, e), out_std),
            "mlp_norm": jnp.zeros((L, e), pd),
        }
        if c.moe_num_experts:
            E = c.moe_num_experts
            layers.update({
                "router": w(next(k), (L, e, E), std),
                "moe_gate": w(next(k), (L, E, e, f), std),
                "moe_up": w(next(k), (L, E, e, f), std),
                "moe_down": w(next(k), (L, E, f, e), out_std),
            })
        else:
            layers.update({
                "gate": w(next(k), (L, e, f), std),
                "up": w(next(k), (L, e, f), std),
                "down": w(next(k), (L, f, e), out_std),
            })
        params: Params = {
            "embed": w(next(k), (c.vocab_size, e), std),
            "layers": layers,
            "final_norm": jnp.zeros((e,), pd),
        }
        if not c.tie_embeddings:
            params["lm_head"] = w(next(k), (e, c.vocab_size), std)
        return params

    def param_logical_axes(self) -> Params:
        layers = {
            "attn_norm": ("layers", "embed"),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "mlp_norm": ("layers", "embed"),
        }
        if self.config.moe_num_experts:
            layers.update({
                "router": ("layers", "embed", None),
                "moe_gate": ("layers", "experts", "embed", "mlp"),
                "moe_up": ("layers", "experts", "embed", "mlp"),
                "moe_down": ("layers", "experts", "mlp", "embed"),
            })
        else:
            layers.update({
                "gate": ("layers", "embed", "mlp"),
                "up": ("layers", "embed", "mlp"),
                "down": ("layers", "mlp", "embed"),
            })
        axes = {
            "embed": ("vocab", "embed"),
            "layers": layers,
            "final_norm": ("embed",),
        }
        if not self.config.tie_embeddings:
            axes["lm_head"] = ("embed", "vocab")
        return axes

    # --------------------------------------------------------- forward
    def _attention(self, q, k, v):
        c = self.config
        if (c.use_ring_attention and self.mesh is not None
                and self.mesh.shape.get("sp", 1) > 1):
            return ring_attention_sharded(q, k, v, self.mesh, causal=True)
        if c.remat and c.remat_policy == "save_attn":
            from ray_tpu.ops.attention import flash_attention_saveable
            from ray_tpu.ops.dispatch import on_tpu
            if on_tpu():
                return flash_attention_saveable(
                    q, k, v, causal=True, block_q=c.attn_block_q,
                    block_k=c.attn_block_k)
            # off-TPU the einsum fallback has no kernel to spare; plain
            # path keeps CPU tests exercising the same math.
        return flash_attention(q, k, v, causal=True,
                               block_q=c.attn_block_q,
                               block_k=c.attn_block_k)

    def _constrain(self, x, axes):
        if self.mesh is None:
            return x
        return with_logical_constraint(x, axes, mesh=self.mesh,
                                       rules=_rules())

    def _embed_lookup(self, table, tokens):
        """Token embedding. With the table sharded (vocab->tp,
        embed->fsdp) a gather forces SPMD involuntary full
        rematerialization (xla spmd_partitioner.cc:652); the one-hot
        contraction partitions cleanly (the vocab axis reduces with a
        psum over tp) and runs on the MXU, so it is what the sharded
        path uses — the same trade MaxText makes on TPU."""
        m = self.mesh
        if m is None or (m.shape.get("tp", 1) == 1
                         and m.shape.get("fsdp", 1) == 1):
            return table[tokens]
        onehot = jax.nn.one_hot(tokens, self.config.vocab_size,
                                dtype=table.dtype)
        onehot = self._constrain(onehot, ("batch", "seq", "vocab"))
        return onehot @ table

    def _layer(self, x, layer: Params, rope):
        """One block; returns (x, moe_aux_loss) — 0.0 for dense FFN."""
        c = self.config
        ad = c.activation_dtype
        b, s, e = x.shape
        hd = c.head_dim

        h = rms_norm(x, layer["attn_norm"], c.norm_eps)
        q = (h @ layer["wq"].astype(ad)).reshape(b, s, c.n_heads, hd)
        k = (h @ layer["wk"].astype(ad)).reshape(b, s, c.kv_heads, hd)
        v = (h @ layer["wv"].astype(ad)).reshape(b, s, c.kv_heads, hd)
        from ray_tpu.ops.rope import apply_rope_cached
        cos, sin = rope
        q = apply_rope_cached(q, cos, sin)
        k = apply_rope_cached(k, cos, sin)
        q = q.transpose(0, 2, 1, 3)   # (b, h, s, hd)
        k = k.transpose(0, 2, 1, 3)
        v = v.transpose(0, 2, 1, 3)
        q = self._constrain(q, ("batch", "heads", "seq", "head_dim"))
        attn = self._attention(q, k, v)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, s, c.n_heads * hd)
        x = x + attn @ layer["wo"].astype(ad)
        x = self._constrain(x, ("batch", "seq", "act_embed"))

        h = rms_norm(x, layer["mlp_norm"], c.norm_eps)
        if c.moe_num_experts:
            from ray_tpu.models.moe import moe_ffn
            y, aux = moe_ffn(
                h, layer["router"], layer["moe_gate"], layer["moe_up"],
                layer["moe_down"], top_k=c.moe_top_k,
                capacity_factor=c.moe_capacity_factor,
                constrain=(None if self.mesh is None else
                           lambda a, ax: self._constrain(a, ax)))
            x = x + y
            return (self._constrain(x, ("batch", "seq", "act_embed")),
                    aux["moe_load_balance_loss"])
        gate = jax.nn.silu(h @ layer["gate"].astype(ad))
        up = h @ layer["up"].astype(ad)
        mlp = self._constrain(gate * up, ("batch", "seq", "mlp"))
        x = x + mlp @ layer["down"].astype(ad)
        return (self._constrain(x, ("batch", "seq", "act_embed")),
                jnp.float32(0.0))

    def hidden(self, params: Params, tokens: jax.Array,
               positions: Optional[jax.Array] = None) -> jax.Array:
        """Trunk: tokens (b, s) -> post-final-norm hidden states (b, s, e)."""
        return self.hidden_and_aux(params, tokens, positions)[0]

    def hidden_and_aux(self, params: Params, tokens: jax.Array,
                       positions: Optional[jax.Array] = None):
        """(hidden states, summed MoE load-balance loss across layers)."""
        from ray_tpu.ops.dispatch import compute_platform
        with compute_platform(self._platform()):
            return self._hidden(params, tokens, positions)

    def _hidden(self, params: Params, tokens: jax.Array,
                positions: Optional[jax.Array] = None):
        c = self.config
        ad = c.activation_dtype
        b, s = tokens.shape
        custom_positions = positions is not None
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s), (b, s))
        x = self._embed_lookup(params["embed"].astype(ad), tokens)
        x = self._constrain(x, ("batch", "seq", "act_embed"))

        # cos/sin computed once; identical for every layer and cheap to
        # hold across remat (transcendentals dominate their recompute).
        from ray_tpu.ops.rope import rope_cos_sin
        rope = rope_cos_sin(positions, c.head_dim, c.rope_theta)

        remat_policy = None
        if c.remat and c.remat_policy == "save_attn":
            from ray_tpu.ops.attention import attn_remat_policy
            remat_policy = attn_remat_policy()

        def _checkpointed(body):
            if c.remat:
                # prevent_cse=False: scan's loop structure already blocks
                # the CSE hazard; True inserts unfusable barriers.
                return jax.checkpoint(body, prevent_cse=False,
                                      policy=remat_policy)
            return body

        if (self.mesh is not None and self.mesh.shape.get("pp", 1) > 1
                and c.pipeline_microbatches > 0):
            if c.moe_num_experts:
                raise NotImplementedError(
                    "MoE + pipeline parallelism is not supported yet "
                    "(the pipeline stage carries activations only)")
            if custom_positions:
                raise NotImplementedError(
                    "pipeline parallelism assumes default positions "
                    "(rope caches are sliced per microbatch, which is "
                    "only exact when rows share the arange positions); "
                    "pass positions=None with pp>1")
            from ray_tpu.parallel.pipeline import pipeline_apply

            # rope rides as explicit consts: closures over tracers don't
            # cross the shard_map manual region. Caches are full-batch;
            # rows are identical (positions broadcast from arange), so
            # slicing to the microbatch is exact.
            def stage(stage_layers, xm, cos, sin):
                rope_mb = (cos[:xm.shape[0]], sin[:xm.shape[0]])

                def sbody(carry, layer):
                    y, _lb = self._layer(carry, layer, rope_mb)
                    return y, None
                out, _ = lax.scan(_checkpointed(sbody), xm, stage_layers)
                return out

            x = pipeline_apply(self.mesh, stage, params["layers"], x,
                               c.pipeline_microbatches, consts=rope)
            return (rms_norm(x, params["final_norm"], c.norm_eps),
                    jnp.float32(0.0))

        def body(carry, layer):
            x, aux = carry
            x, lb = self._layer(x, layer, rope)
            return (x, aux + lb), None

        (x, moe_aux), _ = lax.scan(_checkpointed(body),
                                   (x, jnp.float32(0.0)),
                                   params["layers"])
        return rms_norm(x, params["final_norm"], c.norm_eps), moe_aux

    def _head(self, params: Params) -> jax.Array:
        return (params["embed"].T if self.config.tie_embeddings
                else params["lm_head"])

    def apply(self, params: Params, tokens: jax.Array,
              positions: Optional[jax.Array] = None) -> jax.Array:
        """tokens (b, s) int32 -> logits (b, s, vocab) in f32."""
        c = self.config
        x = self.hidden(params, tokens, positions)
        logits = x @ self._head(params).astype(c.activation_dtype)
        logits = self._constrain(logits, ("batch", "seq", "vocab"))
        return logits.astype(jnp.float32)

    # ------------------------------------------------------------ loss
    def loss(self, params: Params, batch: Dict[str, jax.Array]):
        """Causal LM loss. batch: tokens (b, s); optional loss_mask
        (b, s) aligned with tokens-as-labels: loss_mask[i] = 0 excludes
        token i from being counted as a prediction target (use 0 on
        prompt/padding tokens, 1 on completion tokens)."""
        c = self.config
        tokens = batch["tokens"]
        mask = batch.get("loss_mask")

        def moe_term(aux):
            if not c.moe_num_experts:
                return 0.0
            return c.moe_aux_coef * aux / c.n_layers

        if c.loss_chunk:
            # Full-length formulation (keeps seq divisible by the chunk):
            # labels[i] = tokens[i+1], with the final position masked out.
            from ray_tpu.ops.losses import chunked_lm_loss
            b, s = tokens.shape
            x, aux = self.hidden_and_aux(params, tokens)
            labels = jnp.concatenate([tokens[:, 1:], tokens[:, :1]], axis=1)
            m = (jnp.ones((b, s), jnp.float32) if mask is None
                 else mask.astype(jnp.float32))
            m = jnp.concatenate([m[:, 1:], jnp.zeros((b, 1))], axis=1)
            head = self._head(params).astype(c.activation_dtype)
            return chunked_lm_loss(x, head, labels, m,
                                   chunk_size=c.loss_chunk) + moe_term(aux)
        x, aux = self.hidden_and_aux(params, tokens)
        logits = x @ self._head(params).astype(c.activation_dtype)
        logits = self._constrain(logits,
                                 ("batch", "seq", "vocab"))
        logits = logits.astype(jnp.float32)[:, :-1]
        labels = tokens[:, 1:]
        if mask is not None:
            mask = mask[:, 1:]
        loss, _ = softmax_cross_entropy(logits, labels, mask=mask)
        return loss + moe_term(aux)
