"""Transformer configuration + presets.

Presets cover the reference's LLM workloads (Llama-2-7B fine-tune is the
headline release test, reference release/release_tests.yaml:963-1010) and
small debug models for CI.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: Optional[int] = None      # None = MHA
    d_ff: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: str = "bfloat16"               # activation/compute dtype
    param_dtype: str = "float32"
    remat: bool = True                    # checkpoint each layer in scan
    # "full": recompute everything in bwd (min HBM). "save_attn": save
    # flash-attention out+lse across the checkpoint so the fwd kernel is
    # not re-run in bwd (~(b,s,d_model) bf16 + (b,h,s) f32 per layer).
    remat_policy: str = "full"
    use_ring_attention: bool = False      # seq-parallel attention (sp axis)
    # >0 with a pp>1 mesh: run the layer stack as a GPipe microbatch
    # pipeline over the pp axis (parallel/pipeline.py). Bubble fraction
    # is (pp-1)/(M+pp-1) — pick M >= 4*pp.
    pipeline_microbatches: int = 0
    attn_block_q: int = 128
    attn_block_k: int = 128
    loss_chunk: int = 0                   # >0: chunked LM loss (seq chunks)
    # --- Mixture of Experts (0 = dense FFN). Experts shard over the ep
    # mesh axis; see models/moe.py for dispatch semantics.
    moe_num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01            # load-balance loss weight

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def activation_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def parameter_dtype(self):
        return jnp.dtype(self.param_dtype)

    def _ffn_params(self, active_only: bool = False) -> int:
        e, f = self.d_model, self.d_ff
        if not self.moe_num_experts:
            return 3 * e * f
        experts = self.moe_top_k if active_only else self.moe_num_experts
        return experts * 3 * e * f + e * self.moe_num_experts  # + router

    def num_params(self, active_only: bool = False) -> int:
        """Parameter count (embeddings + layers + head). With MoE,
        `active_only` counts router + top_k experts per token — the
        number that matters for FLOPs."""
        e, hd = self.d_model, self.head_dim
        per_layer = (e * self.n_heads * hd          # wq
                     + 2 * e * self.kv_heads * hd   # wk, wv
                     + self.n_heads * hd * e        # wo
                     + self._ffn_params(active_only)
                     + 2 * e)                       # two norms
        total = self.vocab_size * e + self.n_layers * per_layer + e
        if not self.tie_embeddings:
            total += e * self.vocab_size
        return total

    def flops_per_token(self) -> float:
        """Approximate training FLOPs/token (fwd+bwd ≈ 6·N_active +
        attention)."""
        n = self.num_params(active_only=True)
        attn = 12 * self.n_layers * self.d_model * self.max_seq_len
        return 6.0 * n + attn


def tiny(vocab_size: int = 256) -> TransformerConfig:
    """CI/debug model: runs on the 8-device CPU mesh in seconds."""
    return TransformerConfig(
        vocab_size=vocab_size, d_model=64, n_layers=2, n_heads=4,
        n_kv_heads=2, d_ff=128, max_seq_len=128, remat=False,
        dtype="float32", param_dtype="float32")


def llama2_7b() -> TransformerConfig:
    return TransformerConfig(
        vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
        n_kv_heads=None, d_ff=11008, max_seq_len=4096)


def llama2_13b() -> TransformerConfig:
    return TransformerConfig(
        vocab_size=32000, d_model=5120, n_layers=40, n_heads=40,
        n_kv_heads=None, d_ff=13824, max_seq_len=4096)


def llama3_8b() -> TransformerConfig:
    return TransformerConfig(
        vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
        n_kv_heads=8, d_ff=14336, max_seq_len=8192, rope_theta=500000.0)


PRESETS = {
    "tiny": tiny,
    "llama2-7b": llama2_7b,
    "llama2-13b": llama2_13b,
    "llama3-8b": llama3_8b,
}
