"""Driver entry points: single-chip compile check + multi-chip dry run.

`entry()` returns a jittable forward step on the flagship model;
`dryrun_multichip(n)` jits the FULL training step (loss + grad + adamw)
over n-device meshes with real dp/fsdp/tp/sp shardings (ring attention
on the sp axis), runs THREE steps on tiny shapes, and asserts the
sharded losses/grad-norms match a single-device ground-truth run — a
sharding-correctness gate, not just an isfinite check.

When the host has fewer than n accelerators (the usual case: a 1-chip
bench host), the dry run self-provisions n virtual CPU devices via
`jax_num_cpu_devices` / `xla_force_host_platform_device_count` and
builds the mesh from them.
"""
from __future__ import annotations

import contextlib
import os
import tempfile

import jax
import jax.numpy as jnp

from ray_tpu.models import Transformer, TransformerConfig
from ray_tpu.parallel import MeshSpec, param_shardings, prepare_mesh, shard_pytree


def _flagship_config(**overrides) -> TransformerConfig:
    base = dict(
        vocab_size=32000, d_model=512, n_layers=8, n_heads=8,
        n_kv_heads=4, d_ff=1408, max_seq_len=512, remat=True,
        dtype="bfloat16", param_dtype="float32")
    base.update(overrides)
    return TransformerConfig(**base)


def entry():
    """(fn, example_args) — jittable forward step, single chip."""
    cfg = _flagship_config()
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0))
    tokens = jnp.zeros((2, 256), jnp.int32)

    def fn(params, tokens):
        return model.apply(params, tokens)

    return fn, (params, tokens)


def _provision_devices(n: int):
    """Return n devices, creating virtual CPU devices when the host has
    fewer than n accelerators (e.g. the 1-chip bench host)."""
    try:
        # Must land before the CPU backend initializes; harmless after.
        jax.config.update("jax_num_cpu_devices", n)
    except Exception:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
    devices = jax.devices()
    if len(devices) >= n:
        return devices[:n]
    cpus = jax.devices("cpu")
    if len(cpus) < n:
        raise RuntimeError(
            f"need {n} devices but have {len(devices)} "
            f"{devices[0].platform} and {len(cpus)} cpu; start the "
            f"process with XLA_FLAGS=--xla_force_host_platform_device_"
            f"count={n} (the CPU backend already initialized too small)")
    return cpus[:n]


def _mesh_specs_for(n: int) -> list:
    """Mesh configs covering all of dp/fsdp/tp/sp/pp across the dry run.

    8 devices can't make every axis nontrivial at once, so for
    n % 8 == 0 we exercise three meshes: (dp,fsdp,tp), (fsdp,tp,sp),
    and (pp,dp,tp) — the last runs the GPipe microbatch schedule
    (parallel/pipeline.py) against the same ground truth.
    """
    if n % 8 == 0:
        return [
            MeshSpec(dp=n // 4, fsdp=2, tp=2, sp=1),
            MeshSpec(dp=n // 8, fsdp=2, tp=2, sp=2),
            MeshSpec(pp=2, dp=n // 4, tp=2),
            MeshSpec(dp=n // 4, ep=2, tp=2),   # MoE expert parallelism
        ]
    if n % 2 == 0:
        return [MeshSpec(dp=n // 2, fsdp=2)]
    return [MeshSpec(dp=n)]


def _tiny_config(use_ring: bool) -> TransformerConfig:
    return _flagship_config(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, remat=False, dtype="float32",
        param_dtype="float32", use_ring_attention=use_ring)


@contextlib.contextmanager
def _capture_stderr(chunks: list):
    """fd-level stderr tee into `chunks` (XLA's C++ compiler warnings —
    e.g. spmd_partitioner.cc involuntary-rematerialization — bypass
    Python's sys.stderr, so dup the fd)."""
    import sys
    sys.stderr.flush()
    saved = os.dup(2)
    with tempfile.TemporaryFile(mode="w+b") as tmp:
        os.dup2(tmp.fileno(), 2)
        try:
            yield
        finally:
            sys.stderr.flush()
            os.dup2(saved, 2)
            os.close(saved)
            tmp.seek(0)
            text = tmp.read().decode("utf-8", "replace")
            chunks.append(text)
            # replay so the log still shows what XLA said
            sys.stderr.write(text)


def _grad_norm(g) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(g)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                        for x in leaves))


def _run_steps(model, mesh, devices_one, tokens, n_steps: int = 3):
    """Run `n_steps` of adamw training; returns (losses, grad_norms).

    With mesh=None the whole computation is pinned to `devices_one`
    (the single-device ground truth)."""
    import optax

    params = model.init(jax.random.PRNGKey(0))
    if mesh is not None:
        params = shard_pytree(params,
                              param_shardings(mesh, model.param_logical_axes()))
    else:
        params = jax.device_put(params, devices_one)
        tokens = jax.device_put(tokens, devices_one)
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def train_step(p, s, batch_):
        loss, g = jax.value_and_grad(model.loss)(p, batch_)
        updates, s2 = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s2, loss, _grad_norm(g)

    from ray_tpu.ops.dispatch import compute_platform
    platform = (None if mesh is not None else devices_one.platform)
    losses, gnorms = [], []
    for _ in range(n_steps):
        with compute_platform(platform):
            params, opt_state, loss, gnorm = train_step(
                params, opt_state, {"tokens": tokens})
        losses.append(float(loss))
        gnorms.append(float(gnorm))
    return losses, gnorms


def dryrun_multichip(n_devices: int) -> None:
    # Pin the WHOLE run to the CPU backend before any backend touch:
    # unsharded work (RNG token generation, the single-device ground
    # truth) would otherwise dispatch to the default TPU backend, which
    # this environment cannot share with the virtual-device dry run
    # (same reason tests/conftest.py pins JAX_PLATFORMS=cpu).
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # verified by the backend check below
    try:
        # BEFORE any backend-initializing call (default_backend below
        # would freeze the CPU backend at its current device count).
        jax.config.update("jax_num_cpu_devices", n_devices)
    except Exception:
        pass  # backend already up; _provision_devices re-checks
    if jax.default_backend() != "cpu":
        # The pin only takes effect if no backend was initialized yet;
        # fail loudly rather than crash later on TPU/CPU mixing.
        raise RuntimeError(
            "dryrun_multichip requires the CPU backend but JAX already "
            f"initialized {jax.default_backend()!r}; run it in a fresh "
            "process (before entry() or any other JAX work)")
    devices = _provision_devices(n_devices)
    for spec in _mesh_specs_for(n_devices):
        mesh = prepare_mesh(spec, devices=devices)
        import dataclasses as _dc
        sp = mesh.shape.get("sp", 1)
        pp = mesh.shape.get("pp", 1)
        ep = mesh.shape.get("ep", 1)
        cfg = _tiny_config(use_ring=sp > 1)
        if pp > 1:
            cfg = _dc.replace(cfg, pipeline_microbatches=2)
        if ep > 1:
            # MoE on the ep axis; generous capacity so the sharded run
            # matches the single-device ground truth exactly.
            cfg = _dc.replace(cfg, moe_num_experts=2 * ep, moe_top_k=2,
                              moe_capacity_factor=4.0)
        model = Transformer(cfg, mesh=mesh)
        # batch divisible by dp*fsdp and by pp microbatches; seq by sp
        batch = max(2, mesh.shape["dp"] * mesh.shape["fsdp"],
                    2 * cfg.pipeline_microbatches)
        seq = 32 * max(sp, 2)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab_size)

        captured: list = []
        with _capture_stderr(captured):
            losses, gnorms = _run_steps(model, mesh, devices[0], tokens)
        n_remat = captured[0].count("Involuntary full rematerialization")
        assert n_remat == 0, (
            f"mesh={dict(mesh.shape)}: XLA emitted {n_remat} involuntary-"
            f"full-rematerialization warnings — a sharding annotation is "
            f"forcing the partitioner to replicate a tensor (throughput "
            f"cliff on a real pod). Fix the annotation; see captured "
            f"stderr above.")

        # Ground truth: the SAME architecture (incl. MoE) on ONE device,
        # plain attention, no pipelining.
        ref_model = Transformer(_dc.replace(
            cfg, use_ring_attention=False, pipeline_microbatches=0))
        ref_losses, ref_gnorms = _run_steps(
            ref_model, None, devices[0], tokens)

        for i, (l, rl, g, rg) in enumerate(
                zip(losses, ref_losses, gnorms, ref_gnorms)):
            assert jnp.isfinite(l), f"step {i}: non-finite loss {l}"
            assert abs(l - rl) <= 2e-3 * max(1.0, abs(rl)), (
                f"step {i}: sharded loss {l} != single-device {rl} "
                f"(mesh={dict(mesh.shape)})")
            assert abs(g - rg) <= 5e-3 * max(1.0, abs(rg)), (
                f"step {i}: sharded grad-norm {g} != single-device {rg} "
                f"(mesh={dict(mesh.shape)})")
        print(f"dryrun_multichip({n_devices}): mesh={dict(mesh.shape)} "
              f"losses={[round(l, 4) for l in losses]} == single-device "
              f"ground truth OK")
