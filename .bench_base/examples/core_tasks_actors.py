"""Tasks, actors, objects, placement groups in 30 lines."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo-root import without install

import numpy as np

import ray_tpu

ray_tpu.init(num_cpus=4)


@ray_tpu.remote
def square(x):
    return x * x


@ray_tpu.remote
class Counter:
    def __init__(self):
        self.n = 0

    def add(self, k):
        self.n += k
        return self.n


print("tasks:", ray_tpu.get([square.remote(i) for i in range(8)]))

c = Counter.remote()
print("actor:", ray_tpu.get([c.add.remote(i) for i in range(1, 5)]))

big = ray_tpu.put(np.arange(1_000_000))          # shm-backed object
print("object sum:", ray_tpu.get(square.remote(2)),
      int(ray_tpu.get(big).sum()))

from ray_tpu.util.placement_group import placement_group
pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
print("placement group ready:", pg.wait(timeout_seconds=30))

ray_tpu.shutdown()
