"""ASHA lr sweep over trial actors."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo-root import without install

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune.tuner import TuneConfig

ray_tpu.init(num_cpus=4)


def trainable(config):
    from ray_tpu import tune as rt_tune
    x = 1.0
    for step in range(8):
        x *= (1.0 - config["lr"])          # toy objective -> 0
        rt_tune.report({"loss": abs(x), "step": step})


grid = tune.Tuner(
    trainable,
    param_space={"lr": tune.grid_search([0.9, 0.5, 0.1, 0.01])},
    tune_config=TuneConfig(
        metric="loss", mode="min", max_concurrent_trials=2,
        scheduler=tune.ASHAScheduler(metric="loss", mode="min",
                                     max_t=8, grace_period=2)),
).fit()
best = grid.get_best_result()
print("best config:", best.metrics["config"], "loss:",
      best.metrics["loss"])
ray_tpu.shutdown()
