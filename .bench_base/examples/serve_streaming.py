"""Streaming inference + autoscaling with ray_tpu.serve.

A token-streaming deployment (generator __call__) consumed through the
handle and over chunked HTTP, with replica autoscaling under load.
Reference analogue: serve streaming responses (proxy ASGI streaming) +
serve/_private/autoscaling_state.py.

Run: python examples/serve_streaming.py
"""
import json
import time
import urllib.request

import ray_tpu
from ray_tpu import serve


@serve.deployment(
    num_replicas=1,
    autoscaling_config={"min_replicas": 1, "max_replicas": 3,
                        "target_ongoing_requests": 2.0,
                        "upscale_delay_s": 1.0,
                        "downscale_delay_s": 5.0})
class TokenStreamer:
    """Stands in for an LLM decode loop: yields tokens as produced."""

    def __call__(self, prompt: str):
        for i, word in enumerate(str(prompt).split()):
            time.sleep(0.05)          # per-token decode latency
            yield f"[{i}]{word}"


def main():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    handle = serve.run(TokenStreamer.bind(), name="llm")

    print("streaming via handle:")
    for tok in handle.stream("the quick brown fox jumps"):
        print("  ", tok)

    port = serve.start_http(port=0)
    print(f"streaming via HTTP on :{port}:")
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/llm/stream",
        data=json.dumps("lazy dog time").encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        for line in resp.read().splitlines():
            if line:
                print("  ", json.loads(line)["chunk"])

    print("status:", serve.status())
    serve.stop_http()
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
