"""PPO learns CartPole to 450 (the tuned-example learning gate)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo-root import without install

import ray_tpu
from ray_tpu.rllib.algorithms import PPOConfig

ray_tpu.init(num_cpus=4)
algo = PPOConfig().environment("CartPole-v1").build()
for i in range(250):
    m = algo.train()
    r = m.get("episode_return_mean", float("nan"))
    if i % 10 == 0:
        print(f"iter {i:3d} return {r:7.1f}")
    if r == r and r >= 450:
        print(f"solved at iter {i}: {r:.1f}")
        break
algo.stop()
ray_tpu.shutdown()
