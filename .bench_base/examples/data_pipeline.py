"""jsonl corpus -> tokenize -> dp-sharded jax batches."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo-root import without install

import json
import os
import tempfile

import numpy as np

import ray_tpu
from ray_tpu import data as rd

ray_tpu.init(num_cpus=4)

d = tempfile.mkdtemp()
with open(os.path.join(d, "corpus.jsonl"), "w") as f:
    for i in range(256):
        f.write(json.dumps({"doc_id": i, "text": f"document {i}"}) + "\n")


def tokenize(batch):
    return {"tokens": np.stack([np.arange(16) + d_
                                for d_ in batch["doc_id"]]),
            "doc_id": batch["doc_id"]}


ds = rd.read_json(os.path.join(d, "corpus.jsonl"),
                  rows_per_block=32).map_batches(tokenize)
print("dataset:", ds, "rows:", ds.count())

for i, batch in enumerate(ds.iterator().iter_jax_batches(
        batch_size=64, dtypes={"tokens": "int32"})):
    print(f"batch {i}: tokens {batch['tokens'].shape} "
          f"{batch['tokens'].dtype}")

ray_tpu.shutdown()
