"""Compiled-DAG collective nodes: per-actor shards allreduce inside the
DAG (no driver hop), each participant continuing with the reduced value.

Run:  python examples/dag_collective.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo-root import without install

import numpy as np

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode, allreduce_bind


@ray_tpu.remote
class Shard:
    def __init__(self, rank):
        self.rank = rank

    def grad(self, x):
        # pretend per-rank gradient: rank-scaled view of the input
        return np.asarray(x, dtype=np.float64) * (self.rank + 1)

    def apply(self, reduced):
        return float(np.sum(reduced))


def main():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    actors = [Shard.remote(r) for r in range(3)]

    with InputNode() as inp:
        grads = [a.grad.bind(inp) for a in actors]
        reduced = allreduce_bind(grads, op="mean")
        outs = [a.apply.bind(r) for a, r in zip(actors, reduced)]
        dag = MultiOutputNode(outs).experimental_compile()

    try:
        for step in range(3):
            x = np.full(4, step + 1.0)
            sums = ray_tpu.get(dag.execute(x), timeout=120)
            # mean over scales (1,2,3) = 2x -> sum = 2 * 4 * (step+1)
            print(f"step {step}: {sums}")
            assert all(abs(s - 8.0 * (step + 1)) < 1e-9 for s in sums)
    finally:
        dag.teardown()
    ray_tpu.shutdown()
    print("ok")


if __name__ == "__main__":
    main()
