"""Model serving: deployments + handle + HTTP ingress."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo-root import without install

import json
import urllib.request

import ray_tpu
from ray_tpu import serve

ray_tpu.init(num_cpus=4)


@serve.deployment(num_replicas=2)
class Classifier:
    def __call__(self, body):
        text = str(body.get("text", ""))
        return {"label": "long" if len(text) > 10 else "short",
                "length": len(text)}


handle = serve.run(Classifier.bind(), name="clf")
print("handle:", ray_tpu.get(handle.remote({"text": "hello world!"})))

port = serve.start_http(port=0)
req = urllib.request.Request(
    f"http://127.0.0.1:{port}/clf",
    data=json.dumps({"text": "hi"}).encode())
print("http:", json.loads(urllib.request.urlopen(req).read()))
serve.stop_http()
serve.shutdown()
ray_tpu.shutdown()
