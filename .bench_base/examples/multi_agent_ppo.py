"""Multi-agent PPO: two policies learn two independent CartPoles.

The MultiAgentEnv steps all agents per tick with dict payloads;
policy_mapping_fn routes each agent's (env, agent) column to its own
PPO learner. Reference analogue: rllib/env/multi_agent_env_runner.py.

Run: python examples/multi_agent_ppo.py
"""
import numpy as np

from ray_tpu.rllib.env.multi_agent import (MultiAgentEnv,
                                           MultiAgentPPOConfig,
                                           PolicySpec)


class TwoCartPoles(MultiAgentEnv):
    agents = ("left", "right")

    def __init__(self):
        import gymnasium as gym
        self._envs = {a: gym.make("CartPole-v1") for a in self.agents}
        self._done = {a: False for a in self.agents}

    def reset(self, *, seed=None):
        obs = {}
        for i, a in enumerate(self.agents):
            o, _ = self._envs[a].reset(
                seed=None if seed is None else seed + i)
            obs[a] = o
            self._done[a] = False
        return obs, {}

    def step(self, actions):
        obs, rew, term, trunc = {}, {}, {}, {}
        for a in self.agents:
            if self._done[a]:
                obs[a] = np.zeros(4, np.float32)
                rew[a], term[a], trunc[a] = 0.0, True, False
                continue
            o, r, te, tr, _ = self._envs[a].step(int(actions[a]))
            obs[a], rew[a] = o, float(r)
            term[a], trunc[a] = bool(te), bool(tr)
            if te or tr:
                self._done[a] = True
        term["__all__"] = all(self._done.values())
        trunc["__all__"] = False
        return obs, rew, term, trunc, {}

    def close(self):
        for e in self._envs.values():
            e.close()


def main():
    algo = MultiAgentPPOConfig(
        env_fn=TwoCartPoles,
        policies={"pl": PolicySpec(4, 2), "pr": PolicySpec(4, 2)},
        policy_mapping_fn=lambda a: "pl" if a == "left" else "pr",
        num_envs_per_env_runner=16, rollout_length=64, seed=0).build()
    for i in range(40):
        m = algo.train()
        if i % 5 == 0:
            print(f"iter {i:3d} "
                  f"left={m.get('episode_return_mean/policy/pl'):.1f} "
                  f"right={m.get('episode_return_mean/policy/pr'):.1f}")
    algo.stop()


if __name__ == "__main__":
    main()
