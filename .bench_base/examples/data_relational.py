"""Relational ray_tpu.data: groupby/aggregate, sort, actor-pool maps.

A task-based hash/range exchange powers the all-to-all ops; stateful
preprocessing runs on a pool of long-lived actors. Reference analogue:
data/grouped_data.py + actor_pool_map_operator.py.

Run: python examples/data_relational.py
"""
import numpy as np

import ray_tpu
from ray_tpu import data as rd


class Standardizer:
    """Stateful transform: fit-once, reused across partitions."""

    def __init__(self, mean, std):
        self.mean, self.std = mean, std

    def __call__(self, batch):
        return {"k": batch["k"],
                "z": (batch["v"] - self.mean) / self.std}


def main():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    rng = np.random.default_rng(0)
    ds = rd.from_numpy({"k": rng.integers(0, 5, 1000),
                        "v": rng.normal(10.0, 2.0, 1000)},
                       override_num_blocks=8)

    stats = ds.groupby("k").aggregate(
        rd.Count(), rd.Mean("v"), rd.Std("v"))
    print("per-key stats:")
    for row in stats.sort("k").take_all():
        print(f"  k={int(row['k'])} n={int(row['count()'])} "
              f"mean={row['mean(v)']:.2f} std={row['std(v)']:.2f}")

    mu, sd = ds.mean("v"), ds.std("v")
    z = ds.map_batches(Standardizer, fn_constructor_args=(mu, sd),
                       compute=rd.ActorPoolStrategy(size=2))
    print("standardized mean ~0:", round(z.mean("z"), 4),
          "std ~1:", round(z.std("z"), 4))

    top = ds.sort("v", descending=True).take(3)
    print("top-3 v:", [round(r["v"], 2) for r in top])
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
