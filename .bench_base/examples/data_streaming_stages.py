"""Per-operator streaming execution: a slow, resource-heavy stage gets
its own actor pool and backpressure, so the fast reader can't flood it.

Run:  python examples/data_streaming_stages.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo-root import without install

import time

import numpy as np

import ray_tpu
import ray_tpu.data as rd


class Embedder:
    """Stateful transform: 'loads a model' once per pool worker."""

    def __init__(self, dim):
        time.sleep(0.2)                        # pretend model load
        rng = np.random.default_rng(0)
        self.w = rng.standard_normal((1, dim))

    def __call__(self, batch):
        x = np.asarray(batch["id"], dtype=np.float64)[:, None]
        return {"id": batch["id"], "emb": x @ self.w}


def main():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)

    ds = (rd.range(4000, override_num_blocks=16)
          .map_batches(lambda b: {"id": b["id"] * 2})   # fuses into read
          .map_batches(Embedder, fn_constructor_args=(8,),
                       compute=rd.ActorPoolStrategy(2),
                       num_cpus=1, concurrency=2))      # own stage

    n_rows = sum(len(b["id"]) for b in ds.iter_blocks())
    print(f"rows: {n_rows}")
    print(ds.stats())                   # per-stage tasks / task-s / blocks
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
