"""2-worker JaxTrainer: tiny-transformer SFT with checkpoints."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo-root import without install

import ray_tpu
from ray_tpu.train import (CheckpointConfig, JaxConfig, JaxTrainer,
                           RunConfig, ScalingConfig)

ray_tpu.init(num_cpus=4)


def train_loop(config):
    import jax
    import numpy as np
    import optax

    from ray_tpu import train as rt_train
    from ray_tpu.models import Transformer
    from ray_tpu.models.config import tiny
    from ray_tpu.train import Checkpoint
    from ray_tpu.train.session import make_temp_checkpoint_dir

    cfg = tiny()
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = optax.adamw(3e-3)
    opt_state = opt.init(params)
    tokens = np.asarray(jax.random.randint(
        jax.random.PRNGKey(rt_train.get_context().get_world_rank()),
        (4, 32), 0, cfg.vocab_size))

    @jax.jit
    def step(p, s):
        loss, g = jax.value_and_grad(model.loss)(p, {"tokens": tokens})
        up, s = opt.update(g, s, p)
        return optax.apply_updates(p, up), s, loss

    for i in range(config["steps"]):
        params, opt_state, loss = step(params, opt_state)
        ckpt = None
        if i == config["steps"] - 1:
            d = make_temp_checkpoint_dir()
            ckpt = Checkpoint.from_state(d, {"params": params})
        rt_train.report({"loss": float(loss), "step": i}, ckpt)


result = JaxTrainer(
    train_loop,
    train_loop_config={"steps": 5},
    scaling_config=ScalingConfig(num_workers=2),
    run_config=RunConfig(name="sft_example",
                         checkpoint_config=CheckpointConfig(num_to_keep=1)),
    backend_config=JaxConfig(distributed=False),
).fit()
print("final:", result.metrics, "checkpoint:", result.checkpoint)
ray_tpu.shutdown()
