"""Serve model composition: an ingress fanning out to sub-deployments.

A bound deployment graph — `Ingress.bind(Preprocessor.bind(),
ModelA.bind(), ModelB.bind())` — deploys every node and injects live
DeploymentHandles into the ingress replica at init (reference deployment
graphs: python/ray/serve/_private/deployment_state.py:1245 handle
injection + serve/handle.py handle-passing). Each sub-deployment scales
and recovers independently; handles learn membership changes via the
long-poll push channel (reference serve/_private/long_poll.py).

Run: python examples/serve_composition.py
"""
import ray_tpu
from ray_tpu import serve


@serve.deployment(num_replicas=1)
class Preprocessor:
    def __call__(self, text: str) -> list:
        return [t.lower() for t in text.split()]


@serve.deployment(num_replicas=2)
class SentimentModel:
    POSITIVE = {"good", "great", "love", "fast"}

    def __call__(self, tokens: list) -> float:
        if not tokens:
            return 0.0
        return sum(t in self.POSITIVE for t in tokens) / len(tokens)


@serve.deployment(num_replicas=2)
class LengthModel:
    def __call__(self, tokens: list) -> int:
        return len(tokens)


@serve.deployment(num_replicas=1)
class Ingress:
    """Receives handles to the three sub-deployments at init."""

    def __init__(self, pre, sentiment, length):
        self.pre = pre
        self.sentiment = sentiment
        self.length = length

    def __call__(self, text: str) -> dict:
        tokens = ray_tpu.get(self.pre.remote(text), timeout=60)
        s_ref = self.sentiment.remote(tokens)    # fan out in parallel
        l_ref = self.length.remote(tokens)
        return {"sentiment": ray_tpu.get(s_ref, timeout=60),
                "tokens": ray_tpu.get(l_ref, timeout=60)}


def main():
    ray_tpu.init(num_cpus=8)
    app = Ingress.bind(Preprocessor.bind(), SentimentModel.bind(),
                       LengthModel.bind())
    handle = serve.run(app)
    for text in ("TPUs are fast and I love them",
                 "this is terrible"):
        out = ray_tpu.get(handle.remote(text), timeout=120)
        print(f"{text!r} -> {out}")
    print("deployments:", sorted(serve.status()))
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
