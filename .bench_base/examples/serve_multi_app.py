"""Multi-application serving: independent apps under one controller.

Run:  python examples/serve_multi_app.py

Two applications — a composed greeting pipeline and a standalone
shouter — deploy with their own route prefixes; HTTP traffic routes by
longest prefix; deleting one app leaves the other serving.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo-root import without install

import json
import urllib.request

import ray_tpu
from ray_tpu import serve


@serve.deployment(num_replicas=1)
class Upper:
    def __call__(self, x):
        return str(x).upper()


@serve.deployment(num_replicas=2)
class Greeter:
    def __init__(self, style, shouter):
        self.style = style
        self.shouter = shouter          # live handle to Upper

    def __call__(self, name):
        loud = ray_tpu.get(self.shouter.remote(name), timeout=30)
        return f"{self.style}, {loud}!"


def main():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)

    serve.run(Greeter.bind("Hello", Upper.bind()), name="greet",
              route_prefix="/api/greet")
    # run(name=...) names the app AND its ingress deployment
    serve.run(Upper.bind(), name="shout")

    print("applications:", json.dumps(serve.status_applications(),
                                      indent=1, default=str))

    port = serve.start_http(port=0)
    for path, body in [("/api/greet", "ada"), ("/shout", "quiet")]:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            print(path, "->", json.loads(resp.read())["result"])

    serve.delete("greet")               # whole app graph goes away
    print("after delete:", sorted(serve.status()))
    # the OTHER app keeps serving — the docstring's central claim
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/shout",
        data=json.dumps("still here").encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        survivor = json.loads(resp.read())["result"]
    print("/shout after delete ->", survivor)
    assert survivor == "STILL HERE"
    serve.stop_http()
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
