"""Population-based training + distributed trials with ray_tpu.tune.

Four concurrent trials optimize a synthetic curve; PBT exploits the
top quantile (checkpoint inheritance + mutated lr). Also shows a
2-worker JaxTrainer as a distributed trial under ASHA.
Reference analogue: tune/schedulers/pbt.py + trial placement groups.

Run: python examples/tune_pbt.py
"""
import tempfile
import time

import ray_tpu
from ray_tpu import tune
from ray_tpu.train import Checkpoint, JaxTrainer, RunConfig, ScalingConfig
from ray_tpu.train.session import make_temp_checkpoint_dir


def pbt_trainable(config):
    from ray_tpu import tune as rt
    start, ckpt = 0, rt.get_checkpoint()
    if ckpt is not None:
        start = int(ckpt.load_state()["step"])
    for step in range(start, 10):
        time.sleep(0.3)                  # let the population overlap
        d = make_temp_checkpoint_dir()
        c = Checkpoint.from_state(d, {"step": step + 1})
        rt.report({"score": float(config["lr"]), "step": step}, c)


def main():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    tmp = tempfile.mkdtemp()

    sched = tune.PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=2,
        hyperparam_mutations={"lr": tune.uniform(0.0, 1.0)}, seed=0)
    grid = tune.Tuner(
        pbt_trainable,
        param_space={"lr": tune.grid_search([0.05, 0.1, 0.6, 0.9])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    max_concurrent_trials=4,
                                    scheduler=sched),
        run_config=RunConfig(name="pbt_demo", storage_path=tmp),
    ).fit()
    print("PBT exploits:", sched.num_exploits)
    for t in grid.trials:
        print(f"  {t.trial_id} lr={t.config['lr']:.3f} "
              f"perturbations={t.num_perturbations}")

    # --- distributed trials: each trial is a 2-worker group
    def loop(config):
        from ray_tpu import train as rt
        ctx = rt.get_context()
        for step in range(4):
            rt.report({"loss": 1.0 / (1 + step * config["lr"]),
                       "world": ctx.get_world_size()})

    trainer = JaxTrainer(loop, train_loop_config={"lr": 0.0},
                         scaling_config=ScalingConfig(num_workers=2))
    grid2 = tune.Tuner(
        trainer,
        param_space={"lr": tune.grid_search([0.01, 5.0])},
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    max_concurrent_trials=1),
        run_config=RunConfig(name="dist_demo", storage_path=tmp),
    ).fit()
    best = grid2.get_best_result()
    print("distributed-trial best:", best.metrics["config"],
          "world_size:", best.metrics["world"])
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
