"""Sweep bench.py-shaped configs on the real chip (one per process).

Usage: python tools/bench_sweep.py <block_q> <block_k> <remat_policy> \
           [batch] [loss_chunk]     (remat_policy "none" = remat off)
Prints one result line; run via the loop in the repo makefile or by hand.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from bench import PEAK_FLOPS, _detect_peak  # noqa: E402


def main():
    import optax

    from ray_tpu.models import Transformer, TransformerConfig

    bq, bk = int(sys.argv[1]), int(sys.argv[2])
    policy = sys.argv[3]
    batch = int(sys.argv[4]) if len(sys.argv) > 4 else 8
    loss_chunk = int(sys.argv[5]) if len(sys.argv) > 5 else 512
    seq, steps = 2048, 10

    cfg = TransformerConfig(
        vocab_size=32000, d_model=2048, n_layers=16, n_heads=16,
        n_kv_heads=16, d_ff=5632, max_seq_len=2048,
        remat=policy != "none",
        remat_policy=policy if policy != "none" else "full",
        dtype="bfloat16", param_dtype="bfloat16",
        loss_chunk=loss_chunk, attn_block_q=bq, attn_block_k=bk)

    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = optax.adamw(1e-4)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                cfg.vocab_size)

    def _step(p, s, batch_):
        loss, g = jax.value_and_grad(model.loss)(p, batch_)
        updates, s = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s, loss

    train_step = jax.jit(_step, donate_argnums=(0, 1))
    params, opt_state, loss = train_step(params, opt_state,
                                         {"tokens": tokens})
    float(loss)
    params, opt_state, loss = train_step(params, opt_state,
                                         {"tokens": tokens})
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = train_step(params, opt_state,
                                             {"tokens": tokens})
    float(loss)
    dt = time.perf_counter() - t0
    tok_per_s = batch * seq * steps / dt
    mfu = tok_per_s * cfg.flops_per_token() / _detect_peak()
    print(json.dumps({
        "bq": bq, "bk": bk, "policy": policy, "batch": batch,
        "loss_chunk": loss_chunk,
        "tok_s": round(tok_per_s, 1), "mfu": round(mfu, 4),
        "step_ms": round(dt / steps * 1e3, 1)}))


if __name__ == "__main__":
    main()
